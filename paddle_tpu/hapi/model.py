"""Keras-like high-level Model API.

Reference capability: `hapi.Model` (reference: python/paddle/hapi/
model.py:1052 — prepare/fit/evaluate/predict/save/load over a dygraph or
static network, with callbacks and metrics).

TPU-native realization: the train step is the eager framework step (jit
compilation comes from `paddle.jit.to_static` on the step when
`prepare(..., jit=True)`), input pipeline is io.DataLoader.
"""
from __future__ import annotations

import os

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader
from ..metric import Metric
from ..utils import fault_injection as _fault_injection
from .callbacks import config_callbacks


def _batch_counts(x):
    """(examples, tokens) for throughput accounting: examples = leading
    dim; tokens = element count when the input is integer-typed (token
    ids), else None (dense inputs have no token notion)."""
    try:
        data = x._data_ if hasattr(x, "_data_") else x
        shape = tuple(getattr(data, "shape", ()) or ())
        if not shape:
            return 0, None
        examples = int(shape[0])
        kind = getattr(getattr(data, "dtype", None), "kind", None)
        if kind is None:
            kind = np.asarray(data).dtype.kind
        tokens = int(np.prod(shape)) if kind in ("i", "u") else None
        return examples, tokens
    except Exception:
        return 0, None


class Model:
    """reference: hapi/model.py:1052."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False
        self._amp_level = "O0"
        self._amp_dtype = "bfloat16"
        self._amp_lists = (None, None)
        self._scaler = None
        self._nranks = 1
        self._rank = 0
        # training sentinel (framework/sentinel.py): installed by fit
        # when FLAGS_sentinel is on; None costs one attr read per step
        self._sentinel = None
        # global iteration fed to the sentinel fault-injection seams
        # (bad_batch / loss_spike / grad_bitflip); set by fit per step
        self._fi_step = None
        # the active data.Pipeline train loader (set by fit): its
        # position state rides ModelCheckpoint/sentinel snapshots
        self._data_pipeline = None

    # ---- configuration ----
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit=False):
        self._loss = loss
        metrics = metrics or []
        if isinstance(metrics, Metric):
            metrics = [metrics]
        self._metrics = metrics

        # AMP-aware prepare (reference: hapi/model.py _check_amp_configs
        # — accepts "O1"/"O2" or a dict of auto_cast + GradScaler knobs)
        scaler_kw = {}
        if amp_configs:
            if isinstance(amp_configs, str):
                self._amp_level = amp_configs
            else:
                cfg = dict(amp_configs)
                self._amp_level = cfg.pop("level", "O1")
                self._amp_dtype = cfg.pop("dtype", "bfloat16")
                self._amp_lists = (cfg.pop("custom_white_list", None),
                                   cfg.pop("custom_black_list", None))
                scaler_kw = cfg
            if self._amp_level not in ("O0", "O1", "O2"):
                raise ValueError(
                    f"amp level must be O0/O1/O2, got {self._amp_level!r}")
        from .. import amp as amp_pkg
        if self._amp_level == "O2" and optimizer is not None:
            # cast params to the amp dtype; optimizer keeps f32 masters
            self.network, optimizer = amp_pkg.decorate(
                self.network, optimizer, level="O2",
                dtype=self._amp_dtype)
        if self._amp_level != "O0" and (
                self._amp_dtype in ("float16", "fp16") or scaler_kw):
            # bf16 needs no loss scaling — the scaler only materializes
            # for fp16 or when scaling knobs are passed explicitly
            self._scaler = amp_pkg.GradScaler(**scaler_kw)

        # distributed-aware prepare (reference: DynamicGraphAdapter wraps
        # in DataParallel when nranks>1; here each launched worker holds
        # its data shard and grads all-reduce across processes)
        from ..distributed import env as dist_env
        self._nranks = dist_env.get_world_size()
        self._rank = dist_env.get_rank()

        self._optimizer = optimizer
        self._jit = jit
        self._train_fn = self._train_step
        if jit:
            from ..jit import to_static
            self._train_fn = to_static(self._train_step)
        # compiled train step (framework/train_step.py): built lazily at
        # the first train batch; None = not yet decided, False = ruled out
        self._compiled_step = None
        self._accum_steps = 1
        return self

    # ---- steps ----
    def _compute_loss(self, outputs, labels):
        if self._loss is None:
            raise RuntimeError("prepare(loss=...) before fit/evaluate")
        return self._loss(outputs, labels)

    def _autocast(self):
        from .. import amp as amp_pkg
        return amp_pkg.auto_cast(enable=self._amp_level != "O0",
                                 level=self._amp_level,
                                 dtype=self._amp_dtype,
                                 custom_white_list=self._amp_lists[0],
                                 custom_black_list=self._amp_lists[1])

    def _sync_grads(self, with_found_inf=False):
        """Cross-process DP gradient all-reduce (mean) — the EagerReducer
        analog for the launched-workers path.

        ``with_found_inf`` batches the AMP global inf/nan decision into
        the same reduction pass: the scaler's DEVICE-side flag (computed
        without a host read by ``unscale_(defer_found_inf=True)``) rides
        one extra scalar all_reduce, and the single device→host sync
        happens on the already-reduced scalar — a global decision with no
        per-rank host round-trip.  (A rank skipping the step while
        another applies the possibly inf-contaminated update would
        diverge the replicas.)"""
        from .. import distributed as dist
        for p in self._optimizer._all_params():
            if p.grad is not None:
                dist.all_reduce(p.grad)
                p.grad._data = p.grad._data / self._nranks
        if with_found_inf:
            flag = self._scaler._found_inf_tensor()
            dist.all_reduce(flag)
            self._scaler._found_inf = bool(
                float(np.asarray(flag._data_)[0]) > 0)

    def _forward_loss(self, x, y):
        """Forward + loss under autocast — the only user code the
        compiled train step replays inside its XLA program."""
        with self._autocast():
            out = self.network(x)
            return self._compute_loss(out, y)

    def _train_step(self, x, y, update=True):
        with self._autocast():
            out = self.network(x)
            loss = self._compute_loss(out, y)
        if self._fi_step is not None:
            loss = _fault_injection.spike_loss(loss, self._fi_step)
        bwd = loss
        if self._scaler is not None:
            bwd = self._scaler.scale(bwd)
        if self._accum_steps > 1:
            # scale each micro-batch so the accumulated gradient is the
            # MEAN over the window (matching one big-batch step)
            bwd = bwd * (1.0 / self._accum_steps)
        bwd.backward()
        if self._fi_step is not None:
            _fault_injection.corrupt_grads(self._optimizer, self._fi_step)
        if not update:
            return loss, out     # micro-step: gradients accumulate
        if self._sentinel is not None:
            # LOCAL (pre-all-reduce) grad health, kept on device: the
            # per-rank signal blame attribution needs, computed before
            # a dp reduction smears a flaky host's Inf across the world
            found = self._sentinel.note_eager(self._optimizer)
            if (found is not None and self._scaler is not None
                    and self._scaler._scale == 1.0
                    and self._scaler._always_check):
                # the unit-scale sentinel wrapper reuses this fused
                # flag instead of re-reducing every gradient
                self._scaler._planted_found_inf = found
        if self._scaler is not None:
            if self._nranks > 1:
                self._scaler.unscale_(self._optimizer,
                                      defer_found_inf=True)
                self._sync_grads(with_found_inf=True)
            self._scaler.step(self._optimizer)  # step() runs update()
            if self._sentinel is not None:
                self._sentinel.note_eager_skip(self._scaler._found_inf)
        else:
            if self._nranks > 1:
                self._sync_grads()
            self._optimizer.step()
        self._optimizer.clear_grad()
        return loss, out

    def _ensure_compiled_step(self):
        """The CompiledTrainStep for this model, or None for the eager
        lane.  None stays undecided while the flag is off (it may flip
        on); False latches structural ineligibility."""
        if self._compiled_step is False:
            return None
        if self._compiled_step is not None:
            if self._compiled_step._sentinel != (self._sentinel
                                                 is not None):
                self._compiled_step = None   # rebuild with/without the
            else:                            # health-vector output
                return self._compiled_step
        from ..utils.flags import flag as _flag
        if not _flag("FLAGS_compiled_train_step", True):
            return None
        if (self._jit or self._loss is None or self._optimizer is None
                or type(self).train_batch is not Model.train_batch
                or type(self)._train_step is not Model._train_step
                or type(self)._forward_loss is not Model._forward_loss):
            self._compiled_step = False
            return None
        from ..framework.train_step import CompiledTrainStep
        cs = CompiledTrainStep(
            self._forward_loss, self._optimizer, scaler=self._scaler,
            network=self.network,
            accumulate_grad_batches=self._accum_steps,
            sentinel=self._sentinel is not None,
            eager_step=lambda x, y, update:
                self._train_step(x, y, update)[0])
        if cs.fallback_reason is not None:
            self._compiled_step = False   # structurally eager: skip wrap
            return None
        self._compiled_step = cs
        return cs

    def _train_batch_device(self, inputs, labels=None, update=True):
        """One train step returning the loss ON DEVICE (no host sync):
        fit materializes it only at log_freq boundaries."""
        self.network.train()
        x = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
        y = labels[0] if isinstance(labels, (list, tuple)) else labels
        cs = self._ensure_compiled_step()
        if cs is not None:
            return cs(x, y, update=update)
        if self._jit:
            # the to_static wrapper traces (x, y) only — it must not see
            # the python `update` flag as a traced arg, and a traced
            # full-step program may not honor grads accumulated outside
            # it, so micro-steps (and their closing update) run eagerly
            if update and self._accum_steps <= 1:
                loss, _ = self._train_fn(x, y)
            else:
                loss, _ = self._train_step(x, y, update)
        else:
            loss, _ = self._train_fn(x, y, update)
        return loss

    def train_batch(self, inputs, labels=None, update=True):
        loss = self._train_batch_device(inputs, labels, update)
        return [float(np.asarray(loss._data_))]

    def eval_batch(self, inputs, labels=None):
        from ..core.state import no_grad
        self.network.eval()
        x = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
        y = labels[0] if isinstance(labels, (list, tuple)) else labels
        with no_grad(), self._autocast():
            out = self.network(x)
            loss = self._compute_loss(out, y)
        return [float(np.asarray(loss._data_))], out

    def predict_batch(self, inputs):
        from ..core.state import no_grad
        self.network.eval()
        x = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
        with no_grad(), self._autocast():
            return self.network(x)

    # ---- loops ----
    def _as_loader(self, data, batch_size, shuffle):
        if data is None or isinstance(data, DataLoader):
            return data
        from ..data import Pipeline as _DataPipeline
        if isinstance(data, _DataPipeline):
            # a paddle_tpu.data pipeline carries its own shard/shuffle/
            # batch stages and a checkpointable position — use as-is
            return data
        if self._nranks > 1:
            # each launched worker reads only its shard (reference:
            # hapi fit builds a DistributedBatchSampler when nranks>1)
            from ..io import DistributedBatchSampler
            sampler = DistributedBatchSampler(
                data, batch_size=batch_size, num_replicas=self._nranks,
                rank=self._rank, shuffle=shuffle)
            return DataLoader(data, batch_sampler=sampler)
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None,
            resume=None, max_to_keep=None):
        """``resume=True`` (with ``save_dir``) or ``resume=<dir>`` restores
        model + optimizer + epoch from the latest VALID checkpoint written
        by ModelCheckpoint and continues from there; torn checkpoints are
        skipped transparently.  While checkpointing is active a SIGTERM
        (preemption notice) triggers a save at the next step boundary and
        exit(ELASTIC_EXIT_CODE) so the launch controller relaunches into
        auto-resume (docs/FAULT_TOLERANCE.md)."""
        from .callbacks import ModelCheckpoint
        from ..data import Pipeline as _DataPipeline
        loader = self._as_loader(train_data, batch_size, shuffle)
        eval_loader = self._as_loader(eval_data, batch_size, False)
        # a checkpointable pipeline rides every checkpoint this fit
        # writes (ModelCheckpoint._state) and is rewound by resume /
        # sentinel rollback instead of being fast-forwarded O(steps)
        self._data_pipeline = (loader
                               if isinstance(loader, _DataPipeline)
                               else None)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        accumulate_grad_batches = max(int(accumulate_grad_batches or 1), 1)
        if accumulate_grad_batches != self._accum_steps:
            self._accum_steps = accumulate_grad_batches
            self._compiled_step = None   # rebuild for the new window
        cbs = config_callbacks(callbacks, self, epochs=epochs, steps=steps,
                               verbose=verbose, save_freq=save_freq,
                               save_dir=save_dir,
                               metrics=[m.name() for m in self._metrics],
                               max_to_keep=max_to_keep, log_freq=log_freq)
        ckpt_cb = next((c for c in cbs.callbacks
                        if isinstance(c, ModelCheckpoint)), None)

        initial_epoch = 0
        if resume:
            initial_epoch = self._resume_from(resume, save_dir, ckpt_cb)

        handler = None
        if ckpt_cb is not None and ckpt_cb.save_dir:
            from ..distributed.fleet.elastic import PreemptionHandler
            handler = PreemptionHandler().install()

        # training sentinel (framework/sentinel.py, docs/RESILIENCE.md):
        # anomaly detection over the device-resident loss/grad stream,
        # last-known-good anchor rollback with the offending batch
        # window quarantined on replay, per-rank blame in multi-process
        # worlds.  Off (default): self._sentinel stays None and every
        # seam below is a single attr read.
        sentinel = self._install_sentinel(ckpt_cb)

        # hot-spare recovery (framework/hot_spare.py,
        # docs/FAULT_TOLERANCE.md "Recovery ladder"): periodic host-RAM
        # snapshots streamed to the ring-buddy rank, parked into the
        # guardian store on cooperative exits, so a relaunch restores
        # from peer memory before touching disk.  Off (default): None,
        # and every seam below is a single attr read.
        hot_spare_agent = self._install_hot_spare(ckpt_cb)

        # unified telemetry (docs/OBSERVABILITY.md): step-time histogram,
        # examples/tokens-per-sec, MFU, memory watermarks — published into
        # the metrics registry; exporter thread only if the flag names a
        # path.  FLOPs are measured ONCE from the first batch (one extra
        # eager forward) so MFU works for any network without a formula.
        from ..observability import StepMetrics, maybe_start_exporter
        maybe_start_exporter()
        self.step_metrics = StepMetrics(prefix="train.")
        if self._data_pipeline is not None:
            self.step_metrics.attach_data(self._data_pipeline.goodput)
        flops_pending = True

        self.stop_training = False
        cbs.call("on_train_begin")
        history = {"loss": []}
        it = 0
        logs = {}
        if sentinel is not None:
            sentinel.begin(it=0, epoch=initial_epoch)
        try:
            epoch = initial_epoch
            # post-rollback replay: redo the anchor's epoch, consuming
            # (but not training on) the batches before the anchor point
            # — the deterministic loader order maps global iteration ->
            # batch stably across replays
            replay_epoch, replay_from = None, -1
            while epoch < epochs:
                cbs.call("on_epoch_begin", epoch)
                sampler = getattr(loader, "batch_sampler", None)
                if sampler is not None and hasattr(sampler, "set_epoch"):
                    # epoch-folded reshuffle key: multi-epoch fit must
                    # not replay one fixed order, and a RESUMED fit must
                    # shuffle epoch N the way the uninterrupted run did
                    sampler.set_epoch(epoch)
                for m in self._metrics:
                    m.reset()
                logs = {}
                loss_t = None
                rollback = None
                for step, batch in enumerate(loader):
                    if replay_epoch == epoch and step < replay_from:
                        continue       # fast-forward to the anchor point
                    x, y = self._split_batch(batch)
                    if sentinel is not None and sentinel.quarantined(it):
                        it += 1        # poisoned batch window: skipped
                        continue       # on replay, never refed
                    if _fault_injection.active("bad_batch") is not None:
                        x = _fault_injection.corrupt_batch(x, it)
                    self._fi_step = it
                    cbs.call("on_train_batch_begin", step)
                    if flops_pending:
                        flops_pending = False
                        self._measure_step_flops(x)
                    examples, tokens = _batch_counts(x)
                    update = (accumulate_grad_batches <= 1
                              or (it + 1) % accumulate_grad_batches == 0)
                    self.step_metrics.begin_step()
                    loss_t = self._train_batch_device(x, y, update=update)
                    self.step_metrics.end_step(examples, tokens)
                    # the loss stays ON DEVICE between log points — the
                    # old per-step float() fetch was a full host sync
                    # stalling the dispatch pipeline every step
                    if step % log_freq == 0 or self._metrics:
                        logs["loss"] = float(np.asarray(loss_t._data_))
                    for m in self._metrics:
                        out = self.predict_batch(x)
                        m.update(*m.compute(out, y))
                        logs[m.name()] = m.accumulate()
                    cbs.call("on_train_batch_end", step, logs)
                    if handler is not None and handler.preempted():
                        # save at the step boundary, then request relaunch
                        # — with a plain loader the restarted process
                        # redoes this epoch from its start with the
                        # mid-epoch weights; a data.Pipeline checkpoints
                        # its position and resumes mid-epoch exactly
                        self._sync_compiled_state()
                        ckpt_cb.save_now(next_epoch=epoch)
                        ckpt_cb.manager.wait()
                        if hot_spare_agent is not None:
                            # RAM dies with the relaunch: park every
                            # held snapshot into the guardian store
                            hot_spare_agent.park()
                        handler.uninstall()
                        handler.exit_for_relaunch()
                    if sentinel is not None:
                        rollback = sentinel.after_step(it, epoch, step,
                                                       loss_t, update)
                    it += 1
                    if rollback is not None:
                        break
                    if hot_spare_agent is not None:
                        # book says "resume at iteration `it`": the
                        # step just completed is already inside the
                        # snapshot, so a peer restore loses nothing
                        hot_spare_agent.maybe_snapshot(
                            it, self._sentinel_snapshot,
                            {"it": it, "epoch": epoch,
                             "next_step": step + 1, "next_epoch": epoch})
                    if num_iters and it >= num_iters:
                        break
                if rollback is None and sentinel is not None:
                    rollback = sentinel.flush()
                if rollback is not None:
                    it = rollback.it
                    epoch = rollback.epoch
                    replay_epoch = rollback.epoch
                    # a checkpointable pipeline was rewound onto the
                    # anchor position by _sentinel_restore — there is
                    # nothing to fast-forward past
                    replay_from = (0 if self._data_pipeline is not None
                                   else rollback.next_step)
                    continue           # redo from the anchor point
                replay_epoch, replay_from = None, -1
                if loss_t is not None:
                    logs["loss"] = float(np.asarray(loss_t._data_))
                self._sync_compiled_state()
                history["loss"].append(logs.get("loss"))
                if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                    eval_logs = self.evaluate(eval_loader, verbose=0,
                                              _callbacks=cbs)
                    logs.update({f"eval_{k}": v
                                 for k, v in eval_logs.items()})
                cbs.call("on_epoch_end", epoch, logs)
                epoch += 1
                if self.stop_training or (num_iters and it >= num_iters):
                    break
        finally:
            if handler is not None:
                handler.uninstall()
            if hot_spare_agent is not None:
                hot_spare_agent.close(park=True)
            self._sentinel = None
            self._fi_step = None
        cbs.call("on_train_end", logs)
        return history

    def _sync_compiled_state(self):
        """Materialize device-held compiled-step state (loss-scaler
        scale/good/bad counters) back into the python objects before a
        checkpoint save or epoch boundary reads them."""
        cs = self._compiled_step
        if cs is not None and cs is not False:
            cs.sync_scaler()

    # ---- training sentinel (framework/sentinel.py) ----
    def _install_sentinel(self, ckpt_cb):
        """Build the fit-scoped TrainingSentinel when FLAGS_sentinel is
        on (returns None otherwise).  Non-AMP runs get a unit-scale
        GradScaler with ``always_check_found_inf`` so the existing AMP
        found-inf machinery skips non-finite steps for them too — the
        in-program response the compiled lane applies as a select, with
        no host sync."""
        from ..framework.sentinel import sentinel_enabled
        jit = getattr(self, "_jit", False)
        if not sentinel_enabled() or jit:
            if jit and sentinel_enabled():
                import warnings
                warnings.warn("FLAGS_sentinel is ignored under "
                              "prepare(jit=True): the to_static step "
                              "cannot host the sentinel's seams")
            if getattr(self._scaler, "_sentinel_wrapper", False):
                self._scaler = None     # sentinel turned off since the
            self._sentinel = None       # last fit installed its wrapper
            return None
        from ..framework.sentinel import TrainingSentinel
        from .. import amp as amp_pkg
        if self._scaler is None:
            self._scaler = amp_pkg.GradScaler(
                enable=True, init_loss_scaling=1.0,
                use_dynamic_loss_scaling=False,
                always_check_found_inf=True)
            self._scaler._sentinel_wrapper = True
        manager = None
        if ckpt_cb is not None and ckpt_cb.save_dir and self._nranks == 1:
            from ..framework.checkpoint_manager import CheckpointManager
            if isinstance(ckpt_cb.manager, CheckpointManager):
                manager = ckpt_cb.manager
        self._sentinel = TrainingSentinel(
            self, manager=manager, nranks=self._nranks, rank=self._rank)
        return self._sentinel

    def _install_hot_spare(self, ckpt_cb):
        """Arm the fit-scoped hot-spare agent when FLAGS_hot_spare is
        on (returns None otherwise).  Snapshot capture reuses
        :meth:`_sentinel_snapshot` — the peer replica carries exactly
        the state a sentinel anchor does (params, optimizer moments,
        GradScaler vec, RNG counter, data-pipeline position)."""
        from ..utils.flags import flag
        if not flag("FLAGS_hot_spare", False):
            return None
        from ..framework import hot_spare
        return hot_spare.arm(rank=self._rank, world=self._nranks)

    def _sentinel_snapshot(self):
        """Host-copied model/optimizer/scaler state for the sentinel's
        last-known-good anchor (device buffers may be donated in place
        by the compiled step right after this returns)."""
        self._sync_compiled_state()

        def host(sd):
            return {k: (np.asarray(v._data_) if hasattr(v, "_data_")
                        else v)
                    for k, v in sd.items()}

        from ..core import state as _cstate
        state = {"model": host(self.network.state_dict()),
                 "rng_counter": int(_cstate.STATE.rng_counter)}
        if self._optimizer is not None:
            state["optimizer"] = host(self._optimizer.state_dict())
        if self._scaler is not None:
            state["scaler"] = dict(self._scaler.state_dict())
        pipe = getattr(self, "_data_pipeline", None)
        if pipe is not None:
            state["data_pipeline"] = pipe.state_dict()
        return state

    def _sentinel_restore(self, state):
        """Roll the live model back onto an anchor snapshot."""
        cs = self._compiled_step
        if cs is not None and cs is not False:
            cs._scaler_vec = None       # re-seed device scaler state
            cs.last_health = None       # from the restored host values
        self.network.set_state_dict(state["model"])
        if self._optimizer is not None and state.get("optimizer"):
            opt_state = {k: (Tensor(v) if isinstance(v, np.ndarray)
                             else v)
                         for k, v in state["optimizer"].items()}
            self._optimizer.set_state_dict(opt_state)
        if self._scaler is not None and state.get("scaler"):
            self._scaler.load_state_dict(dict(state["scaler"]))
            self._scaler._found_inf = False
            self._scaler._unscaled = False
        if "rng_counter" in state:
            from ..core import state as _cstate
            _cstate.STATE.rng_counter = int(state["rng_counter"])
        pipe = getattr(self, "_data_pipeline", None)
        if pipe is not None and state.get("data_pipeline"):
            pipe.load_state_dict(state["data_pipeline"])

    def _measure_step_flops(self, x):
        """Analytic FLOPs of one train step via the dispatch-funnel
        counter (ops/flops.py) — one extra eager forward, once per fit;
        feeds the train.mfu gauge.  Never fatal: a network the counter
        cannot run eagerly just reports no MFU."""
        try:
            from ..core.state import no_grad
            from ..ops.flops import FlopsCounter
            with no_grad(), FlopsCounter() as fc:
                self.network(x)
            if fc.forward_flops:
                self.step_metrics.set_flops_per_step(fc.train_step_flops)
        except Exception:
            pass

    def _checkpoint_mesh_spec(self):
        """The rank factorization sharded checkpoints use for BOTH save
        and resume.  When a hybrid ``ProcessMesh`` is active (the
        auto-layout planner's ``plan.build_mesh()``, or an operator's
        ``with mesh:`` scope) its >1 axes ARE the factorization — the
        plan's layout round-trips through sharded checkpoints with no
        env override.  Otherwise the hapi trainer is data-parallel and
        the spec is pure-dp over the launched world."""
        from ..distributed.mesh import get_mesh
        from ..distributed.reshard import MeshSpec
        mesh = get_mesh()
        if mesh is not None and any(
                mesh.get_dim_size(n) > 1 for n in mesh.dim_names
                if n != "dp"):
            axes = [n for n in mesh.dim_names if mesh.get_dim_size(n) > 1]
            return MeshSpec(tuple(axes),
                            tuple(mesh.get_dim_size(n) for n in axes))
        return MeshSpec(("dp",), (max(self._nranks, 1),))

    def _resume_target_mesh(self):
        """The mesh this incarnation reshards checkpoints onto: the
        ``PADDLE_RESHARD_MESH`` env override (an operator or controller
        pinning a plan, cf. fleet.elastic.reshard_mesh_for) wins, then
        the active hybrid mesh's factorization
        (:meth:`_checkpoint_mesh_spec` — the planner's dp×mp plan needs
        no env override), then pure-dp over the current world — which
        matches what ModelCheckpoint saves, keeping the same-topology
        resume on the zero-copy fast path."""
        import json as _json
        from ..distributed.reshard import MeshSpec
        raw = os.environ.get("PADDLE_RESHARD_MESH")
        if raw:
            obj = _json.loads(raw)
            return MeshSpec(obj["axes"], obj["shape"])
        return self._checkpoint_mesh_spec()

    def _resume_from(self, resume, save_dir, ckpt_cb):
        """Restore model/optimizer/epoch from the latest valid checkpoint;
        returns the epoch to continue from (0 when nothing to restore).

        Elastic resize (docs/FAULT_TOLERANCE.md): when the checkpoint's
        manifest carries a shard layout and this relaunch runs a
        DIFFERENT world size / mesh, the state is resharded onto the
        topology the auto_tuner picked for the new world.  Identical
        layouts take the zero-copy fast path (each rank reads only its
        own shard file); a layout-incompatible checkpoint raises
        ``LayoutMismatchError`` naming both layouts instead of silently
        loading garbage.  Pre-layout checkpoints still load whole, as
        before."""
        resume_dir = resume if isinstance(resume, (str, os.PathLike)) \
            else (save_dir or (ckpt_cb.save_dir if ckpt_cb else None))
        if not resume_dir:
            raise ValueError(
                "fit(resume=True) needs save_dir (or resume=<dir>)")
        # rung 1 of the recovery ladder: a relaunched incarnation pulls
        # its shard from the buddy's RAM (or the parked guardian-store
        # copy) before touching disk.  Any rung-1 failure warned loudly
        # inside restore_with_ladder and we fall through to rung 3.
        from ..utils.flags import flag as _flag
        if _flag("FLAGS_hot_spare", False):
            from ..framework import hot_spare
            got = hot_spare.restore_with_ladder(
                os.environ.get("PADDLE_JOB_ID", "default"), self._rank,
                disk_fn=None)
            if got is not None:
                state, book, _source = got
                self._sentinel_restore(state)
                return int(book.get("next_epoch", book.get("epoch", 0)))
        from ..distributed.reshard import restore_latest_resharded
        restored = restore_latest_resharded(
            str(resume_dir), self._resume_target_mesh(), self._rank)
        if restored is None:
            return 0
        state, _step, report = restored
        if not report.get("fast_path"):
            from ..utils.log import get_logger
            get_logger().warning(
                "resume resharded checkpoint %s -> %s (%s arrays)",
                report.get("saved_mesh"), report.get("target_mesh"),
                report.get("arrays_resharded"))
        self.network.set_state_dict(state["model"])
        if self._optimizer is not None and state.get("optimizer"):
            self._optimizer.set_state_dict(state["optimizer"])
        pipe = getattr(self, "_data_pipeline", None)
        if pipe is not None and state.get("data_pipeline"):
            # O(1) mid-epoch rewind: the pipeline re-derives its buffers
            # from (epoch, global position) — and because the position
            # is global, the same state loads on a resized dp world
            pipe.load_state_dict(state["data_pipeline"])
        return int(state.get("next_epoch", 0))

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None,
                 _callbacks=None):
        loader = self._as_loader(eval_data, batch_size, False)
        cbs = _callbacks or config_callbacks(callbacks, self,
                                             verbose=verbose)
        cbs.call("on_eval_begin")
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(loader):
            x, y = self._split_batch(batch)
            loss, out = self.eval_batch(x, y)
            losses.append(loss[0])
            for m in self._metrics:
                m.update(*m.compute(out, y))
            cbs.call("on_eval_batch_end", step, {"loss": loss[0]})
        logs = {"loss": float(np.mean(losses)) if losses else 0.0}
        for m in self._metrics:
            logs[m.name()] = m.accumulate()
        cbs.call("on_eval_end", logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._as_loader(test_data, batch_size, False)
        outs = []
        for batch in loader:
            x, _ = self._split_batch(batch, allow_no_label=True)
            outs.append(self.predict_batch(x))
        if stack_outputs:
            import jax.numpy as jnp
            return Tensor(jnp.concatenate([o._data_ for o in outs]))
        return outs

    @staticmethod
    def _split_batch(batch, allow_no_label=False):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return batch[0], batch[1]
            if allow_no_label:
                return batch[0], None
        return batch, None

    # ---- persistence ----
    def save(self, path, training=True):
        from ..framework.io import save
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load
        self.network.set_state_dict(load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None \
                and os.path.exists(opt_path):
            self._optimizer.set_state_dict(load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        """reference: Model.summary → hapi/model_summary.py; delegates
        to paddle.summary (per-layer table, output shapes when
        input_size is given)."""
        from .. import summary as _summary
        return _summary(self.network, input_size=input_size,
                        dtypes=dtype)
