"""Vision datasets (reference capability: python/paddle/vision/datasets/ —
MNIST/FashionMNIST/Cifar loaders).

Zero-egress environment: loaders read the standard local file formats when
present (`image_path`/`label_path` args, idx/ubyte for MNIST, pickled
batches for CIFAR) and raise a clear error otherwise — no download path.
`FakeData` provides the CI stand-in (reference analog: the fake_cpu_device
test pattern)."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io import Dataset


class FakeData(Dataset):
    """Deterministic synthetic image dataset for tests/benchmarks."""

    def __init__(self, num_samples=256, image_shape=(1, 28, 28),
                 num_classes=10, seed=0, transform=None):
        self.n = num_samples
        self.shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        rng = np.random.default_rng(seed)
        self.images = rng.standard_normal(
            (num_samples,) + self.shape).astype(np.float32)
        self.labels = rng.integers(0, num_classes,
                                   (num_samples, 1)).astype(np.int64)

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        img = self.images[i]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[i]


def _read_idx(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(dims)


class MNIST(Dataset):
    """reference: vision/datasets/mnist.py — idx/ubyte reader."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        base = os.environ.get("MNIST_DATA_HOME", "")
        tag = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(
            base, f"{tag}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(
            base, f"{tag}-labels-idx1-ubyte.gz")
        if not (os.path.exists(image_path) and os.path.exists(label_path)):
            raise FileNotFoundError(
                f"MNIST files not found ({image_path}); this environment "
                "has no network egress — point image_path/label_path at "
                "local idx files or use vision.datasets.FakeData")
        self.images = _read_idx(image_path)
        self.labels = _read_idx(label_path).astype(np.int64)
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, i):
        img = self.images[i]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = (img.astype(np.float32) / 255.0)[None]
        return img, np.asarray([self.labels[i]], dtype=np.int64)


FashionMNIST = MNIST  # same idx format, different files


class DatasetFolder(Dataset):
    """Directory-per-class dataset (reference:
    vision/datasets/folder.py DatasetFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        exts = tuple(extensions or (".jpg", ".jpeg", ".png", ".bmp",
                                    ".npy"))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class directories under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    path = os.path.join(dirpath, fname)
                    ok = (is_valid_file(path) if is_valid_file
                          else fname.lower().endswith(exts))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        from PIL import Image
        return Image.open(path).convert("RGB")

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        path, target = self.samples[i]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target


class ImageFolder(DatasetFolder):
    """Flat image folder without labels (reference: ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        exts = tuple(extensions or (".jpg", ".jpeg", ".png", ".bmp",
                                    ".npy"))
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(dirpath, fname)
                ok = (is_valid_file(path) if is_valid_file
                      else fname.lower().endswith(exts))
                if ok:
                    self.samples.append(path)

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        img = self.loader(self.samples[i])
        if self.transform is not None:
            img = self.transform(img)
        return (img,)


class _Cifar(Dataset):
    _n_coarse = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        import os
        import pickle
        import tarfile
        if data_file is None or not os.path.exists(data_file):
            raise RuntimeError(
                f"{type(self).__name__}: pass data_file= pointing at the "
                "local CIFAR archive (no network egress for download)")
        self.transform = transform
        self.mode = mode
        data, labels = [], []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                name = os.path.basename(m.name)
                want = self._member_wanted(name, mode)
                if want:
                    d = pickle.loads(tf.extractfile(m).read(),
                                     encoding="bytes")
                    data.append(d[b"data"])
                    labels.extend(d.get(self._label_key,
                                        d.get(b"labels", [])))
        self.data = np.concatenate(data).reshape(-1, 3, 32, 32) \
            if data else np.empty((0, 3, 32, 32), np.uint8)
        self.labels = np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        img = self.data[i]
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        return img, int(self.labels[i])


class Cifar10(_Cifar):
    """reference: vision/datasets/cifar.py Cifar10."""
    _label_key = b"labels"

    @staticmethod
    def _member_wanted(name, mode):
        return name.startswith("data_batch") if mode == "train" \
            else name == "test_batch"


class Cifar100(_Cifar):
    """reference: vision/datasets/cifar.py Cifar100."""
    _label_key = b"fine_labels"

    @staticmethod
    def _member_wanted(name, mode):
        return name == ("train" if mode == "train" else "test")


class Flowers(Dataset):
    """Oxford-102 flowers (reference: vision/datasets/flowers.py):
    needs the images archive + labels .mat + setid .mat."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True,
                 backend=None):
        import os
        for f in (data_file, label_file, setid_file):
            if f is None or not os.path.exists(f):
                raise RuntimeError(
                    "Flowers: pass data_file=, label_file=, setid_file= "
                    "pointing at local copies (no network egress)")
        import scipy.io as sio
        labels = sio.loadmat(label_file)["labels"].ravel()
        setid = sio.loadmat(setid_file)
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[mode]
        self.indexes = setid[key].ravel()
        self.labels = labels
        self.transform = transform
        # open once; scanning the archive per __getitem__ would be
        # O(archive) I/O per sample (the reference caches the tar too)
        import tarfile
        self._tar = tarfile.open(data_file)
        self._members = {m.name: m for m in self._tar.getmembers()}

    def __len__(self):
        return len(self.indexes)

    def __getitem__(self, i):
        from PIL import Image
        import io as _io
        idx = int(self.indexes[i])
        m = self._tar.extractfile(
            self._members[f"jpg/image_{idx:05d}.jpg"])
        img = Image.open(_io.BytesIO(m.read())).convert("RGB")
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx - 1])


class VOC2012(Dataset):
    """Pascal VOC-2012 segmentation pairs (reference:
    vision/datasets/voc2012.py)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        import os
        import tarfile
        if data_file is None or not os.path.exists(data_file):
            raise RuntimeError("VOC2012: pass data_file= pointing at the "
                               "local VOCtrainval archive")
        self.transform = transform
        split = {"train": "train.txt", "valid": "val.txt",
                 "test": "val.txt"}[mode]
        self._tar = tarfile.open(data_file)
        self._members = {m.name: m for m in self._tar.getmembers()}
        seg_dir = "VOCdevkit/VOC2012/ImageSets/Segmentation/"
        names = self._tar.extractfile(
            self._members[seg_dir + split]).read().decode().split()
        self.names = names

    def __len__(self):
        return len(self.names)

    def __getitem__(self, i):
        import io as _io
        from PIL import Image
        name = self.names[i]
        img = Image.open(_io.BytesIO(self._tar.extractfile(
            self._members[
                f"VOCdevkit/VOC2012/JPEGImages/{name}.jpg"]).read()))
        lbl = Image.open(_io.BytesIO(self._tar.extractfile(
            self._members[
                f"VOCdevkit/VOC2012/SegmentationClass/{name}.png"]
        ).read()))
        img = np.asarray(img.convert("RGB"))
        lbl = np.asarray(lbl)
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl
