"""Bijective transforms (reference: python/paddle/distribution/transform.py
— Transform base with forward/inverse/log-det-jacobian, Affine/Exp/
Sigmoid/Tanh/Power/Abs/Softmax/StickBreaking/Chain/Independent/Reshape).

TPU-native: transforms are pure jnp maps; TransformedDistribution composes
them with a base distribution's sampler/log_prob so the whole chain traces
into one XLA program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = [
    "Type", "Transform", "AffineTransform", "ExpTransform",
    "PowerTransform", "SigmoidTransform", "TanhTransform", "AbsTransform",
    "SoftmaxTransform", "StickBreakingTransform", "ChainTransform",
    "IndependentTransform", "ReshapeTransform", "StackTransform",
]


def _arr(x):
    return x._data_ if isinstance(x, Tensor) else jnp.asarray(x)


class Type:
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"

    @classmethod
    def is_injective(cls, t):
        return t in (cls.BIJECTION, cls.INJECTION)


class Transform:
    _type = Type.INJECTION

    @property
    def type(self):
        return self._type

    # event dims consumed/produced (0 = elementwise)
    _domain_event_dim = 0
    _codomain_event_dim = 0

    def forward(self, x):
        return Tensor(self._forward(_arr(x)))

    def inverse(self, y):
        return Tensor(self._inverse(_arr(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(self._forward_log_det_jacobian(_arr(x)))

    def inverse_log_det_jacobian(self, y):
        return Tensor(-self._forward_log_det_jacobian(
            self._inverse(_arr(y))))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # array-level hooks subclasses implement
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self.loc = _arr(loc).astype(jnp.float32)
        self.scale = _arr(scale).astype(jnp.float32)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, power):
        self.power = _arr(power).astype(jnp.float32)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh(x)^2) = 2 (log2 - x - softplus(-2x))
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class AbsTransform(Transform):
    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # right inverse

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class SoftmaxTransform(Transform):
    _type = Type.OTHER
    _domain_event_dim = 1
    _codomain_event_dim = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)


class StickBreakingTransform(Transform):
    """R^{n} → simplex^{n+1} (reference transform.py:StickBreakingTransform)."""
    _type = Type.BIJECTION
    _domain_event_dim = 1
    _codomain_event_dim = 1

    def _forward(self, x):
        n = x.shape[-1]
        offset = jnp.log(jnp.arange(n, 0, -1, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        zcum = jnp.cumprod(1 - z, axis=-1)
        pad = jnp.ones_like(z[..., :1])
        return jnp.concatenate([z, pad], -1) * \
            jnp.concatenate([pad, zcum], -1)

    def _inverse(self, y):
        n = y.shape[-1] - 1
        ycum = jnp.cumsum(y[..., :-1], axis=-1)
        rem = 1 - ycum + y[..., :-1]          # remaining stick incl. current
        z = y[..., :-1] / rem
        offset = jnp.log(jnp.arange(n, 0, -1, dtype=y.dtype))
        return jnp.log(z) - jnp.log1p(-z) + offset

    def _forward_log_det_jacobian(self, x):
        n = x.shape[-1]
        offset = jnp.log(jnp.arange(n, 0, -1, dtype=x.dtype))
        xo = x - offset
        z = jax.nn.sigmoid(xo)
        zcum1 = jnp.cumprod(1 - z, axis=-1)
        pad = jnp.ones_like(z[..., :1])
        rem = jnp.concatenate([pad, zcum1[..., :-1]], -1)
        # dy_i/dx_i = sigma(xo)sigma(-xo) * prod_{j<i}(1-z_j), triangular
        return jnp.sum(-jax.nn.softplus(xo) - jax.nn.softplus(-xo)
                       + jnp.log(rem), axis=-1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)
        self._type = (Type.BIJECTION if all(
            t.type == Type.BIJECTION for t in self.transforms)
            else Type.OTHER)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            j = t._forward_log_det_jacobian(x)
            total = j if total is None else total + j
            x = t._forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape


class IndependentTransform(Transform):
    """Reinterprets the rightmost batch dims of a base transform as event
    dims (sums the log-det over them)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        self._type = base.type

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        j = self.base._forward_log_det_jacobian(x)
        return jnp.sum(j, axis=tuple(range(-self.rank, 0)))


class ReshapeTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)


class StackTransform(Transform):
    """Applies a sequence of transforms to slices along `axis`
    (reference: python/paddle/distribution/transform.py:1051)."""

    def __init__(self, transforms, axis=0):
        import typing
        if not transforms or not isinstance(transforms, typing.Sequence):
            raise TypeError(
                f"Expected 'transforms' is Sequence[Transform], but got "
                f"{type(transforms)}.")
        if not all(isinstance(t, Transform) for t in transforms):
            raise TypeError(
                "Expected all element in transforms is Transform Type.")
        if not isinstance(axis, int):
            raise TypeError(f"Expected 'axis' is int, but got {type(axis)}.")
        self._transforms = list(transforms)
        self._axis = axis
        self._type = (Type.BIJECTION if all(
            t.type == Type.BIJECTION for t in self._transforms)
            else Type.OTHER)

    @property
    def transforms(self):
        return self._transforms

    @property
    def axis(self):
        return self._axis

    def _map(self, fn_name, v):
        slices = [jnp.squeeze(s, self._axis)
                  for s in jnp.split(v, v.shape[self._axis],
                                     axis=self._axis)]
        outs = [getattr(t, fn_name)(s)
                for t, s in zip(self._transforms, slices)]
        return jnp.stack(outs, axis=self._axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _forward_log_det_jacobian(self, x):
        return self._map("_forward_log_det_jacobian", x)
