"""MFU sweep: find the best single-chip GPT-2 batch size on real
hardware and record it for bench.py.

BASELINE.md config 2 fixes model+seq but not batch; the MXU is fed
better at larger batches (more rows per matmul tile, fixed overheads
amortized), so the sweep measures tokens/sec at several batch sizes
with the same slope-timing bench.py uses, writes the winner to
benchmarks/TUNED.json (bench.py adopts it), and appends every
measurement to benchmarks/TPU_RUNS.jsonl with "sweep": true so the
numbers stay auditable (VERDICT r03 item 1 demands recorded evidence
for every perf claim).

Run only on TPU — exits immediately on CPU.
"""
from __future__ import annotations

import datetime
import json
import os
import sys
import time

import numpy as np

BATCHES = [int(b) for b in os.environ.get(
    "MFU_SWEEP_BATCHES", "8,16,32").split(",")]
SEQ = 1024
STEPS = 8


def _log(msg):
    print(f"[mfu_sweep] {msg}", file=sys.stderr, flush=True)


def measure(batch):
    """One measured config in a fresh python process (a fresh process
    releases all device buffers of the previous config)."""
    import subprocess
    code = f"""
import json, sys, time
import numpy as np
import jax
import paddle_tpu as paddle
from paddle_tpu.models import GPTForCausalLM
from paddle_tpu.models.gpt import gpt_config

batch, seq, steps = {batch}, {SEQ}, {STEPS}
cfg = gpt_config("gpt2-124m", max_seq_len=seq, use_flash_attention=True)
try:
    from paddle_tpu.pallas.flash_attention import autotune_blocks
    autotune_blocks(seq, cfg.head_dim, batch=batch, heads=cfg.num_heads)
except Exception:
    pass
paddle.seed(0)
with paddle.amp.auto_cast(enable=True, level="O2", dtype="bfloat16"):
    model = GPTForCausalLM(cfg)
opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters(),
                             weight_decay=0.01)
rng = np.random.default_rng(0)
data = rng.integers(0, cfg.vocab_size, (batch, seq + 1), dtype=np.int32)
x, y = paddle.to_tensor(data[:, :-1]), paddle.to_tensor(data[:, 1:])
x1, y1 = paddle.to_tensor(data[:1, :-1]), paddle.to_tensor(data[:1, 1:])

# one donated-buffer compiled step (framework/train_step.py) — the same
# lane bench.py measures; eager fallback stays byte-identical
from paddle_tpu.framework.train_step import CompiledTrainStep

def forward(x, y):
    with paddle.amp.auto_cast(enable=True, level="O2", dtype="bfloat16"):
        _, loss = model(x, labels=y)
    return loss

def eager_step(x, y, update=True):
    loss = forward(x, y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    return loss

_cs = CompiledTrainStep(forward, opt, network=model, eager_step=eager_step)

def train_step(x, y):
    return _cs(x, y, update=True)

for _ in range(2):
    loss = train_step(x1, y1)
for _ in range(3):
    loss = train_step(x, y)
float(loss)

def timed(k):
    t0 = time.perf_counter()
    lv = None
    for _ in range(k):
        lv = train_step(x, y)
    lv = float(lv)
    return time.perf_counter() - t0, lv

t1, _ = timed(1)
tN, final_loss = timed(steps)
slope = (tN - t1) / (steps - 1)
print(json.dumps({{"batch": batch, "slope": slope,
                  "tokens_per_sec": batch * seq / slope,
                  "step_time_ms_p50": slope * 1e3,
                  "step_lane": "compiled" if _cs.compiled else "eager",
                  "t1": t1, "tN": tN, "loss": final_loss}}))
"""
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=2000)
    except subprocess.TimeoutExpired:
        _log(f"batch {batch} TIMED OUT — skipping")
        return None
    if r.returncode != 0:
        _log(f"batch {batch} FAILED: {r.stderr[-400:]}")
        return None
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return None


def main():
    import jax
    if jax.devices()[0].platform not in ("tpu", "axon"):
        _log("not on TPU — sweep skipped")
        return 1
    here = os.path.dirname(os.path.abspath(__file__))
    runs_path = os.path.join(here, "TPU_RUNS.jsonl")
    from paddle_tpu.cost_model import device_peak_flops
    peak = device_peak_flops(jax.devices()[0].platform)
    results = []
    for b in BATCHES:
        _log(f"measuring batch {b} ...")
        rec = measure(b)
        if rec is None:
            continue
        results.append(rec)
        _log(f"batch {b}: {rec['tokens_per_sec']:.0f} tok/s")
        with open(runs_path, "a") as f:
            f.write(json.dumps({
                "ts": datetime.datetime.now(
                    datetime.timezone.utc).isoformat(timespec="seconds"),
                "metric": "gpt2_124m_train_tokens_per_sec",
                "sweep": True, "batch": rec["batch"], "seq": SEQ,
                "tokens_per_sec": round(rec["tokens_per_sec"], 1),
                "step_lane": rec.get("step_lane"),
                "step_time_ms_p50": round(
                    rec.get("step_time_ms_p50", 0), 3),
                "loss": round(rec["loss"], 4),
                "timing": {"t1_s": round(rec["t1"], 6),
                           "tN_s": round(rec["tN"], 6), "N": STEPS,
                           "slope_s_per_step": round(rec["slope"], 6),
                           "method": "slope"},
                "platform": jax.devices()[0].platform,
                "peak_flops": peak,
            }) + "\n")
    if not results:
        _log("no successful measurements")
        return 1
    best = max(results, key=lambda r: r["tokens_per_sec"])
    tuned_path = os.path.join(here, "TUNED.json")
    with open(tuned_path, "w") as f:
        json.dump({"gpt2_124m": {"batch": best["batch"], "seq": SEQ,
                                 "tokens_per_sec": round(
                                     best["tokens_per_sec"], 1)}}, f)
    _log(f"best batch {best['batch']} "
         f"({best['tokens_per_sec']:.0f} tok/s) -> {tuned_path}")
    print(json.dumps(best))
    return 0


if __name__ == "__main__":
    sys.exit(main())
