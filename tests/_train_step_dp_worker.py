"""2-process eager-dp trajectory worker for tests/test_train_step.py.

Each rank trains the identically-seeded MLP on its shard of the SAME
global batches through hapi's eager lane (per-tensor ``_sync_grads``
all-reduce); the parent test replays the global batches through the
compiled train step's in-program dp ``pmean`` on a 2-device mesh and
asserts the trajectories match.  Also asserts the compiled step itself
DECLINES a multi-process CPU world (host-collective lane)."""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["PADDLE_MASTER"],
    num_processes=int(os.environ["WORLD_SIZE"]),
    process_id=int(os.environ["PADDLE_TRAINER_ID"]))

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu import nn, Model  # noqa: E402


def main():
    out_dir = sys.argv[1]
    dist.init_parallel_env()
    rank, world = dist.get_rank(), dist.get_world_size()
    assert world == 2

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = paddle.optimizer.AdamW(0.01, parameters=net.parameters(),
                                 weight_decay=0.01)
    model = Model(net)
    model.prepare(optimizer=opt, loss=lambda o, y: ((o - y) ** 2).mean())
    assert model._nranks == 2

    rng = np.random.default_rng(0)
    losses = []
    for _ in range(6):
        xg = rng.standard_normal((4, 8)).astype("float32")
        yg = rng.standard_normal((4, 4)).astype("float32")
        x = paddle.to_tensor(xg[rank * 2:(rank + 1) * 2])
        y = paddle.to_tensor(yg[rank * 2:(rank + 1) * 2])
        losses.append(model.train_batch(x, y)[0])

    # the compiled step must have declined this world: 2-proc CPU runs
    # the host-collective eager lane, which one XLA program cannot span
    assert model._compiled_step is False, model._compiled_step

    with open(os.path.join(out_dir, f"result.{rank}.json"), "w") as f:
        json.dump({"losses": losses,
                   "weights": [p.numpy().ravel().tolist()
                               for p in net.parameters()]}, f)
    open(os.path.join(out_dir, f"ok.{rank}"), "w").close()


if __name__ == "__main__":
    main()
