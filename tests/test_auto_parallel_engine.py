"""Auto-parallel Engine: cost-based planning + fit (reference pattern:
test/auto_parallel/engine_api.py; planner analog of static/tuner/
rule_based_tuner.py / parallel_tuner.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import auto_parallel as ap


class _TinyDataset(paddle.io.Dataset):
    def __init__(self, n=32):
        rng = np.random.default_rng(0)
        self.x = rng.normal(size=(n, 16)).astype(np.float32)
        self.y = rng.integers(0, 4, size=(n,)).astype(np.int64)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _model():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 4))


def test_engine_plan_picks_feasible_config():
    dist.set_mesh(None)
    model = _model()
    eng = ap.Engine(model=model, loss=nn.CrossEntropyLoss(),
                    optimizer=paddle.optimizer.AdamW(
                        1e-3, parameters=model.parameters()))
    planned = eng.plan(global_batch=32, seq_len=16, n_devices=8,
                       device="v5e")
    # a full factorization of the device count, no internal keys leaked
    assert planned["dp"] * planned["mp"] * planned["pp"] \
        * planned["sharding"] == 8
    assert not any(k.startswith("_") for k in planned)
    # the plan is written through to the strategy fleet.init consumes
    hc = eng._strategy._inner.hybrid_configs
    assert hc["dp_degree"] == planned["dp"]
    assert hc["mp_degree"] == planned["mp"]
    # tiny dense model on a v5e: data parallel should dominate the ranking
    assert planned["dp"] * planned["sharding"] >= planned["mp"]


def test_engine_plan_then_fit_decreases_loss():
    dist.set_mesh(None)
    np.random.seed(0)  # DataLoader shuffle order must not depend on
    # whatever earlier tests drew from the global numpy stream
    model = _model()
    opt = paddle.optimizer.AdamW(1e-2, parameters=model.parameters())
    eng = ap.Engine(model=model, loss=nn.CrossEntropyLoss(), optimizer=opt)
    eng.plan(global_batch=32, seq_len=16, n_devices=8, device="v5e")
    eng.prepare()
    history = eng.fit(_TinyDataset(), epochs=4, batch_size=8)
    losses = history["loss"]
    assert len(losses) == 4
    assert all(np.isfinite(losses))
    assert min(losses[1:]) < losses[0]
    dist.set_mesh(None)
