"""Multi-pod launcher + elastic scale-in/out over the native TCPStore
(VERDICT r3 item 5; reference: launch/controllers/master.py:73,186
HTTPMaster/ETCDMaster rendezvous, fleet/elastic/manager.py:487,510
scale-out/in)."""
import os
import threading
import time

import pytest

from paddle_tpu.distributed.launch.context import (Context, parse_args,
                                                   free_port)
from paddle_tpu.distributed.launch.controller import (
    ElasticCollectiveController,
)

WORKER = os.path.join(os.path.dirname(__file__), "_pod_worker.py")


def _pod(endpoint, pod_id, host, outdir, nnodes, park="-", job="j",
         quiet=0.5):
    args = parse_args([
        "--master", endpoint, "--nnodes", nnodes,
        "--node_rank", "0" if host else "1",
        "--pod_id", pod_id, "--job_id", job,
        "--nproc_per_node", "1", "--elastic_quiet", str(quiet),
        "--elastic_timeout", "15",
        WORKER, str(outdir), park])
    return ElasticCollectiveController(Context(args=args))


def _run_in_thread(ctrl, out):
    def target():
        out[ctrl.kv.pod_id] = ctrl.run()
    t = threading.Thread(target=target, daemon=True)
    t.start()
    return t


def _wait_for(path, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path):
            return True
        time.sleep(0.1)
    return False


def test_two_pod_launch_rendezvous_ranks(tmp_path):
    # two pods rendezvous through the store; worker ranks are assigned
    # from the committed membership order, not static node_rank
    ep = f"127.0.0.1:{free_port()}"
    codes = {}
    a = _pod(ep, "a", True, tmp_path, "2", job="two")
    b = _pod(ep, "b", False, tmp_path, "2", job="two")
    ta = _run_in_thread(a, codes)
    time.sleep(0.3)
    tb = _run_in_thread(b, codes)
    ta.join(60)
    tb.join(60)
    assert codes == {"a": 0, "b": 0}
    assert (tmp_path / "w2.r0").exists()      # pod a → rank 0
    assert (tmp_path / "w2.r1").exists()      # pod b → rank 1
    assert not (tmp_path / "w1.r0").exists()  # never committed solo


def test_scale_out_joiner_triggers_rebuild(tmp_path):
    # pod a starts alone (elastic range 1:2) and its worker parks; pod b
    # joining must trigger a rendezvous rebuild: a's worker is restarted
    # with world=2 and contiguous remapped ranks
    ep = f"127.0.0.1:{free_port()}"
    codes = {}
    a = _pod(ep, "a", True, tmp_path, "1:2", park="1", job="so")
    ta = _run_in_thread(a, codes)
    assert _wait_for(tmp_path / "w1.r0"), "solo rendezvous never committed"
    b = _pod(ep, "b", False, tmp_path, "1:2", park="-", job="so")
    tb = _run_in_thread(b, codes)
    ta.join(60)
    tb.join(60)
    assert codes == {"a": 0, "b": 0}
    assert (tmp_path / "w2.r0").exists()      # a restarted into world 2
    assert (tmp_path / "w2.r1").exists()      # b joined as rank 1


def test_scale_in_dead_pod_triggers_rebuild(tmp_path):
    # two pods commit world=2 (a's worker parks); b then dies without
    # deregistering — its heartbeat expires, a rebuilds to world=1
    from paddle_tpu.distributed.launch.master import KVMaster

    ep = f"127.0.0.1:{free_port()}"
    codes = {}
    # quiet=3.0 >> b's join delay: the first commit must include BOTH
    # pods (a solo world-1 commit would exit a's worker prematurely)
    a = _pod(ep, "a", True, tmp_path, "1:2", park="2", job="si",
             quiet=3.0)
    a.kv._hb.ttl = 1.5
    ta = _run_in_thread(a, codes)
    time.sleep(0.3)
    # pod b: bare rendezvous participant with a heartbeat we can cut
    kvb = KVMaster(ep, "b", np=1, is_host=False, job_id="si", ttl=1.5,
                   timeout=30)
    kvb.start_heartbeat(interval=0.3)
    r, pods, idx = kvb.rendezvous(1, 2, quiet=0.5)
    assert [p["id"] for p in pods] == ["a", "b"] and idx == 1
    assert _wait_for(tmp_path / "w2.r0"), "world-2 rendezvous missing"
    # b dies abruptly: stop stamping, leave its key to expire via TTL
    kvb._stop.set()
    ta.join(60)
    kvb.store.close()
    assert codes == {"a": 0}
    assert (tmp_path / "w1.r0").exists()      # a rebuilt down to world 1


def test_rendezvous_assigns_contiguous_ranks_multi_proc(tmp_path):
    # pods with different nproc_per_node: rank blocks are contiguous in
    # pod-id order and PADDLE_TRAINERS_NUM is the global worker count
    ep = f"127.0.0.1:{free_port()}"
    codes = {}
    args_a = parse_args([
        "--master", ep, "--nnodes", "2", "--node_rank", "0",
        "--pod_id", "a", "--job_id", "mp", "--nproc_per_node", "2",
        "--elastic_timeout", "15", WORKER, str(tmp_path), "-"])
    args_b = parse_args([
        "--master", ep, "--nnodes", "2", "--node_rank", "1",
        "--pod_id", "b", "--job_id", "mp", "--nproc_per_node", "1",
        "--elastic_timeout", "15", WORKER, str(tmp_path), "-"])
    a = ElasticCollectiveController(Context(args=args_a))
    b = ElasticCollectiveController(Context(args=args_b))
    ta = _run_in_thread(a, codes)
    tb = _run_in_thread(b, codes)
    ta.join(60)
    tb.join(60)
    assert codes == {"a": 0, "b": 0}
    for r in range(3):
        assert (tmp_path / f"w3.r{r}").exists(), r


FAULT_WORKER = os.path.join(os.path.dirname(__file__), "_fault_worker.py")


def test_fault_tolerance_level_relaunches_crashed_worker(tmp_path,
                                                         monkeypatch):
    """PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL>0 (reference: elastic
    manager.py:178, spelling as in the reference): a worker crashing
    with an ordinary nonzero code is relaunched instead of failing the
    job; level 0 keeps the fail-fast behavior."""
    # level 1: crash-once worker recovers on the relaunch
    monkeypatch.setenv("PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL", "1")
    ep = f"127.0.0.1:{free_port()}"
    args = parse_args([
        "--master", ep, "--nnodes", "1", "--node_rank", "0",
        "--pod_id", "p0", "--job_id", "ft", "--nproc_per_node", "1",
        "--elastic_quiet", "0.2", "--elastic_timeout", "15",
        "--max_restart", "3",
        FAULT_WORKER, str(tmp_path)])
    rc = ElasticCollectiveController(Context(args=args)).run()
    assert rc == 0
    assert (tmp_path / "ok.0").exists()

    # level 0: same crash is terminal
    monkeypatch.setenv("PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL", "0")
    out2 = tmp_path / "lvl0"
    out2.mkdir()
    ep2 = f"127.0.0.1:{free_port()}"
    args2 = parse_args([
        "--master", ep2, "--nnodes", "1", "--node_rank", "0",
        "--pod_id", "p0", "--job_id", "ft0", "--nproc_per_node", "1",
        "--elastic_quiet", "0.2", "--elastic_timeout", "15",
        "--max_restart", "3",
        FAULT_WORKER, str(out2)])
    rc2 = ElasticCollectiveController(Context(args=args2)).run()
    assert rc2 == 3
    assert not (out2 / "ok.0").exists()
