"""paddle.text (reference: python/paddle/text/__init__.py): NLP datasets
plus the Viterbi decoder ops.

Datasets follow the reference's file-backed protocol but accept a local
``data_file`` (this environment has no network egress); downloading
constructors raise with a clear message instead of hanging."""
from __future__ import annotations

import gzip
import os
import tarfile

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..io import Dataset

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16", "ViterbiDecoder", "viterbi_decode"]


class _FileDataset(Dataset):
    _name = "dataset"

    def __init__(self, data_file=None, mode="train", **kwargs):
        self.mode = mode
        if data_file is None or not os.path.exists(data_file):
            raise RuntimeError(
                f"{type(self).__name__}: pass data_file= pointing at a "
                f"local copy of the {self._name} archive — this "
                f"environment has no network access for auto-download "
                f"(reference datasets download from paddle dataset CDNs)")
        self.data_file = data_file
        self._examples = self._load()

    def _load(self):
        raise NotImplementedError

    def __len__(self):
        return len(self._examples)

    def __getitem__(self, i):
        return self._examples[i]


class Imdb(_FileDataset):
    """IMDB sentiment (reference: text/datasets/imdb.py)."""
    _name = "aclImdb"

    def _load(self):
        out = []
        with tarfile.open(self.data_file) as tf:
            for m in tf.getmembers():
                path = m.name
                if f"/{self.mode}/pos/" in path and path.endswith(".txt"):
                    out.append((tf.extractfile(m).read().decode(), 1))
                elif f"/{self.mode}/neg/" in path and path.endswith(".txt"):
                    out.append((tf.extractfile(m).read().decode(), 0))
        return out


class Imikolov(_FileDataset):
    """PTB language-model ngrams (reference: text/datasets/imikolov.py)."""
    _name = "simple-examples"

    def __init__(self, data_file=None, data_type="NGRAM", window_size=2,
                 mode="train", min_word_freq=50):
        self.data_type = data_type
        self.window_size = window_size
        super().__init__(data_file, mode)

    def _load(self):
        split = {"train": "ptb.train.txt", "test": "ptb.test.txt"}.get(
            self.mode, "ptb.valid.txt")
        with tarfile.open(self.data_file) as tf:
            member = [m for m in tf.getmembers()
                      if m.name.endswith(split)][0]
            text = tf.extractfile(member).read().decode()
        out = []
        for line in text.splitlines():
            words = line.split()
            for i in range(len(words) - self.window_size + 1):
                out.append(tuple(words[i:i + self.window_size]))
        return out


class Conll05st(_FileDataset):
    """CoNLL-2005 SRL (reference: text/datasets/conll05.py)."""
    _name = "conll05st"

    def _load(self):
        out = []
        with tarfile.open(self.data_file) as tf:
            for m in tf.getmembers():
                if m.isfile() and m.name.endswith(".txt"):
                    for line in tf.extractfile(m).read().decode(
                            errors="replace").splitlines():
                        if line.strip():
                            out.append(tuple(line.split()))
        return out


class Movielens(_FileDataset):
    """MovieLens ratings (reference: text/datasets/movielens.py)."""
    _name = "ml-1m"

    def _load(self):
        out = []
        with (gzip.open(self.data_file, "rt", errors="replace")
              if self.data_file.endswith(".gz")
              else open(self.data_file, errors="replace")) as f:
            for line in f:
                parts = line.strip().split("::")
                if len(parts) == 4:
                    u, m, r, _ = parts
                    out.append((int(u), int(m), float(r)))
        return out


class UCIHousing(_FileDataset):
    """Boston housing regression (reference: text/datasets/uci_housing.py)."""
    _name = "housing.data"

    def _load(self):
        rows = np.loadtxt(self.data_file)
        feats = rows[:, :-1].astype(np.float32)
        feats = (feats - feats.mean(0)) / (feats.std(0) + 1e-6)
        split = int(0.8 * len(rows))
        sel = slice(0, split) if self.mode == "train" else \
            slice(split, None)
        return [(feats[i], np.float32(rows[i, -1]))
                for i in range(*sel.indices(len(rows)))]


class WMT14(_FileDataset):
    """WMT-14 en-fr pairs (reference: text/datasets/wmt14.py)."""
    _name = "wmt14"

    def _load(self):
        out = []
        with tarfile.open(self.data_file) as tf:
            names = [m.name for m in tf.getmembers()]
            src = [n for n in names if self.mode in n and n.endswith(".en")]
            trg = [n for n in names if self.mode in n and n.endswith(".fr")]
            if src and trg:
                s = tf.extractfile(src[0]).read().decode().splitlines()
                t = tf.extractfile(trg[0]).read().decode().splitlines()
                out = list(zip(s, t))
        return out


class WMT16(WMT14):
    """WMT-16 en-de pairs (reference: text/datasets/wmt16.py)."""
    _name = "wmt16"


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Viterbi decoding over emission potentials [B, T, N] with a
    transition matrix [N(+2), N(+2)] (reference: text/viterbi_decode.py
    over the viterbi_decode kernel).  Returns (scores [B], paths [B, T])."""
    def fn(pot, trans, lens):
        b, t_max, n = pot.shape
        if include_bos_eos_tag:
            start = trans[-2, :n]
            stop = trans[:n, -1]
        else:
            start = jnp.zeros((n,), pot.dtype)
            stop = jnp.zeros((n,), pot.dtype)
        trans_nn = trans[:n, :n]

        alpha0 = pot[:, 0] + start[None, :]

        def step(alpha, pot_t):
            scores = alpha[:, :, None] + trans_nn[None]  # [B, from, to]
            best = jnp.max(scores, axis=1) + pot_t
            back = jnp.argmax(scores, axis=1)
            return best, (best, back)

        _, (alphas_rest, backs) = jax.lax.scan(
            step, alpha0, jnp.moveaxis(pot[:, 1:], 1, 0))
        alphas = jnp.concatenate([alpha0[None], alphas_rest], axis=0)
        t_idx = jnp.clip(lens.astype(jnp.int32) - 1, 0, t_max - 1)
        final = alphas[t_idx, jnp.arange(b)] + stop[None, :]
        scores = jnp.max(final, axis=-1)
        last_tag = jnp.argmax(final, axis=-1).astype(jnp.int32)

        def backtrace(carry, back_t_rev):
            tag, t = carry
            prev = back_t_rev[jnp.arange(b), tag].astype(jnp.int32)
            within = (t <= t_idx) & (t >= 1)
            tag = jnp.where(within, prev, tag)
            return (tag, t - 1), tag

        # backs[k] maps alpha at step k → best predecessor; iterate from
        # the top (t = T-1 .. 1), emitting the tag at t-1 each step
        (_, _), tags_rev = jax.lax.scan(
            backtrace, (last_tag, jnp.full((b,), t_max - 1)), backs[::-1])
        path = jnp.concatenate([tags_rev[::-1].T, last_tag[:, None]],
                               axis=1)
        return scores, path.astype(jnp.int64)

    return apply_op("viterbi_decode", fn,
                    (potentials, transition_params, lengths))


class ViterbiDecoder:
    """Layer wrapper (reference: text/viterbi_decode.py ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
