"""Graph-learning ops (reference capability: python/paddle/geometric/ —
segment math, send/recv message passing, graph reindex/sampling).

TPU-native realization: everything lowers to `jax.ops.segment_*` /
gather-scatter, which XLA compiles to efficient sorted-segment kernels;
the whole message-passing step stays in one fused program (the reference
ships dedicated CUDA kernels under paddle/phi/kernels/gpu/graph_*).
Sampling (`sample_neighbors`) is host-side by nature — it runs on CPU with
numpy, mirroring the reference's CPU sampling path.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "send_u_recv", "send_ue_recv", "send_uv", "reindex_graph",
    "sample_neighbors",
    "reindex_heter_graph", "weighted_sample_neighbors",
]


def _arr(x):
    return x._data_ if isinstance(x, Tensor) else jnp.asarray(x)


def _num_segments(segment_ids, out_size):
    if out_size is not None:
        return int(out_size)
    ids = _arr(segment_ids)
    if isinstance(ids, jax.core.Tracer):
        raise ValueError(
            "segment count is data-dependent under tracing/jit — pass "
            "out_size= (static) so the op compiles to a fixed shape")
    return int(jax.device_get(ids.max())) + 1 if ids.size else 0


def _finite_or_zero(v):
    # empty segments come back +/-inf from segment_max/min; the reference
    # returns 0 for nodes with no incoming messages
    return jnp.where(jnp.isfinite(v), v, jnp.zeros_like(v))


def _segment(op_name, reducer, data, segment_ids, out_size=None, name=None,
             fix_empty=False):
    n = _num_segments(segment_ids, out_size)

    def fn(x, ids):
        out = reducer(x, ids.astype(jnp.int32), num_segments=n)
        # only max/min produce +/-inf for EMPTY segments; sum must keep
        # propagating NaN/Inf from the data itself
        return _finite_or_zero(out) if fix_empty else out
    return apply_op(op_name, fn, (data, segment_ids))


def segment_sum(data, segment_ids, out_size=None, name=None):
    """reference: geometric/math.py segment_sum (kernel:
    phi/kernels/gpu/segment_pool_kernel.cu).  Pass out_size under jit."""
    return _segment("segment_sum", jax.ops.segment_sum, data, segment_ids,
                    out_size)


def segment_mean(data, segment_ids, out_size=None, name=None):
    n = _num_segments(segment_ids, out_size)

    def fn(x, ids):
        ids = ids.astype(jnp.int32)
        s = jax.ops.segment_sum(x, ids, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((x.shape[0],), x.dtype), ids,
                                  num_segments=n)
        shape = (n,) + (1,) * (x.ndim - 1)
        return s / jnp.maximum(cnt.reshape(shape), 1)
    return apply_op("segment_mean", fn, (data, segment_ids))


def segment_max(data, segment_ids, out_size=None, name=None):
    return _segment("segment_max", jax.ops.segment_max, data, segment_ids,
                    out_size, fix_empty=True)


def segment_min(data, segment_ids, out_size=None, name=None):
    return _segment("segment_min", jax.ops.segment_min, data, segment_ids,
                    out_size, fix_empty=True)


_REDUCE = {
    "sum": jax.ops.segment_sum,
    "mean": None,   # handled explicitly
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src], scatter-reduce onto dst (reference:
    geometric/message_passing/send_recv.py:send_u_recv)."""
    if reduce_op not in _REDUCE:
        raise ValueError(f"unknown reduce_op {reduce_op!r}")
    n = out_size if out_size is not None else \
        _num_segments(dst_index, None)
    n = max(int(n), _arr(x).shape[0]) if out_size is None else int(n)

    def fn(xv, src, dst):
        src = src.astype(jnp.int32)
        dst = dst.astype(jnp.int32)
        msg = xv[src]
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msg, dst, num_segments=n)
            cnt = jax.ops.segment_sum(
                jnp.ones((msg.shape[0],), xv.dtype), dst, num_segments=n)
            shape = (n,) + (1,) * (xv.ndim - 1)
            return s / jnp.maximum(cnt.reshape(shape), 1)
        out = _REDUCE[reduce_op](msg, dst, num_segments=n)
        return _finite_or_zero(out) if reduce_op in ("max", "min") else out
    return apply_op("send_u_recv", fn, (x, src_index, dst_index))


_MSG_OPS = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
}


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine node features x[src] with edge features y, then
    scatter-reduce (reference: send_recv.py:send_ue_recv)."""
    if message_op not in _MSG_OPS:
        raise ValueError(f"unknown message_op {message_op!r}")
    if reduce_op not in _REDUCE:
        raise ValueError(f"unknown reduce_op {reduce_op!r}")
    n = out_size if out_size is not None else \
        max(_num_segments(dst_index, None), _arr(x).shape[0])
    n = int(n)

    def fn(xv, yv, src, dst):
        src = src.astype(jnp.int32)
        dst = dst.astype(jnp.int32)
        msg = _MSG_OPS[message_op](xv[src], yv)
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msg, dst, num_segments=n)
            cnt = jax.ops.segment_sum(
                jnp.ones((msg.shape[0],), msg.dtype), dst, num_segments=n)
            shape = (n,) + (1,) * (msg.ndim - 1)
            return s / jnp.maximum(cnt.reshape(shape), 1)
        out = _REDUCE[reduce_op](msg, dst, num_segments=n)
        return _finite_or_zero(out) if reduce_op in ("max", "min") else out
    return apply_op("send_ue_recv", fn, (x, y, src_index, dst_index))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from both endpoints (reference:
    send_recv.py:send_uv)."""
    if message_op not in _MSG_OPS:
        raise ValueError(f"unknown message_op {message_op!r}")

    def fn(xv, yv, src, dst):
        return _MSG_OPS[message_op](xv[src.astype(jnp.int32)],
                                    yv[dst.astype(jnp.int32)])
    return apply_op("send_uv", fn, (x, y, src_index, dst_index))


def reindex_graph(x, neighbors, count, name=None):
    """Compact global node ids to local ids (reference:
    geometric/reindex.py:reindex_graph).  Host-side (shapes are
    data-dependent)."""
    xs = np.asarray(jax.device_get(_arr(x)))
    nb = np.asarray(jax.device_get(_arr(neighbors)))
    cnt = np.asarray(jax.device_get(_arr(count)))
    # order: x's nodes first, then newly-seen neighbors (reference order)
    order = {}
    for v in xs.tolist():
        order.setdefault(int(v), len(order))
    for v in nb.tolist():
        order.setdefault(int(v), len(order))
    remap = np.array([order[int(v)] for v in np.concatenate([xs, nb])],
                     dtype=np.int64)
    reindex_src = remap[len(xs):]
    reindex_dst = np.repeat(np.arange(len(xs), dtype=np.int64), cnt)
    nodes = np.array(sorted(order, key=order.get), dtype=np.int64)
    return (Tensor(jnp.asarray(reindex_src)),
            Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(nodes)))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """Uniform neighbor sampling over CSC (reference:
    geometric/sampling/neighbors.py:sample_neighbors).  Host-side numpy,
    like the reference CPU path.  With return_eids=True the sampled
    edges' ids are returned as a third output (from `eids` when given,
    else CSC edge positions)."""
    rows = np.asarray(jax.device_get(_arr(row)))
    ptr = np.asarray(jax.device_get(_arr(colptr)))
    nodes = np.asarray(jax.device_get(_arr(input_nodes)))
    eid_arr = (np.asarray(jax.device_get(_arr(eids)))
               if eids is not None else None)
    rng = np.random.default_rng()
    out_nb, out_cnt, out_eid = [], [], []
    for nid in nodes.tolist():
        beg, end = int(ptr[nid]), int(ptr[nid + 1])
        pos = np.arange(beg, end)
        if sample_size >= 0 and len(pos) > sample_size:
            pos = rng.choice(pos, size=sample_size, replace=False)
        out_nb.append(rows[pos])
        out_cnt.append(len(pos))
        if return_eids:
            out_eid.append(eid_arr[pos] if eid_arr is not None else pos)
    neighbors = np.concatenate(out_nb) if out_nb else np.zeros(0, np.int64)
    result = (Tensor(jnp.asarray(neighbors.astype(np.int64))),
              Tensor(jnp.asarray(np.array(out_cnt, np.int64))))
    if return_eids:
        sampled = (np.concatenate(out_eid) if out_eid
                   else np.zeros(0, np.int64))
        result = result + (Tensor(jnp.asarray(sampled.astype(np.int64))),)
    return result


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Multi-edge-type reindex (reference: geometric/reindex.py:139
    reindex_heter_graph): one shared id space across edge types — x's
    nodes first, then neighbors in edge-type order of first appearance."""
    xs = np.asarray(jax.device_get(_arr(x)))
    nbs = [np.asarray(jax.device_get(_arr(n))) for n in neighbors]
    cnts = [np.asarray(jax.device_get(_arr(c))) for c in count]
    order = {}
    for v in xs.tolist():
        order.setdefault(int(v), len(order))
    for nb in nbs:
        for v in nb.tolist():
            order.setdefault(int(v), len(order))
    src_parts = [np.array([order[int(v)] for v in nb], np.int64)
                 for nb in nbs]
    dst_parts = [np.repeat(np.arange(len(xs), dtype=np.int64), cnt)
                 for cnt in cnts]
    nodes = np.array(sorted(order, key=order.get), dtype=np.int64)
    return (Tensor(jnp.asarray(np.concatenate(src_parts))),
            Tensor(jnp.asarray(np.concatenate(dst_parts))),
            Tensor(jnp.asarray(nodes)))


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None,
                              return_eids=False, name=None):
    """Weight-proportional neighbor sampling without replacement over CSC
    (reference: geometric/sampling/neighbors.py:175
    weighted_sample_neighbors)."""
    rows = np.asarray(jax.device_get(_arr(row)))
    ptr = np.asarray(jax.device_get(_arr(colptr)))
    w = np.asarray(jax.device_get(_arr(edge_weight))).astype(np.float64)
    nodes = np.asarray(jax.device_get(_arr(input_nodes)))
    eid_arr = (np.asarray(jax.device_get(_arr(eids)))
               if eids is not None else None)
    rng = np.random.default_rng()
    out_nb, out_cnt, out_eid = [], [], []
    for nid in nodes.tolist():
        beg, end = int(ptr[nid]), int(ptr[nid + 1])
        pos = np.arange(beg, end)
        if sample_size >= 0 and len(pos) > sample_size:
            p = w[pos]
            p = p / p.sum() if p.sum() > 0 else None
            pos = rng.choice(pos, size=sample_size, replace=False, p=p)
        out_nb.append(rows[pos])
        out_cnt.append(len(pos))
        if return_eids:
            out_eid.append(eid_arr[pos] if eid_arr is not None else pos)
    neighbors = np.concatenate(out_nb) if out_nb else np.zeros(0, np.int64)
    result = (Tensor(jnp.asarray(neighbors.astype(np.int64))),
              Tensor(jnp.asarray(np.array(out_cnt, np.int64))))
    if return_eids:
        sampled = (np.concatenate(out_eid) if out_eid
                   else np.zeros(0, np.int64))
        result = result + (Tensor(jnp.asarray(sampled.astype(np.int64))),)
    return result
