"""`paddle_tpu.serving` — continuous-batching inference engine + fleet.

The single-shot entry points (`models.generation.generate`,
`inference.Predictor.run`) decode one fixed batch to completion.  This
package turns the compile-once decode step into a multi-tenant server:
a paged KV cache with shared-prefix reuse and chunked prefill
(`paged_kv`, the default) or fixed per-slot stripes (`kv_slots`), a
background scheduler with Orca-style continuous batching (`engine`),
admission control with bounded queueing and per-request deadlines
(`api`), serving metrics through `utils.monitor` (`stats`), and —
scaling past one process — replicated engines behind a drain-aware,
session-affine router that loses zero requests when a replica dies
(`router`, `fleet`).  See docs/SERVING.md.
"""
from __future__ import annotations

from .adapters import AdapterPool  # noqa: F401
from .api import (  # noqa: F401
    AdapterConfigError, DeadlineExceededError, EngineShutdownError,
    NoReplicaError, PageMigrationError, QueueFullError,
    RequestCancelledError, RequestOutput, SamplingParams,
    SchedulerStallError, ServingConfig, ServingError,
    UnknownAdapterError,
)
from .compiled_tick import (  # noqa: F401
    CompiledServingTick, TickFallbackWarning,
)
from .engine import Engine  # noqa: F401
from .fleet import ReplicaConfig, ReplicaServer, ServingFleet  # noqa: F401
from .kv_slots import SlotKVCache  # noqa: F401
from .paged_kv import PagedKVCache, PrefixTree  # noqa: F401
from .router import HashRing, RouterConfig, ServingRouter  # noqa: F401
from .stats import (  # noqa: F401
    reset_router_stats, reset_serving_stats, serving_stats,
)

__all__ = [
    "Engine", "ServingConfig", "SamplingParams", "RequestOutput",
    "CompiledServingTick", "TickFallbackWarning",
    "SlotKVCache", "PagedKVCache", "PrefixTree", "ServingError",
    "QueueFullError", "DeadlineExceededError", "EngineShutdownError",
    "SchedulerStallError", "NoReplicaError", "PageMigrationError",
    "RequestCancelledError",
    "AdapterConfigError", "UnknownAdapterError", "AdapterPool",
    "serving_stats", "reset_serving_stats", "reset_router_stats",
    "ServingRouter", "RouterConfig", "HashRing", "ServingFleet",
    "ReplicaServer", "ReplicaConfig",
]
