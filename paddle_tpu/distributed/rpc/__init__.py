from .rpc import (  # noqa: F401
    RAW_THRESHOLD, Blob, init_rpc, rpc_sync, rpc_async, shutdown,
    get_worker_info, get_all_worker_infos, get_current_worker_info,
    WorkerInfo, RpcServer, connect_worker, forget_worker,
)
