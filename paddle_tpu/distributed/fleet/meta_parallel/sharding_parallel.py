"""ShardingParallel wrapper (reference: fleet/meta_parallel/
sharding_parallel.py — ZeRO entry of distributed_model).

On TPU this commits ZeRO placements: params sharded over the sharding axis
(stage 3) or left replicated with sharded optimizer state (stages 1/2) —
see fleet.sharding for the layout story."""
from __future__ import annotations

from ....nn.layer import Layer
from ...mesh import get_mesh


class ShardingParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        from ..base import _commit_params
        stage = 1
        if strategy is not None:
            stage = int(getattr(strategy, "sharding_configs",
                                {}).get("stage", 1))
        mesh = get_mesh()
        if mesh is not None:
            _commit_params(layers, mesh,
                           shard_axis="sharding" if stage >= 3 else None)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)
