from .dataset import Dataset, IterableDataset, TensorDataset, Subset, ConcatDataset, random_split  # noqa: F401
from .sampler import Sampler, SequenceSampler, RandomSampler, BatchSampler, DistributedBatchSampler, WeightedRandomSampler  # noqa: F401
from .dataloader import DataLoader, DataLoaderTimeoutError, DataLoaderWarning, default_collate_fn  # noqa: F401
from .dataset import ChainDataset, ComposeDataset  # noqa: F401
from .worker_info import get_worker_info, WorkerInfo  # noqa: F401
