"""Host-mediated collectives: the CPU/gloo fallback lane.

Reference capability: ``ProcessGroupGloo`` — the reference serves CPU
processes a real collective backend when NCCL has no device to drive.
TPU-native realization: eager collectives normally compile INTO the XLA
program (`collective._multiproc_collective`), but some backends cannot
execute cross-process programs at all (jaxlib's CPU client raises
``Multiprocess computations aren't implemented``).  This module supplies
the same semantics at host level: every rank posts its contribution into
a shared KV store under ``{job}/hc/g{gid}/s{seq}/r{rank}``, polls for
its peers' contributions, stacks them, and derives the op result locally
(all_reduce = reduce over the stacked axis, all_to_all = transpose — the
same math `_multiproc_collective`'s XLA programs encode).

Two properties matter here beyond correctness:

- the poll loop is a *Python-level* blocking point, so the collective
  watchdog (`distributed/watchdog.py`) can abort a gather stuck on a
  dead peer with an async-raised `CollectiveTimeoutError`/
  `PeerFailureError` — unlike a C-blocked XLA transfer, which needs the
  watchdog's hard-abort escalation;
- the store is pluggable and defaults to whatever the job already has:
  the launch controllers' guardian store (``PADDLE_GUARDIAN_STORE`` /
  ``PADDLE_GUARDIAN_DIR``), falling back to the jax coordination
  service's KV (`CoordKVStore`) that every multi-controller job carries
  — which is per-incarnation by construction, so a relaunched job never
  reads a dead incarnation's stale contributions.

Selection: ``FLAGS_collective_backend`` = ``auto`` (XLA first, fall back
on the specific "multiprocess not implemented" failure) | ``xla`` |
``host``.
"""
from __future__ import annotations

import io
import os
import threading
import time

import numpy as np


class CoordKVStore:
    """TCPStore-shaped KV (set/get/list_prefix/delete_key) over the jax
    coordination-service client — the rendezvous channel
    ``jax.distributed.initialize`` already established, so host
    collectives and the error trap need no extra infrastructure."""

    def __init__(self, client):
        self._client = client

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        self._client.key_value_set_bytes(key, bytes(value),
                                         allow_overwrite=True)

    def get(self, key, default=None):
        try:
            return self._client.blocking_key_value_get_bytes(key, 1)
        except Exception:
            return default

    def list_prefix(self, prefix):
        try:
            pairs = self._client.key_value_dir_get_bytes(
                prefix.rstrip("/"))
        except Exception:
            return {}
        return {k: v for k, v in pairs if k.startswith(prefix)}

    def delete_key(self, key):
        try:
            self._client.key_value_delete(key)
        except Exception:
            pass

    def close(self):
        pass


def coord_kv_store():
    """The coordination-service KV, or None outside a multi-controller
    job."""
    try:
        from jax._src import distributed as _jd
        client = _jd.global_state.client
    except Exception:
        return None
    return CoordKVStore(client) if client is not None else None


def guardian_store():
    """The store the launch controller exported for the guardian, if
    any (shared with the error trap — one substrate, two protocols)."""
    endpoint = os.environ.get("PADDLE_GUARDIAN_STORE")
    root = os.environ.get("PADDLE_GUARDIAN_DIR")
    try:
        if endpoint:
            from .store import TCPStore
            host, port = endpoint.rsplit(":", 1)
            return TCPStore(host, int(port), timeout=20.0)
        if root:
            from .store import FileKVStore
            return FileKVStore(root)
    except Exception:
        return None
    return None


class HostCollectives:
    """One gather primitive; every collective derives from it."""

    def __init__(self, store, job="default"):
        self.store = store
        self.job = str(job)
        self._seq: dict[int, int] = {}
        self._lock = threading.Lock()

    def _key(self, gid, seq, rank):
        return f"{self.job}/hc/g{gid}/s{seq}/r{rank}"

    def gather(self, group, local, poll_s=0.005, rank=None):
        """Post this rank's array, block until every group member's
        contribution for the same per-group sequence number arrives,
        return them stacked ``[nranks, ...]`` in group order.

        ``rank`` overrides the ambient process index — launched workers
        that never initialize jax.distributed (the pickle/CPU lane, e.g.
        the elastic resize drill) pass their PADDLE_TRAINER_ID here.

        The wait polls in small sleeps — deliberately interpreter-level,
        so the collective watchdog can abort it when a peer is dead."""
        from . import env as _env
        gid = getattr(group, "id", 0)
        with self._lock:
            seq = self._seq.get(gid, 0)
            self._seq[gid] = seq + 1
        local = np.asarray(local)
        me = _env.get_rank() if rank is None else int(rank)
        buf = io.BytesIO()
        np.save(buf, local, allow_pickle=False)
        self.store.set(self._key(gid, seq, me), buf.getvalue())
        if seq >= 2:
            # a peer inside seq-1 has, by construction, consumed every
            # seq-2 contribution — reclaim ours (bounded store growth)
            self.store.delete_key(self._key(gid, seq - 2, me))
        parts: dict[int, np.ndarray] = {}
        while True:
            for idx, rank in enumerate(group.ranks):
                if idx in parts:
                    continue
                val = self.store.get(self._key(gid, seq, rank))
                if val is not None:
                    parts[idx] = np.load(io.BytesIO(val),
                                         allow_pickle=False)
            if len(parts) == group.nranks:
                return np.stack([parts[i]
                                 for i in range(group.nranks)])
            time.sleep(poll_s)


_HC = None
_HC_LOCK = threading.Lock()


def bootstrap():
    """Process-wide HostCollectives over the best available store, or
    None when the process has no shared substrate (single-process)."""
    global _HC
    with _HC_LOCK:
        if _HC is None:
            store = guardian_store() or coord_kv_store()
            if store is None:
                _HC = False
            else:
                _HC = HostCollectives(
                    store, job=os.environ.get("PADDLE_JOB_ID", "default"))
        return _HC or None


def reset():
    global _HC
    with _HC_LOCK:
        _HC = None
