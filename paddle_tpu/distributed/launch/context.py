"""Launch context: argument parsing + node/cluster description.

Reference capability: launch/context (reference:
python/paddle/distributed/launch/context/__init__.py — args, node info,
event loop) and the env-var contract PADDLE_TRAINER_* consumed by
fleet.init / init_parallel_env.
"""
from __future__ import annotations

import argparse
import os
import socket


def free_port():
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch distributed training "
                    "(reference: paddle.distributed.launch)")
    p.add_argument("--master", default=None,
                   help="coordinator ip:port (defaults to local free port);"
                        " with --nnodes it selects TCPStore rendezvous"
                        " (multi-pod elastic mode)")
    p.add_argument("--nnodes", default="1",
                   help="node count N, or elastic range MIN:MAX")
    p.add_argument("--node_rank", type=int, default=0,
                   help="in elastic mode only designates the store host"
                        " (rank 0); worker ranks come from rendezvous")
    p.add_argument("--pod_id", default=None,
                   help="stable pod identity for rendezvous ordering"
                        " (default: ip-pid)")
    p.add_argument("--elastic_quiet", type=float, default=1.0,
                   help="seconds membership must be stable before an"
                        " elastic rendezvous commits below MAX nodes")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--log_dir", default=None)
    p.add_argument("--job_id", default="default")
    p.add_argument("--devices", default=None,
                   help="visible device ids for each local process")
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--elastic_timeout", type=int, default=30)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class Context:
    def __init__(self, args=None, argv=None):
        self.args = args or parse_args(argv)
        self.node_ip = os.environ.get("POD_IP", "127.0.0.1")

    def nnodes_range(self):
        """(min, max) node count; `--nnodes 2` → (2, 2), `1:4` → (1, 4)."""
        spec = str(self.args.nnodes)
        if ":" in spec:
            lo, hi = spec.split(":", 1)
            return int(lo), int(hi)
        n = int(spec)
        return n, n

    def world_size(self):
        return self.nnodes_range()[0] * self.args.nproc_per_node

    def global_rank(self, local_rank):
        return self.args.node_rank * self.args.nproc_per_node + local_rank

    def proc_env(self, local_rank, master, rank=None, world=None):
        """The PADDLE_TRAINER_* contract + JAX multi-controller vars.
        `rank`/`world` override the static node_rank arithmetic when a
        rendezvous assigned them (elastic mode)."""
        if rank is None:
            rank = self.global_rank(local_rank)
        if world is None:
            world = self.world_size()
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_MASTER": master,
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_JOB_ID": self.args.job_id,
            "RANK": str(rank),
            "WORLD_SIZE": str(world),
            "COORDINATOR_ADDRESS": master,
        })
        # workers must import paddle_tpu even when the package is not
        # pip-installed (scripts get only their own dir on sys.path)
        import paddle_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(paddle_tpu.__file__)))
        existing = env.get("PYTHONPATH")
        if not existing:
            # unset OR empty-string: plain pkg_root (appending os.pathsep
            # to "" would add a trailing empty entry = cwd on sys.path)
            env["PYTHONPATH"] = pkg_root
        elif pkg_root not in existing.split(os.pathsep):
            # preserve the original verbatim (empty entries mean cwd)
            env["PYTHONPATH"] = pkg_root + os.pathsep + existing
        return env
