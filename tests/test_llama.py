"""Llama model family (reference capability: PaddleNLP Llama over Fleet;
BASELINE.md config 4).  Pattern: parallel-vs-serial numerics like
test/collective/fleet/ hybrid tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import LlamaForCausalLM, llama_config
from paddle_tpu.models.llama import _repeat_kv


def _ids(b=2, s=64, vocab=512, seed=0):
    return paddle.to_tensor(
        np.random.default_rng(seed).integers(0, vocab, (b, s))
        .astype("int32"))


def test_eager_trains():
    paddle.seed(0)
    m = LlamaForCausalLM(llama_config("tiny"))
    opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
    ids = _ids()
    losses = []
    for _ in range(4):
        _, loss = m(ids, labels=ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


def test_gqa_repeat_kv():
    x = paddle.to_tensor(
        np.arange(2 * 3 * 2 * 4, dtype=np.float32).reshape(2, 3, 2, 4))
    y = _repeat_kv(x, 3)
    assert tuple(y.shape) == (2, 3, 6, 4)
    xn = np.asarray(x._data_)
    yn = np.asarray(y._data_)
    for rep in range(3):
        np.testing.assert_allclose(yn[:, :, rep], xn[:, :, 0])
        np.testing.assert_allclose(yn[:, :, 3 + rep], xn[:, :, 1])


def test_gqa_matches_mha_when_equal_heads():
    """num_kv_heads == num_heads must reduce to plain MHA paths."""
    paddle.seed(1)
    cfg = llama_config("tiny", num_kv_heads=4)   # == num_heads
    m = LlamaForCausalLM(cfg)
    out = m(_ids())
    assert tuple(out.shape) == (2, 64, 512)


def test_to_static_parity():
    paddle.seed(2)
    m = LlamaForCausalLM(llama_config("tiny"))
    ids = _ids(seed=3)
    eager = m(ids)

    @paddle.jit.to_static
    def fwd(x):
        return m(x)

    compiled = fwd(ids)
    np.testing.assert_allclose(np.asarray(eager._data_),
                               np.asarray(compiled._data_), atol=1e-4)


def test_parallel_llama_matches_serial():
    """dp4×mp2 hybrid llama numerics vs the serial model (same params)."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import ParallelLlamaForCausalLM

    s = fleet.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 4, "mp_degree": 2}
    fleet.init(is_collective=True, strategy=s)

    # tied embeddings on both sides so the parameter lists align 1:1
    cfg = llama_config("tiny", tie_word_embeddings=True)
    paddle.seed(7)
    sm = LlamaForCausalLM(cfg)
    paddle.seed(7)
    pm = ParallelLlamaForCausalLM(cfg)
    for p_t, p_s in zip(pm.parameters(), sm.parameters()):
        p_t.set_value(p_s.numpy())
    fleet.distributed_model(pm)
    ids = _ids(b=4, seed=5)
    _, ploss = pm(ids, labels=ids)
    _, sloss = sm(ids, labels=ids)
    np.testing.assert_allclose(float(ploss.numpy()), float(sloss.numpy()),
                               rtol=2e-3)


def test_parallel_llama_untied_head():
    """Default Llama-2 config is untied — the parallel model must carry a
    separate (vocab-sharded) lm_head like the serial one."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models import ParallelLlamaForCausalLM
    s = fleet.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 4, "mp_degree": 2}
    fleet.init(is_collective=True, strategy=s)
    cfg = llama_config("tiny")          # tie_word_embeddings=False
    pm = ParallelLlamaForCausalLM(cfg)
    assert pm.lm_head is not None
    sm = LlamaForCausalLM(cfg)
    assert len(list(pm.parameters())) == len(list(sm.parameters()))
    for p_t, p_s in zip(pm.parameters(), sm.parameters()):
        p_t.set_value(p_s.numpy())
    fleet.distributed_model(pm)
    ids = _ids(b=4, seed=9)
    _, ploss = pm(ids, labels=ids)
    _, sloss = sm(ids, labels=ids)
    np.testing.assert_allclose(float(ploss.numpy()), float(sloss.numpy()),
                               rtol=2e-3)
