"""hapi callbacks (reference capability: python/paddle/hapi/callbacks.py —
ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler hooks)."""
from __future__ import annotations

import os
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model=None, params=None):
        self.callbacks = list(callbacks)
        for cb in self.callbacks:
            cb.set_model(model)
            cb.set_params(params)

    def call(self, hook, *args, **kwargs):
        for cb in self.callbacks:
            getattr(cb, hook)(*args, **kwargs)


class ProgBarLogger(Callback):
    """reference: callbacks.py ProgBarLogger — per-epoch line logging."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                               else f"{k}: {v}"
                               for k, v in (logs or {}).items())
            print(f"step {step + 1}/{self.steps or '?'} - {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                               else f"{k}: {v}"
                               for k, v in (logs or {}).items())
            print(f"epoch {epoch + 1} done in {dt:.1f}s - {items}")


class ModelCheckpoint(Callback):
    """reference: callbacks.py ModelCheckpoint — periodic save."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """reference: callbacks.py EarlyStopping."""

    def __init__(self, monitor="loss", mode="auto", patience=0,
                 verbose=1, min_delta=0, baseline=None,
                 save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.stopped = False
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda a, b: a > b + self.min_delta
            self.best = float("-inf")
        else:
            self.better = lambda a, b: a < b - self.min_delta
            self.best = float("inf")
        self.wait = 0

    def on_eval_end(self, logs=None):
        val = (logs or {}).get(self.monitor)
        if val is None:
            return
        if isinstance(val, (list, tuple)):
            val = val[0]
        if self.better(val, self.best):
            self.best = val
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stopped = True
                self.model.stop_training = True


class LRScheduler(Callback):
    """reference: callbacks.py LRScheduler — steps the optimizer's
    LRScheduler each epoch (or batch)."""

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s:
            s.step()


def config_callbacks(callbacks, model, epochs=None, steps=None,
                     verbose=2, save_freq=1, save_dir=None, metrics=None):
    cbs = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbs):
        cbs.insert(0, ProgBarLogger(verbose=verbose))
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbs):
        cbs.append(ModelCheckpoint(save_freq, save_dir))
    cl = CallbackList(cbs, model=model,
                      params={"epochs": epochs, "steps": steps,
                              "verbose": verbose, "metrics": metrics or []})
    return cl
