"""Geometric package (reference: python/paddle/geometric/ +
test/legacy_test/test_graph_send_recv_op.py numpy-reference pattern)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import geometric as G


def _np(t):
    return np.asarray(t._data_)


def test_segment_ops():
    data = paddle.to_tensor(np.array(
        [[1., 2.], [3., 4.], [5., 6.], [7., 8.]], np.float32))
    ids = paddle.to_tensor(np.array([0, 0, 1, 2], np.int32))
    np.testing.assert_allclose(_np(G.segment_sum(data, ids)),
                               [[4., 6.], [5., 6.], [7., 8.]])
    np.testing.assert_allclose(_np(G.segment_mean(data, ids)),
                               [[2., 3.], [5., 6.], [7., 8.]])
    np.testing.assert_allclose(_np(G.segment_max(data, ids)),
                               [[3., 4.], [5., 6.], [7., 8.]])
    np.testing.assert_allclose(_np(G.segment_min(data, ids)),
                               [[1., 2.], [5., 6.], [7., 8.]])


def test_segment_sum_grad():
    data = paddle.to_tensor(np.ones((4, 2), np.float32))
    data.stop_gradient = False
    ids = paddle.to_tensor(np.array([0, 0, 1, 1], np.int32))
    out = G.segment_sum(data, ids)
    (out * paddle.to_tensor(np.array([[1.], [10.]], np.float32))).sum() \
        .backward()
    np.testing.assert_allclose(_np(data.grad),
                               [[1., 1.], [1., 1.], [10., 10.], [10., 10.]])


@pytest.mark.parametrize("reduce_op,expect", [
    ("sum", [[4., 6.], [1., 2.], [0., 0.]]),
    ("mean", [[2., 3.], [1., 2.], [0., 0.]]),
    ("max", [[3., 4.], [1., 2.], [0., 0.]]),
])
def test_send_u_recv(reduce_op, expect):
    x = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.]],
                                  np.float32))
    src = paddle.to_tensor(np.array([0, 1, 0], np.int32))
    dst = paddle.to_tensor(np.array([0, 0, 1], np.int32))
    out = G.send_u_recv(x, src, dst, reduce_op=reduce_op)
    np.testing.assert_allclose(_np(out), expect)


def test_send_ue_recv_and_send_uv():
    x = paddle.to_tensor(np.array([[1., 1.], [2., 2.]], np.float32))
    e = paddle.to_tensor(np.array([[0.5, 0.5], [1., 1.]], np.float32))
    src = paddle.to_tensor(np.array([0, 1], np.int32))
    dst = paddle.to_tensor(np.array([1, 0], np.int32))
    out = G.send_ue_recv(x, e, src, dst, message_op="mul", reduce_op="sum")
    np.testing.assert_allclose(_np(out), [[2., 2.], [0.5, 0.5]])
    uv = G.send_uv(x, x, src, dst, message_op="add")
    np.testing.assert_allclose(_np(uv), [[3., 3.], [3., 3.]])


def test_send_u_recv_grad_flows():
    x = paddle.to_tensor(np.ones((3, 2), np.float32))
    x.stop_gradient = False
    src = paddle.to_tensor(np.array([0, 1, 2], np.int32))
    dst = paddle.to_tensor(np.array([0, 0, 1], np.int32))
    G.send_u_recv(x, src, dst).sum().backward()
    assert x.grad is not None
    np.testing.assert_allclose(_np(x.grad), np.ones((3, 2)))


def test_reindex_graph():
    x = paddle.to_tensor(np.array([10, 5], np.int64))
    neighbors = paddle.to_tensor(np.array([3, 10, 5, 7], np.int64))
    count = paddle.to_tensor(np.array([2, 2], np.int64))
    src, dst, nodes = G.reindex_graph(x, neighbors, count)
    # nodes: x first (10→0, 5→1), then new neighbors (3→2, 7→3)
    np.testing.assert_array_equal(_np(nodes), [10, 5, 3, 7])
    np.testing.assert_array_equal(_np(src), [2, 0, 1, 3])
    np.testing.assert_array_equal(_np(dst), [0, 0, 1, 1])


def test_sample_neighbors():
    # CSC graph: node0 ← {1,2,3}, node1 ← {0}
    row = paddle.to_tensor(np.array([1, 2, 3, 0], np.int64))
    colptr = paddle.to_tensor(np.array([0, 3, 4], np.int64))
    nodes = paddle.to_tensor(np.array([0, 1], np.int64))
    nb, cnt = G.sample_neighbors(row, colptr, nodes, sample_size=2)
    assert _np(cnt).tolist() == [2, 1]
    assert set(_np(nb)[:2].tolist()) <= {1, 2, 3}
    assert _np(nb)[2] == 0
    # full sampling
    nb2, cnt2 = G.sample_neighbors(row, colptr, nodes, sample_size=-1)
    assert _np(cnt2).tolist() == [3, 1]
