"""Global framework state: grad mode, default dtype, RNG, trace mode.

The reference keeps equivalent state in C++ singletons (tracer state in
paddle/fluid/imperative/tracer.h, AMP state in eager_amp_auto_cast.h).  Here it
is a small thread-local Python object; the performance path does not consult it
per-op inside compiled programs.
"""
from __future__ import annotations

import contextlib
import threading

import jax

from . import dtype as _dtype


class _FrameworkState(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.default_dtype = _dtype.float32
        # amp: None | ("O1"|"O2", compute_dtype)
        self.amp_level = "O0"
        self.amp_dtype = _dtype.bfloat16
        self.amp_custom_white_list = set()
        self.amp_custom_black_list = set()
        # RNG: a JAX PRNG key + a split counter. Under trace (to_static), the
        # tracer installs a symbolic base key so dropout masks differ per step.
        self.rng_key = jax.random.PRNGKey(0)
        self.rng_counter = 0
        # trace mode (set by paddle_tpu.jit tracer while tracing)
        self.tracer = None
        # active ops/flops.FlopsCounter (profiler MFU accounting)
        self.flops_counter = None


STATE = _FrameworkState()


def seed(s: int):
    """Set the global random seed (reference: paddle.seed)."""
    STATE.rng_key = jax.random.PRNGKey(s)
    STATE.rng_counter = 0
    return s


def next_rng_key():
    """Return a fresh PRNG key. Cheap fold_in instead of split-chain so the
    traced form is a pure function of (base_key, python counter)."""
    tr = STATE.tracer
    if tr is not None:
        base = tr.rng_base()
        key = jax.random.fold_in(base, tr.rng_counter)
        tr.rng_counter += 1
        return key
    key = jax.random.fold_in(STATE.rng_key, STATE.rng_counter)
    STATE.rng_counter += 1
    return key


def grad_enabled() -> bool:
    return STATE.grad_enabled


@contextlib.contextmanager
def no_grad():
    prev = STATE.grad_enabled
    STATE.grad_enabled = False
    try:
        yield
    finally:
        STATE.grad_enabled = prev


@contextlib.contextmanager
def enable_grad():
    prev = STATE.grad_enabled
    STATE.grad_enabled = True
    try:
        yield
    finally:
        STATE.grad_enabled = prev


def set_default_dtype(d):
    STATE.default_dtype = _dtype.convert_dtype(d)


def get_default_dtype():
    return STATE.default_dtype
