"""Fleet datasets + monitor counters + structured log (reference:
fleet/dataset/dataset.py, platform/monitor.cc, fleet/utils/log_util.py)."""
import numpy as np

from paddle_tpu.distributed.fleet.dataset import (
    InMemoryDataset, QueueDataset,
)
from paddle_tpu.utils import monitor
from paddle_tpu.utils.log import get_logger, log_every_n, set_log_level


def _write_files(tmp_path, n_files=3, rows=5):
    files = []
    v = 0
    for i in range(n_files):
        p = tmp_path / f"part-{i}.txt"
        lines = []
        for _ in range(rows):
            lines.append(f"{v} {v + 0.5}")
            v += 1
        p.write_text("\n".join(lines) + "\n")
        files.append(str(p))
    return files


def test_in_memory_dataset(tmp_path):
    files = _write_files(tmp_path)
    ds = InMemoryDataset()
    ds.init(batch_size=4)
    ds.set_filelist(files)
    n = ds.load_into_memory()
    assert n == 15
    batches = list(ds)
    assert len(batches) == 4          # 4+4+4+3
    assert batches[0].shape == (4, 2)
    total = np.concatenate([b for b in batches])
    assert total.shape == (15, 2)

    ds.set_shuffle_seed(1)
    before = np.concatenate(list(ds))
    ds.local_shuffle()
    after = np.concatenate(list(ds))
    assert sorted(before[:, 0].tolist()) == sorted(after[:, 0].tolist())
    assert not np.array_equal(before, after)
    ds.release_memory()


def test_file_split_across_workers(tmp_path):
    files = _write_files(tmp_path, n_files=4)
    ds = InMemoryDataset()
    ds.init(batch_size=100)
    ds.set_filelist(files)
    n0 = ds.load_into_memory(worker_id=0, worker_num=2)
    all0 = np.concatenate(list(ds))
    n1 = ds.load_into_memory(worker_id=1, worker_num=2)
    all1 = np.concatenate(list(ds))
    assert n0 + n1 == 20
    # disjoint coverage
    assert not set(all0[:, 0].tolist()) & set(all1[:, 0].tolist())


def test_queue_dataset_streams(tmp_path):
    files = _write_files(tmp_path, n_files=2)
    ds = QueueDataset()
    ds.init(batch_size=3)
    ds.set_filelist(files)
    batches = list(iter(ds))
    assert sum(b.shape[0] for b in batches) == 10


def test_custom_parse_fn(tmp_path):
    p = tmp_path / "labeled.txt"
    p.write_text("1,2,0\n3,4,1\n")
    ds = InMemoryDataset()
    ds.init(batch_size=2)
    ds.set_filelist([str(p)])

    def parse(line):
        *feat, label = line.split(",")
        return (np.array([float(f) for f in feat], np.float32),
                np.int64(label))

    ds.set_parse_func(parse)
    ds.load_into_memory()
    x, y = next(iter(ds))
    assert x.shape == (2, 2) and y.tolist() == [0, 1]


def test_monitor_counters():
    monitor.reset()
    monitor.incr("test.a")
    monitor.incr("test.a", 4)
    monitor.set_value("test.b", 7.5)
    assert monitor.get_monitor_value("test.a") == 5
    assert monitor.all_stats()["test.b"] == 7.5
    monitor.reset("test.a")
    assert monitor.get_monitor_value("test.a") == 0


def test_jit_counters_increment():
    import paddle_tpu as paddle
    monitor.reset()

    @paddle.jit.to_static
    def f(x):
        return x * 2

    x = paddle.to_tensor(np.ones(4, np.float32))
    f(x)   # warmup
    f(x)   # discovery
    f(x)   # compiled
    f(x)   # compiled
    assert monitor.get_monitor_value("jit.cache_miss") >= 1
    assert monitor.get_monitor_value("jit.cache_hit") >= 2


def test_logger_rank_stamped(capsys):
    set_log_level("INFO")
    log = get_logger()
    log.info("hello from the framework")
    err = capsys.readouterr().err
    assert "rank" in err and "hello from the framework" in err
    for _ in range(5):
        log_every_n("info", "repeated message", n=100)
    err = capsys.readouterr().err
    assert err.count("repeated message") == 1


def test_fleet_utils_fs(tmp_path):
    """LocalFS surface (reference: fleet/utils/fs.py) + gated HDFS."""
    import pytest
    from paddle_tpu.distributed.fleet.utils import (
        LocalFS, HDFSClient, ExecuteError, FSFileExistsError)
    fs = LocalFS()
    d = tmp_path / "a"
    fs.mkdirs(str(d))
    fs.touch(str(d / "x.txt"))
    (d / "sub").mkdir()
    dirs, files = fs.ls_dir(str(d))
    assert dirs == ["sub"] and files == ["x.txt"]
    assert fs.is_file(str(d / "x.txt")) and fs.is_dir(str(d / "sub"))
    fs.mv(str(d / "x.txt"), str(d / "y.txt"))
    assert fs.is_exist(str(d / "y.txt"))
    with pytest.raises(FSFileExistsError):
        fs.touch(str(d / "y.txt"), exist_ok=False)
    fs.upload(str(d / "y.txt"), str(tmp_path / "copy.txt"))
    assert fs.is_file(str(tmp_path / "copy.txt"))
    fs.delete(str(d))
    assert not fs.is_exist(str(d))
    assert not fs.need_upload_download()

    h = HDFSClient()          # constructible, ops gated
    with pytest.raises(ExecuteError, match="no hadoop"):
        h.ls_dir("/tmp")


def test_distributed_infer_pulls_ps_tables():
    import numpy as np
    from paddle_tpu.distributed.ps import TheOnePSRuntime, PSClient
    from paddle_tpu.distributed.fleet.utils import DistributedInfer
    cfg = {"tables": {0: {"type": "sparse", "dim": 3, "lr": 1.0}}}
    rt = TheOnePSRuntime("server", cfg)
    rt.init_server()
    client = PSClient(rt.server_address)
    try:
        rows = client.pull_sparse(0, [4, 9])
        di = DistributedInfer()
        di.init_distributed_infer_env(client=client, table_ids=[0])
        local = di.local_rows(0)
        np.testing.assert_allclose(local[4], rows[0])
        np.testing.assert_allclose(local[9], rows[1])
        # dirname path: pickled save-state loads without live servers
        import pickle, tempfile, os
        with tempfile.NamedTemporaryFile(suffix=".pkl",
                                         delete=False) as f:
            pickle.dump(client.save(), f)
        di2 = DistributedInfer()
        di2.init_distributed_infer_env(dirname=f.name, table_ids=[0])
        np.testing.assert_allclose(di2.local_rows(0)[4], rows[0])
        os.unlink(f.name)
    finally:
        client.stop_server()
        client.close()
        rt.stop()
