"""Profiler summary tables (reference capability:
python/paddle/profiler/profiler_statistic.py — Overview / Operator /
UserDefined summaries with per-name call counts, CPU+device time
total/avg/max/min, ratio columns, sorted by a SortedKeys criterion).

The data comes from the host span buffer the dispatch funnel fills while
a profiler records (cat="Operator", with analytic FLOPs and optional
device-complete durations) plus user RecordEvent spans and ProfileStep
step spans."""
from __future__ import annotations

from enum import Enum


class SortedKeys(Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5


class _Agg:
    __slots__ = ("calls", "total", "mx", "mn", "dev_total", "dev_mx",
                 "dev_mn", "dev_calls", "flops")

    def __init__(self):
        self.calls = 0
        self.total = 0.0
        self.mx = 0.0
        self.mn = float("inf")
        self.dev_total = 0.0
        self.dev_mx = 0.0
        self.dev_mn = float("inf")
        self.dev_calls = 0
        self.flops = 0

    def add(self, dur, dev_dur=None, flops=None):
        self.calls += 1
        self.total += dur
        self.mx = max(self.mx, dur)
        self.mn = min(self.mn, dur)
        if dev_dur is not None:
            self.dev_calls += 1
            self.dev_total += dev_dur
            self.dev_mx = max(self.dev_mx, dev_dur)
            self.dev_mn = min(self.dev_mn, dev_dur)
        if flops:
            self.flops += flops


def _collect(events):
    """Split events into (ops, user, steps) per-name aggregates."""
    ops, user, steps = {}, {}, _Agg()
    for ev in events:
        dur = ev.get("dur", 0.0)
        cat = ev.get("cat", "")
        args = ev.get("args") or {}
        if cat == "Operator":
            ops.setdefault(ev["name"], _Agg()).add(
                dur, args.get("device_dur"), args.get("flops"))
        elif cat == "ProfileStep" or ev["name"].startswith("ProfileStep"):
            steps.add(dur)
        else:
            user.setdefault(ev["name"], _Agg()).add(dur)
    return ops, user, steps


_SORT = {
    SortedKeys.CPUTotal: lambda a: -a.total,
    SortedKeys.CPUAvg: lambda a: -(a.total / max(a.calls, 1)),
    SortedKeys.CPUMax: lambda a: -a.mx,
    SortedKeys.CPUMin: lambda a: -(a.mn if a.calls else 0.0),
    SortedKeys.GPUTotal: lambda a: -a.dev_total,
    SortedKeys.GPUAvg: lambda a: -(a.dev_total / max(a.dev_calls, 1)),
}


def _fmt(us, scale):
    return f"{us * scale:.3f}"


def _table(title, rows, header, widths):
    total_w = sum(widths)
    out = ["", f"{('-' * 20)}{title}{('-' * 20)}".center(total_w), ""]
    out.append("".join(h.ljust(w) if i == 0 else h.rjust(w)
                       for i, (h, w) in enumerate(zip(header, widths))))
    out.append("-" * total_w)
    for row in rows:
        out.append("".join(
            str(c)[:widths[0] - 1].ljust(w) if i == 0
            else str(c).rjust(w)
            for i, (c, w) in enumerate(zip(row, widths))))
    return out


def summary(prof, time_unit="ms", sorted_by=SortedKeys.CPUTotal,
            op_detail=True):
    """Reference-style multi-section report: Overview, Operator Summary
    (calls / CPU total,avg,max,min / ratio / device time / GFLOPs),
    UserDefined Summary."""
    scale = {"s": 1e-6, "ms": 1e-3, "us": 1.0}[time_unit]
    sorted_by = sorted_by or SortedKeys.CPUTotal
    ops, user, steps = _collect(prof.events)
    lines = [f"Time unit: {time_unit}"]

    # ---- Overview ----
    op_total = sum(a.total for a in ops.values())
    dev_total = sum(a.dev_total for a in ops.values())
    user_total = sum(a.total for a in user.values())
    rows = []
    if steps.calls:
        rows.append(("ProfileStep", steps.calls, _fmt(steps.total, scale),
                     _fmt(steps.total / max(steps.calls, 1), scale)))
    rows.append(("Operator", sum(a.calls for a in ops.values()),
                 _fmt(op_total, scale),
                 _fmt(op_total / max(sum(a.calls for a in ops.values()), 1),
                      scale)))
    if user:
        rows.append(("UserDefined", sum(a.calls for a in user.values()),
                     _fmt(user_total, scale),
                     _fmt(user_total /
                          max(sum(a.calls for a in user.values()), 1),
                          scale)))
    lines += _table("Overview Summary", rows,
                    ("Event Type", "Calls", "Total", "Avg"),
                    (24, 10, 14, 12))

    # ---- Operator Summary ----
    if op_detail and ops:
        key = _SORT[sorted_by]
        rows = []
        for name, a in sorted(ops.items(), key=lambda kv: key(kv[1])):
            ratio = 100.0 * a.total / op_total if op_total else 0.0
            rows.append((
                name, a.calls, _fmt(a.total, scale),
                _fmt(a.total / max(a.calls, 1), scale),
                _fmt(a.mx, scale),
                _fmt(a.mn if a.calls else 0.0, scale),
                f"{ratio:.2f}",
                _fmt(a.dev_total, scale) if a.dev_calls else "-",
                (_fmt(a.dev_total / a.dev_calls, scale)
                 if a.dev_calls else "-"),
                f"{a.flops / 1e9:.3f}" if a.flops else "-",
            ))
        lines += _table(
            "Operator Summary", rows,
            ("Name", "Calls", "CPU Total", "Avg", "Max", "Min",
             "Ratio(%)", "Dev Total", "Dev Avg", "GFLOPs"),
            (26, 7, 11, 9, 9, 9, 9, 11, 9, 10))

    # ---- UserDefined Summary ----
    if user:
        rows = []
        for name, a in sorted(user.items(), key=lambda kv: -kv[1].total):
            rows.append((name, a.calls, _fmt(a.total, scale),
                         _fmt(a.total / max(a.calls, 1), scale),
                         _fmt(a.mx, scale),
                         _fmt(a.mn if a.calls else 0.0, scale)))
        lines += _table("UserDefined Summary", rows,
                        ("Name", "Calls", "Total", "Avg", "Max", "Min"),
                        (28, 8, 12, 10, 10, 10))
    return "\n".join(lines)
