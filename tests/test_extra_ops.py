"""Long-tail op pack + inplace variants (reference: the paddle.* symbols
exported by python/paddle/__init__.py __all__; OpTest-style numpy
reference checks per SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def T(a):
    return paddle.to_tensor(np.asarray(a))


def test_top_level_all_parity():
    """Every symbol in the reference's top-level __all__ exists here."""
    import re
    ref = open("/root/reference/python/paddle/__init__.py").read()
    ref_all = set(re.findall(
        r"'([^']+)'", re.search(r"__all__ = \[(.*?)\]", ref, re.S).group(1)))
    missing = sorted(s for s in ref_all
                     if not hasattr(paddle, s) and s != "DataParallel")
    assert missing == [], f"top-level API gaps: {missing}"
    assert paddle.DataParallel is not None  # lazy __getattr__


def test_math_extras_match_numpy():
    x = np.linspace(0.5, 2.0, 7).astype(np.float32)
    np.testing.assert_allclose(paddle.asinh(T(x)).numpy(), np.arcsinh(x),
                               rtol=1e-6)
    np.testing.assert_allclose(paddle.acosh(T(1 + x)).numpy(),
                               np.arccosh(1 + x), rtol=1e-6)
    np.testing.assert_allclose(paddle.atanh(T(x / 4)).numpy(),
                               np.arctanh(x / 4), rtol=1e-6)
    np.testing.assert_allclose(paddle.logaddexp(T(x), T(2 * x)).numpy(),
                               np.logaddexp(x, 2 * x), rtol=1e-6)
    import scipy.special as sp
    np.testing.assert_allclose(paddle.digamma(T(x)).numpy(), sp.digamma(x),
                               rtol=1e-5)
    np.testing.assert_allclose(paddle.lgamma(T(x)).numpy(), sp.gammaln(x),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(paddle.i0(T(x)).numpy(), sp.i0(x), rtol=1e-5)
    np.testing.assert_allclose(paddle.i1e(T(x)).numpy(), sp.i1e(x),
                               rtol=1e-5)


def test_addmm_and_mm():
    a = np.ones((2, 2), np.float32)
    x = np.arange(4, dtype=np.float32).reshape(2, 2)
    y = np.eye(2, dtype=np.float32)
    out = paddle.addmm(T(a), T(x), T(y), beta=0.5, alpha=2.0)
    np.testing.assert_allclose(out.numpy(), 0.5 * a + 2.0 * x)
    np.testing.assert_allclose(paddle.mm(T(x), T(y)).numpy(), x)


def test_cdist():
    x = np.zeros((3, 4), np.float32)
    y = np.ones((2, 4), np.float32)
    np.testing.assert_allclose(paddle.cdist(T(x), T(y)).numpy(),
                               np.full((3, 2), 2.0), rtol=1e-6)
    np.testing.assert_allclose(
        paddle.cdist(T(x), T(y), p=1.0).numpy(), np.full((3, 2), 4.0),
        rtol=1e-6)


def test_cummin_cummax_indices():
    x = np.array([3.0, 1.0, 2.0, 0.5, 4.0], np.float32)
    v, i = paddle.cummin(T(x))
    np.testing.assert_allclose(v.numpy(), np.minimum.accumulate(x))
    np.testing.assert_array_equal(i.numpy(), [0, 1, 1, 3, 3])
    v, i = paddle.cummax(T(x))
    np.testing.assert_allclose(v.numpy(), np.maximum.accumulate(x))
    np.testing.assert_array_equal(i.numpy(), [0, 0, 0, 0, 4])


def test_logcumsumexp():
    x = np.array([0.1, 0.5, 2.0, -1.0], np.float32)
    ref = np.log(np.cumsum(np.exp(x)))
    np.testing.assert_allclose(paddle.logcumsumexp(T(x)).numpy(), ref,
                               rtol=1e-5)


def test_nan_reductions():
    x = np.array([[1.0, np.nan, 3.0], [np.nan, 5.0, 6.0]], np.float32)
    np.testing.assert_allclose(paddle.nanmedian(T(x)).numpy(),
                               np.nanmedian(x))
    np.testing.assert_allclose(
        paddle.nanquantile(T(x), 0.5, axis=1).numpy(),
        np.nanquantile(x, 0.5, axis=1))


def test_take_flat_semantics():
    x = np.arange(6).reshape(2, 3)
    idx = np.array([[0, 5], [-1, -6]])
    out = paddle.take(T(x), T(idx))
    np.testing.assert_array_equal(out.numpy(), [[0, 5], [5, 0]])
    out = paddle.take(T(x), T(np.array([7, -8])), mode="wrap")
    np.testing.assert_array_equal(out.numpy(), [1, 4])


def test_shape_manip_extras():
    x = np.arange(24).reshape(2, 12).astype(np.float32)
    np.testing.assert_array_equal(
        paddle.unflatten(T(x), 1, [3, 4]).numpy(), x.reshape(2, 3, 4))
    parts = paddle.unstack(T(x), axis=0)
    assert len(parts) == 2
    np.testing.assert_array_equal(parts[1].numpy(), x[1])
    vs = paddle.vsplit(T(x), 2)
    np.testing.assert_array_equal(vs[0].numpy(), x[:1])
    np.testing.assert_array_equal(
        paddle.view(T(x), [4, 6]).numpy(), x.reshape(4, 6))
    np.testing.assert_array_equal(
        paddle.view_as(T(x), T(np.zeros((6, 4)))).numpy(), x.reshape(6, 4))
    np.testing.assert_array_equal(
        paddle.as_strided(T(x.reshape(-1)), [2, 3], [12, 1]).numpy(),
        x.reshape(-1)[np.arange(2)[:, None] * 12 + np.arange(3)])
    np.testing.assert_array_equal(
        paddle.crop(T(x), shape=[1, 3], offsets=[1, 2]).numpy(),
        x[1:2, 2:5])


def test_unique_consecutive():
    x = np.array([1, 1, 2, 2, 2, 3, 1, 1])
    out, inv, counts = paddle.unique_consecutive(
        T(x), return_inverse=True, return_counts=True)
    np.testing.assert_array_equal(out.numpy(), [1, 2, 3, 1])
    np.testing.assert_array_equal(counts.numpy(), [2, 3, 1, 2])
    np.testing.assert_array_equal(inv.numpy(), [0, 0, 1, 1, 1, 2, 3, 3])


def test_trapezoid():
    y = np.array([1.0, 2.0, 3.0], np.float32)
    np.testing.assert_allclose(paddle.trapezoid(T(y)).numpy(), 4.0)
    np.testing.assert_allclose(
        paddle.cumulative_trapezoid(T(y)).numpy(), [1.5, 4.0])


def test_renorm():
    x = np.array([[3.0, 4.0], [0.3, 0.4]], np.float32)
    out = paddle.renorm(T(x), p=2.0, axis=0, max_norm=1.0).numpy()
    np.testing.assert_allclose(np.linalg.norm(out[0]), 1.0, rtol=1e-5)
    np.testing.assert_allclose(out[1], x[1], rtol=1e-6)  # under the cap


def test_shard_index():
    lbl = np.array([0, 5, 9, 13])
    out = paddle.shard_index(T(lbl), index_num=16, nshards=2, shard_id=1)
    np.testing.assert_array_equal(out.numpy(), [-1, -1, 1, 5])


def test_utility_surface():
    x = T(np.ones((2, 3), np.float32))
    assert paddle.is_tensor(x) and not paddle.is_tensor(5)
    assert paddle.is_floating_point(x) and not paddle.is_integer(x)
    assert int(paddle.numel(x)) == 6 and int(paddle.rank(x)) == 2
    np.testing.assert_array_equal(paddle.shape(x).numpy(), [2, 3])
    assert paddle.tolist(x) == [[1.0, 1.0, 1.0], [1.0, 1.0, 1.0]]
    assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]
    p = paddle.create_parameter([3, 4], "float32")
    assert list(p.shape) == [3, 4] and not p.stop_gradient
    st = paddle.get_rng_state()
    paddle.set_rng_state(st)
    with paddle.LazyGuard():
        pass
    repr(paddle.CPUPlace()), repr(paddle.CUDAPlace(0))


def test_inplace_variants_grad_and_leaf_protection():
    t = paddle.to_tensor(np.full(3, 2.0, np.float32))
    t.stop_gradient = False
    y = paddle.tanh_(t * 1.0)   # in-place on an intermediate
    y.sum().backward()
    np.testing.assert_allclose(t.grad.numpy(),
                               1.0 - np.tanh(2.0) ** 2 * np.ones(3),
                               rtol=1e-5)
    with pytest.raises(RuntimeError, match="leaf"):
        paddle.scale_(t, 0.5)
    with paddle.no_grad():
        paddle.scale_(t, 0.5)
    np.testing.assert_allclose(t.numpy(), np.ones(3), rtol=1e-6)


def test_random_fills():
    paddle.seed(123)
    x = paddle.zeros([1000])
    paddle.normal_(x, mean=1.0, std=0.1)
    assert abs(float(x.mean()) - 1.0) < 0.02
    paddle.uniform_(x, min=0.0, max=2.0)
    assert 0.0 <= float(x.min()) and float(x.max()) <= 2.0
    paddle.geometric_(x, probs=0.5)
    assert float(x.min()) > 0.0  # continuous value, support (0, inf)
    paddle.cauchy_(x)
    assert np.isfinite(x.numpy()).all()


def test_summary_and_flops():
    from paddle_tpu import nn
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    info = paddle.summary(net, (1, 8))
    assert info["total_params"] == 8 * 16 + 16 + 16 * 2 + 2
    fl = paddle.flops(net, (4, 8))
    assert fl >= 2 * 4 * 8 * 16  # at least the first matmul


def test_svd_returns_vh_reference_contract():
    """paddle.linalg.svd returns (U, S, VH) with x == U @ diag(S) @ VH
    (reference tensor/linalg.py: 'VH is the conjugate transpose of V');
    a previous implementation returned V and broke reconstruction."""
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    u, s, vh = paddle.linalg.svd(paddle.to_tensor(a),
                                 full_matrices=False)
    assert tuple(u.shape) == (3, 3) and tuple(vh.shape) == (3, 4)
    np.testing.assert_allclose(
        u.numpy() @ np.diag(s.numpy()) @ vh.numpy(), a, atol=1e-4)
    nu, ns, nvh = np.linalg.svd(a, full_matrices=False)
    np.testing.assert_allclose(np.abs(s.numpy()), np.abs(ns), rtol=1e-5,
                               atol=1e-5)  # rank-2: s[2] is numeric 0
