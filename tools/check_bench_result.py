#!/usr/bin/env python
"""Benchmark regression gate.

Reference capability: tools/check_op_benchmark_result.py — CI compares a
run's numbers against recorded baselines and fails on regressions beyond
a threshold.

Usage: python tools/check_bench_result.py BENCH_rN.json [--threshold 0.9]
Compares `value` against the recorded per-platform best in
BENCH_BASELINE.json (written by bench.py).

An `eager_op_dispatch_*` result (benchmarks/eager_overhead.py) is
validated against its JSON schema instead of the throughput baseline —
the microbench's comparison is self-contained (cached vs uncached in
one process).  A `serving_*` result (benchmarks/serving_bench.py) is
likewise schema-validated, plus a floor on its self-contained
continuous-batching speedup vs the sequential baseline.  A
`serving_paged_*` result (--workload prefix) gates the paged KV
cache: >= 2x tokens/sec vs the slot engine at equal cache memory,
prefix-cache hits on every shared-prompt request, and strictly more
concurrent sequences than preallocation would have allowed."""
from __future__ import annotations

import argparse
import json
import os
import sys


_EAGER_SCHEMA = {
    # key -> accepted types; every key is required
    "metric": str,
    "value": (int, float),
    "unit": str,
    "speedup_vs_uncached": (int, float),
    "step_speedup_vs_uncached": (int, float),
    "cached": dict,
    "uncached": dict,
    "loss": (int, float),
    "iters": int,
    "ops_per_fwd": int,
    "smoke": bool,
    "platform": str,
    "tier1": dict,
}
_EAGER_TIER1_KEYS = ("hits", "misses", "evictions", "bypasses",
                     "entries", "bytes")


def check_eager_overhead(run):
    """Schema gate for benchmarks/eager_overhead.py output."""
    errors = []
    for key, types in _EAGER_SCHEMA.items():
        if key not in run:
            errors.append(f"missing key {key!r}")
        elif not isinstance(run[key], types):
            errors.append(f"{key!r} has type {type(run[key]).__name__}, "
                          f"expected {types}")
    if not errors:
        for side in ("cached", "uncached"):
            for k in ("fwd_ops_per_sec", "step_ops_per_sec"):
                v = run[side].get(k)
                if not isinstance(v, (int, float)) or v <= 0:
                    errors.append(f"{side}.{k} must be a positive number, "
                                  f"got {v!r}")
        for k in _EAGER_TIER1_KEYS:
            if not isinstance(run["tier1"].get(k), int):
                errors.append(f"tier1.{k} missing or not an int")
        if not errors:
            if run["value"] <= 0:
                errors.append("value must be positive")
            if run["speedup_vs_uncached"] <= 0:
                errors.append("speedup_vs_uncached must be positive")
            if run["tier1"]["hits"] <= 0:
                errors.append("tier1.hits is zero — the cached pass "
                              "never hit its own cache")
        # sentinel healthy-path gate (ISSUE 10): detection on top of
        # the guarded eager step must cost <= 2% (older recorded
        # baselines predate the section, so it is optional there)
        sen = run.get("sentinel")
        if isinstance(sen, dict):
            ratio = sen.get("overhead_vs_guarded")
            if not isinstance(ratio, (int, float)) or ratio <= 0:
                errors.append("sentinel.overhead_vs_guarded missing or "
                              f"not positive: {ratio!r}")
            elif ratio > _SENTINEL_MAX_OVERHEAD:
                errors.append(
                    f"sentinel eager overhead {ratio:.3f}x > "
                    f"{_SENTINEL_MAX_OVERHEAD}x vs the guarded step")
            if sen.get("anomalies"):
                errors.append("sentinel flagged anomalies on the "
                              "healthy bench workload")
    if errors:
        print("eager_overhead schema check FAILED:")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(f"eager_overhead schema OK: {run['value']:.1f} ops/sec, "
          f"{run['speedup_vs_uncached']:.2f}x vs uncached, "
          f"tier1 hits={run['tier1']['hits']}")
    return 0


_TRAIN_STEP_SCHEMA = {
    # key -> accepted types; every key is required
    "metric": str,
    "value": (int, float),
    "unit": str,
    "speedup_vs_eager": (int, float),
    "eager": dict,
    "compiled": dict,
    "losses_allclose": bool,
    "losses_max_reldiff": (int, float),
    "losses_bitwise_equal": bool,
    "compiled_lane_active": bool,
    "steps": int,
    "batch": int,
    "seq": int,
    "smoke": bool,
    "platform": str,
}

# acceptance floors (ISSUE 8): the one-program donated-buffer train step
# must beat op-by-op eager dispatch by >= 1.5x step-time p50 on the CPU
# smoke config (dispatch-bound; clears ~4x).  The full CPU config is
# dominated by real matmul time — the one-program win there is bounded
# by Amdahl at ~1.4x on a quiet box — so it carries a softer 1.15x
# regression floor rather than the headline gate.
_TRAIN_STEP_MIN_SPEEDUP_SMOKE = 1.5
_TRAIN_STEP_MIN_SPEEDUP_FULL = 1.15

# sentinel healthy-path ceiling (ISSUE 10): the sentinel's detection
# signals (device health vector, cond-sampled grad norm) on top of the
# guarded (found-inf-armed) step, measured interleaved so box drift
# cancels.  The skip machinery itself is the PRE-EXISTING AMP select
# path and is recorded informationally, not gated here.
_SENTINEL_MAX_OVERHEAD = 1.02


def check_train_step_bench(run):
    """Schema + speedup/equality gate for benchmarks/train_step_bench.py."""
    errors = []
    for key, types in _TRAIN_STEP_SCHEMA.items():
        if key not in run:
            errors.append(f"missing key {key!r}")
        elif run[key] is None or not isinstance(run[key], types):
            errors.append(f"{key!r} has type {type(run[key]).__name__}, "
                          f"expected {types}")
    if not errors:
        for side in ("eager", "compiled"):
            for k in ("p50_ms", "p99_ms", "mean_ms", "steps"):
                v = run[side].get(k)
                if not isinstance(v, (int, float)) or v <= 0:
                    errors.append(f"{side}.{k} must be a positive "
                                  f"number, got {v!r}")
        if run["value"] <= 0:
            errors.append("value must be positive")
        if not run["compiled_lane_active"]:
            errors.append("compiled lane fell back to eager — the gate "
                          "measured eager twice")
        floor = (_TRAIN_STEP_MIN_SPEEDUP_SMOKE if run["smoke"]
                 else _TRAIN_STEP_MIN_SPEEDUP_FULL)
        if run["speedup_vs_eager"] < floor:
            errors.append(
                f"speedup_vs_eager {run['speedup_vs_eager']:.2f} < "
                f"required {floor}x")
        if run["platform"] == "cpu" and not run["losses_allclose"]:
            errors.append(
                "compiled fp32 loss trajectory diverged from eager on "
                f"CPU beyond ulp tolerance (max rel diff "
                f"{run.get('losses_max_reldiff')})")
        sen = run.get("sentinel")
        if not isinstance(sen, dict):
            errors.append("missing 'sentinel' overhead section")
        else:
            ratio = sen.get("overhead_vs_guarded")
            if not isinstance(ratio, (int, float)) or ratio <= 0:
                errors.append("sentinel.overhead_vs_guarded missing or "
                              f"not positive: {ratio!r}")
            elif ratio > _SENTINEL_MAX_OVERHEAD:
                errors.append(
                    f"sentinel compiled overhead {ratio:.3f}x > "
                    f"{_SENTINEL_MAX_OVERHEAD}x vs the guarded step")
            if not sen.get("pair_compiled"):
                errors.append("sentinel overhead pair fell back to "
                              "eager — the gate measured nothing")
    if errors:
        print("train_step_bench schema check FAILED:")
        for e in errors:
            print(f"  - {e}")
        return 1
    tag = ("bit-equal" if run["losses_bitwise_equal"]
           else f"ulp-close (max rel {run['losses_max_reldiff']:.1e})")
    print(f"train_step_bench schema OK: p50 {run['value']:.1f}ms "
          f"compiled vs {run['eager']['p50_ms']:.1f}ms eager "
          f"({run['speedup_vs_eager']:.2f}x), trajectories {tag}")
    return 0


_MFU_SWEEP_SCHEMA = {
    # key -> accepted types; every key is required
    "metric": str,
    "value": (int, float),
    "unit": str,
    "world_size": int,
    "model": dict,
    "layouts": dict,
    "speedup_hybrid_vs_dp": (int, float),
    "planner": dict,
    "steps": int,
    "batch": int,
    "seq": int,
    "smoke": bool,
    "platform": str,
}
_MFU_LAYOUT_KEYS = ("dp", "mp", "p50_ms", "tokens_per_sec", "compiled",
                    "projected_ms", "projected_err", "anchor", "mfu")

# acceptance floors (ISSUE 12): at equal world size the hybrid
# dp×mp compiled step must beat the dp-only compiled step by >= 1.3x
# step-time p50 on the parameter-heavy sweep config (pure dp moves the
# full model per step in its grad all-reduce and replicates the
# optimizer update; smoke clears ~3.5x), the planner's pick must match
# or beat every hand-written layout on the grid (<= 5% of the measured
# best), and the calibrated projection must land within 25% of the
# measured step time on held-out layouts.
_MFU_MIN_HYBRID_SPEEDUP = 1.3
_MFU_MAX_PICK_VS_BEST = 1.05
_MFU_MAX_PROJECTED_ERR = 0.25


def check_mfu_sweep(run):
    """Schema + hybrid-speedup/planner gates for
    benchmarks/mfu_sweep.py (layout sweep, MFU_SWEEP.json)."""
    errors = []
    for key, types in _MFU_SWEEP_SCHEMA.items():
        if key not in run:
            errors.append(f"missing key {key!r}")
        elif run[key] is None or not isinstance(run[key], types):
            errors.append(f"{key!r} has type {type(run[key]).__name__}, "
                          f"expected {types}")
    if not errors:
        if len(run["layouts"]) < 2:
            errors.append("fewer than 2 layouts measured — nothing to "
                          "compare")
        for name, lay in run["layouts"].items():
            for k in _MFU_LAYOUT_KEYS:
                if k not in lay:
                    errors.append(f"layouts.{name} missing {k!r}")
            if not lay.get("compiled"):
                errors.append(f"layouts.{name} fell back to eager "
                              f"({lay.get('fallback_reason')}) — the "
                              "sweep measured the wrong lane")
        losses = {round(lay.get("loss", 0), 4)
                  for lay in run["layouts"].values()}
        if len(losses) != 1:
            errors.append(f"per-layout losses diverged: {sorted(losses)}"
                          " — layouts did not compute the same step")
        if run["speedup_hybrid_vs_dp"] < _MFU_MIN_HYBRID_SPEEDUP:
            errors.append(
                f"speedup_hybrid_vs_dp {run['speedup_hybrid_vs_dp']:.2f}"
                f" < required {_MFU_MIN_HYBRID_SPEEDUP}x at equal world "
                "size")
        planner = run["planner"]
        if not planner.get("pick_measured"):
            errors.append("planner pick was not on the measured grid")
        ratio = planner.get("pick_vs_best")
        if not isinstance(ratio, (int, float)) or \
                ratio > _MFU_MAX_PICK_VS_BEST:
            errors.append(
                f"planner pick is {ratio!r}x the measured-best layout "
                f"(> {_MFU_MAX_PICK_VS_BEST}) — the planner lost to a "
                "hand-written layout")
        err = planner.get("max_projected_err")
        if not isinstance(err, (int, float)) or \
                err > _MFU_MAX_PROJECTED_ERR:
            errors.append(
                f"max projected-vs-measured error {err!r} > "
                f"{_MFU_MAX_PROJECTED_ERR} on held-out layouts")
    if errors:
        print("mfu_sweep schema check FAILED:")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(f"mfu_sweep schema OK: best layout dp{run['planner']['pick']['dp']}"
          f"xmp{run['planner']['pick']['mp']} at {run['value']:.1f}ms, "
          f"{run['speedup_hybrid_vs_dp']:.2f}x vs dp-only, planner err "
          f"{run['planner']['max_projected_err']:.3f}")
    return 0


_SERVING_SCHEMA = {
    # key -> accepted types; every key is required
    "metric": str,
    "value": (int, float),
    "unit": str,
    "speedup_vs_sequential": (int, float),
    "sequential": dict,
    "serving": dict,
    "ttft_ms_avg": (int, float),
    "per_token_ms_avg": (int, float),
    "slot_occupancy": (int, float),
    "num_requests": int,
    "num_slots": int,
    "max_new_tokens": int,
    "greedy_mismatches": int,
    "smoke": bool,
    "platform": str,
}

# acceptance floor: continuous batching must sustain >= 2x the
# sequential per-request generate() throughput at >= 4 concurrent
# requests (ISSUE 3); CPU smoke runs clear ~3x, so 2.0 has margin
# without being noise-sensitive
_SERVING_MIN_SPEEDUP = 2.0


def check_serving_bench(run):
    """Schema + speedup gate for benchmarks/serving_bench.py output."""
    errors = []
    for key, types in _SERVING_SCHEMA.items():
        if key not in run:
            errors.append(f"missing key {key!r}")
        elif run[key] is None or not isinstance(run[key], types):
            errors.append(f"{key!r} has type {type(run[key]).__name__}, "
                          f"expected {types}")
    if not errors:
        for side in ("sequential", "serving"):
            for k in ("tokens_per_sec", "wall_s", "tokens"):
                v = run[side].get(k)
                if not isinstance(v, (int, float)) or v <= 0:
                    errors.append(f"{side}.{k} must be a positive "
                                  f"number, got {v!r}")
        if run["value"] <= 0:
            errors.append("value must be positive")
        if run["greedy_mismatches"] != 0:
            errors.append(f"{run['greedy_mismatches']} serving outputs "
                          "diverged from the sequential greedy baseline")
        if not 0.0 < run["slot_occupancy"] <= 1.0:
            errors.append(f"slot_occupancy {run['slot_occupancy']!r} "
                          "outside (0, 1]")
        if run["num_requests"] >= 4 and \
                run["speedup_vs_sequential"] < _SERVING_MIN_SPEEDUP:
            errors.append(
                f"speedup_vs_sequential {run['speedup_vs_sequential']:.2f}"
                f" < required {_SERVING_MIN_SPEEDUP}x at "
                f"{run['num_requests']} concurrent requests")
    if errors:
        print("serving_bench schema check FAILED:")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(f"serving_bench schema OK: {run['value']:.1f} tokens/sec, "
          f"{run['speedup_vs_sequential']:.2f}x vs sequential, "
          f"occupancy {run['slot_occupancy']:.2f}, "
          f"ttft {run['ttft_ms_avg']:.0f}ms")
    return 0


_PAGED_SCHEMA = {
    # key -> accepted types; every key is required
    "metric": str,
    "value": (int, float),
    "unit": str,
    "speedup_vs_slots": (int, float),
    "slots": dict,
    "paged": dict,
    "prefix_cache_hits": int,
    "prefix_cache_hit_tokens": int,
    "max_concurrent": int,
    "prealloc_capacity": int,
    "pool_pages": int,
    "prefix_len": int,
    "num_requests": int,
    "max_new_tokens": int,
    "greedy_mismatches": int,
    "smoke": bool,
    "platform": str,
}

# acceptance floors (ISSUE 7): on the shared-prefix workload the paged
# engine must sustain >= 2x the slot engine's tokens/sec at EQUAL cache
# memory (smoke clears ~2.3x, full ~2.6x), and must have run strictly
# more concurrent sequences than the same bytes preallocated as
# max_seq_len stripes could
_PAGED_MIN_SPEEDUP = 2.0


def check_paged_bench(run):
    """Schema + speedup/occupancy gates for the shared-prefix lane of
    benchmarks/serving_bench.py (--workload prefix)."""
    errors = []
    for key, types in _PAGED_SCHEMA.items():
        if key not in run:
            errors.append(f"missing key {key!r}")
        elif run[key] is None or not isinstance(run[key], types):
            errors.append(f"{key!r} has type {type(run[key]).__name__}, "
                          f"expected {types}")
    if not errors:
        for side in ("slots", "paged"):
            for k in ("tokens_per_sec", "wall_s", "tokens",
                      "slot_occupancy", "ttft_ms_avg"):
                v = run[side].get(k)
                if not isinstance(v, (int, float)) or v <= 0:
                    errors.append(f"{side}.{k} must be a positive "
                                  f"number, got {v!r}")
        if run["value"] <= 0:
            errors.append("value must be positive")
        if run["greedy_mismatches"] != 0:
            errors.append(f"{run['greedy_mismatches']} paged outputs "
                          "diverged from the sequential greedy baseline")
        if run["num_requests"] >= 4 and \
                run["speedup_vs_slots"] < _PAGED_MIN_SPEEDUP:
            errors.append(
                f"speedup_vs_slots {run['speedup_vs_slots']:.2f} < "
                f"required {_PAGED_MIN_SPEEDUP}x at equal cache memory")
        if run["prefix_cache_hits"] < run["num_requests"]:
            errors.append(
                f"prefix_cache_hits {run['prefix_cache_hits']} < "
                f"{run['num_requests']} — the shared system prompt was "
                "recomputed instead of reused")
        if run["max_concurrent"] <= run["prealloc_capacity"]:
            errors.append(
                f"max_concurrent {run['max_concurrent']} <= "
                f"prealloc_capacity {run['prealloc_capacity']} — paging "
                "admitted no more sequences than slot preallocation")
    if errors:
        print("serving_paged schema check FAILED:")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(f"serving_paged schema OK: {run['value']:.1f} tokens/sec, "
          f"{run['speedup_vs_slots']:.2f}x vs slot engine, "
          f"{run['prefix_cache_hits']} prefix hits, "
          f"{run['max_concurrent']} concurrent vs "
          f"{run['prealloc_capacity']} preallocated")
    return 0


_SPEC_SCHEMA = {
    # key -> accepted types; every key is required
    "metric": str,
    "value": (int, float),
    "unit": str,
    "speedups": dict,
    "speedup_min": (int, float),
    "speculation_k": int,
    "acceptance_rate": (int, float),
    "batches": dict,
    "int8_kv": dict,
    "max_new_tokens": int,
    "greedy_mismatches": int,
    "spec_draft_ms_avg": (int, float),
    "spec_verify_ms_avg": (int, float),
    "spec_rollback_ms_avg": (int, float),
    "smoke": bool,
    "platform": str,
}

# acceptance floors (ISSUE 11): the speculative lane must sustain >= 2x
# the plain paged engine's decode tokens/sec at every measured batch
# size 1..4 (smoke clears ~2.7x with K=8 and a 1-block draft against an
# 8-block target), keep greedy outputs bit-equal to sequential
# generate(), and accept most of what a perfectly-agreeing draft
# proposes (the lane's draft computes the target's function; a low rate
# means the accept machinery itself broke).  The int8-KV section must
# show the pages-in-use peak at equal token load at ~half the fp32
# pool's (quantized pages pack 2x the tokens in half the bytes).
_SPEC_MIN_SPEEDUP = 2.0
_SPEC_MIN_ACCEPTANCE = 0.8
_SPEC_MAX_INT8_PAGES_RATIO = 0.6


_TICK_SCHEMA = {
    # key -> accepted types; every key is required
    "metric": str,
    "value": (int, float),
    "unit": str,
    "speedup_vs_uncompiled": (int, float),
    "uncompiled": dict,
    "compiled": dict,
    "tick_compiled_hits": (int, float),
    "tick_fallbacks": (int, float),
    "slot_occupancy": (int, float),
    "num_slots": int,
    "num_requests": int,
    "max_new_tokens": int,
    "greedy_mismatches": int,
    "sampled_mismatches": int,
    "smoke": bool,
    "platform": str,
}
_TICK_MIN_SPEEDUP = 1.5


def check_tick_bench(run):
    """Schema + speedup/bit-equality gates for the high-occupancy
    compiled-tick lane of benchmarks/serving_bench.py (--workload
    occupancy, ISSUE 13): at 8+ slots of short decodes the ONE-program
    tick must deliver >= 1.5x tokens/sec over the uncompiled scheduler
    with outputs bit-equal (greedy vs the sequential reference, seeded
    sampled across lanes) and zero fallbacks."""
    errors = []
    for key, types in _TICK_SCHEMA.items():
        if key not in run:
            errors.append(f"missing key {key!r}")
        elif run[key] is None or not isinstance(run[key], types):
            errors.append(f"{key!r} has type {type(run[key]).__name__}, "
                          f"expected {types}")
    if not errors:
        for side in ("uncompiled", "compiled"):
            for k in ("tokens_per_sec", "wall_s", "tokens"):
                v = run[side].get(k)
                if not isinstance(v, (int, float)) or v <= 0:
                    errors.append(f"{side}.{k} must be a positive "
                                  f"number, got {v!r}")
        if run["num_slots"] < 8:
            errors.append(f"num_slots {run['num_slots']} < 8 — not a "
                          "high-occupancy lane")
        if run["speedup_vs_uncompiled"] < _TICK_MIN_SPEEDUP:
            errors.append(
                f"speedup_vs_uncompiled {run['speedup_vs_uncompiled']:.2f}"
                f" < required {_TICK_MIN_SPEEDUP}x at "
                f"{run['num_slots']} slots")
        if run["tick_compiled_hits"] <= 0:
            errors.append("tick_compiled_hits is 0 — the compiled lane "
                          "never actually ran the tick program")
        if run["tick_fallbacks"] != 0:
            errors.append(f"{run['tick_fallbacks']} tick fallback(s) on "
                          "an all-hostable workload")
        if run["greedy_mismatches"] != 0:
            errors.append(
                f"{run['greedy_mismatches']} outputs diverged from the "
                "sequential greedy baseline — the compiled tick must be "
                "output-invariant")
        if run["sampled_mismatches"] != 0:
            errors.append(
                f"{run['sampled_mismatches']} seeded-sampled outputs "
                "diverged between the compiled and uncompiled lanes")
    if errors:
        print("serving_tick schema check FAILED:")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(f"serving_tick schema OK: {run['value']:.1f} tokens/sec, "
          f"{run['speedup_vs_uncompiled']:.2f}x vs uncompiled at "
          f"{run['num_slots']} slots, {run['tick_compiled_hits']} "
          "compiled ticks, outputs bit-equal")
    return 0


def check_spec_bench(run):
    """Schema + speedup/acceptance/capacity gates for the speculative
    lane of benchmarks/serving_bench.py (--workload speculative)."""
    errors = []
    for key, types in _SPEC_SCHEMA.items():
        if key not in run:
            errors.append(f"missing key {key!r}")
        elif run[key] is None or not isinstance(run[key], types):
            errors.append(f"{key!r} has type {type(run[key]).__name__}, "
                          f"expected {types}")
    if not errors:
        for name in ("batch_1", "batch_4"):
            side = run["batches"].get(name)
            if not isinstance(side, dict):
                errors.append(f"batches.{name} missing")
                continue
            for k in ("baseline_tokens_per_sec", "spec_tokens_per_sec",
                      "speedup"):
                v = side.get(k)
                if not isinstance(v, (int, float)) or v <= 0:
                    errors.append(f"batches.{name}.{k} must be a "
                                  f"positive number, got {v!r}")
            sp = run["speedups"].get(name)
            if isinstance(sp, (int, float)) and sp < _SPEC_MIN_SPEEDUP:
                errors.append(
                    f"speedups.{name} {sp:.2f} < required "
                    f"{_SPEC_MIN_SPEEDUP}x vs the non-speculative "
                    "paged engine")
        if run["value"] <= 0:
            errors.append("value must be positive")
        if run["greedy_mismatches"] != 0:
            errors.append(
                f"{run['greedy_mismatches']} outputs diverged from the "
                "sequential greedy baseline — speculation must be "
                "output-invariant")
        if run["acceptance_rate"] < _SPEC_MIN_ACCEPTANCE:
            errors.append(
                f"acceptance_rate {run['acceptance_rate']:.2f} < "
                f"{_SPEC_MIN_ACCEPTANCE} with a function-identical "
                "draft — the accept machinery is rejecting good tokens")
        int8 = run["int8_kv"]
        for k in ("pages_peak_float32", "pages_peak_int8", "ratio"):
            if not isinstance(int8.get(k), (int, float)) or \
                    int8[k] <= 0:
                errors.append(f"int8_kv.{k} missing or not positive")
        if not errors and int8["ratio"] > _SPEC_MAX_INT8_PAGES_RATIO:
            errors.append(
                f"int8_kv.ratio {int8['ratio']:.2f} > "
                f"{_SPEC_MAX_INT8_PAGES_RATIO} — quantized KV did not "
                "deliver ~2x effective cache capacity at equal tokens")
    if errors:
        print("serving_speculative schema check FAILED:")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(f"serving_speculative schema OK: {run['value']:.1f} tokens/"
          f"sec, speedups {run['speedups']}, acceptance "
          f"{run['acceptance_rate']:.2f}, int8 pages ratio "
          f"{run['int8_kv']['ratio']:.2f}")
    return 0


_LORA_SCHEMA = {
    # key -> accepted types; every key is required
    "metric": str,
    "value": (int, float),
    "unit": str,
    "speedup_vs_sequential_adapters": (int, float),
    "sequential_adapters": dict,
    "multiplexed": dict,
    "num_adapters": int,
    "adapter_rank": int,
    "max_adapters": int,
    "num_slots": int,
    "requests_per_adapter": int,
    "max_new_tokens": int,
    "adapter_mismatches": int,
    "dropped_requests": int,
    "tick_fallbacks": (int, float),
    "tick_compiled_hits": (int, float),
    "adapters_loaded": (int, float),
    "adapter_evictions": (int, float),
    "adapter_load_ms_avg": (int, float),
    "smoke": bool,
    "platform": str,
}

# acceptance floors (ISSUE 16): multiplexing N adapters through ONE
# batched engine must sustain >= 5x the aggregate tokens/sec of N
# sequential single-adapter engine runs (the CI smoke lane, 4 adapters
# on 4 slots, clears a lower 2x floor), every per-request output must
# be bit-equal to the dedicated-engine reference, adapter hot-swap
# must drop zero requests, and the compiled tick must serve the whole
# mixed-adapter workload without a single fallback.
_LORA_MIN_SPEEDUP = 5.0
_LORA_MIN_SPEEDUP_SMOKE = 2.0


def check_lora_bench(run):
    """Schema + speedup/bit-equality/zero-drop gates for the
    multi-tenant LoRA lane of benchmarks/serving_bench.py (--workload
    multitenant, ISSUE 16)."""
    errors = []
    for key, types in _LORA_SCHEMA.items():
        if key not in run:
            errors.append(f"missing key {key!r}")
        elif run[key] is None or not isinstance(run[key], types):
            errors.append(f"{key!r} has type {type(run[key]).__name__}, "
                          f"expected {types}")
    if not errors:
        for side in ("sequential_adapters", "multiplexed"):
            for k in ("tokens_per_sec", "wall_s", "tokens"):
                v = run[side].get(k)
                if not isinstance(v, (int, float)) or v <= 0:
                    errors.append(f"{side}.{k} must be a positive "
                                  f"number, got {v!r}")
        floor = _LORA_MIN_SPEEDUP_SMOKE if run["smoke"] \
            else _LORA_MIN_SPEEDUP
        if run["speedup_vs_sequential_adapters"] < floor:
            errors.append(
                f"speedup_vs_sequential_adapters "
                f"{run['speedup_vs_sequential_adapters']:.2f} < required "
                f"{floor}x for {run['num_adapters']} adapters")
        if run["adapter_mismatches"] != 0:
            errors.append(
                f"{run['adapter_mismatches']} outputs diverged from the "
                "single-adapter engine reference — per-slot adapter "
                "gather must be output-invariant")
        if run["dropped_requests"] != 0:
            errors.append(f"{run['dropped_requests']} request(s) "
                          "dropped during adapter hot-swap")
        if run["tick_fallbacks"] != 0:
            errors.append(f"{run['tick_fallbacks']} tick fallback(s) on "
                          "a mixed-adapter workload")
        if run["tick_compiled_hits"] <= 0:
            errors.append("tick_compiled_hits is 0 — the compiled tick "
                          "never actually served the multiplexed lane")
        if run["adapters_loaded"] < run["num_adapters"]:
            errors.append(
                f"adapters_loaded {run['adapters_loaded']} < "
                f"num_adapters {run['num_adapters']} — some tenant "
                "never reached a pool slot")
    if errors:
        print("serving_lora schema check FAILED:")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(f"serving_lora schema OK: {run['value']:.1f} tokens/sec, "
          f"{run['speedup_vs_sequential_adapters']:.2f}x vs "
          f"{run['num_adapters']} sequential single-adapter runs, "
          f"{run['adapter_evictions']} eviction(s), outputs bit-equal, "
          "zero drops/fallbacks")
    return 0


_FLEET_SCHEMA = {
    # key -> accepted types; every key is required
    "metric": str,
    "value": (int, float),
    "unit": str,
    "passed": bool,
    "num_replicas": int,
    "num_slots": int,
    "num_requests": int,
    "max_new_tokens": int,
    "drain_deadline_s": (int, float),
    "variants": dict,
    "smoke": bool,
    "platform": str,
}
_FLEET_VARIANT_KEYS = ("lost_requests", "greedy_mismatches",
                       "duplicate_tokens", "recovery_p99_s", "failovers",
                       "resubmissions", "requests_recovered",
                       "leaked_processes")


def check_fleet_bench(run):
    """Schema + zero-loss/recovery gates for
    benchmarks/serving_fleet_bench.py (ISSUE 9): with replicas dying
    mid-load, every request completes bit-equal to the single-model
    greedy reference (zero lost, zero duplicate tokens), p99 recovery
    stays under the drain deadline, the SIGTERM victim exits 0 within
    the deadline, and no replica process leaks."""
    errors = []
    for key, types in _FLEET_SCHEMA.items():
        if key not in run:
            errors.append(f"missing key {key!r}")
        elif run[key] is None or not isinstance(run[key], types):
            errors.append(f"{key!r} has type {type(run[key]).__name__}, "
                          f"expected {types}")
    if not errors:
        if not run["variants"]:
            errors.append("no chaos variants recorded")
        for name, v in run["variants"].items():
            for k in _FLEET_VARIANT_KEYS:
                if k not in v:
                    errors.append(f"variants.{name} missing {k!r}")
            if errors:
                continue
            if v["lost_requests"] != 0:
                errors.append(f"{name}: {v['lost_requests']} requests "
                              "LOST when the replica died")
            if v["greedy_mismatches"] != 0 or v["duplicate_tokens"] != 0:
                errors.append(
                    f"{name}: {v['greedy_mismatches']} outputs diverged "
                    "from the single-model greedy reference (dropped or "
                    "duplicated tokens on failover)")
            if v["recovery_p99_s"] >= run["drain_deadline_s"]:
                errors.append(
                    f"{name}: recovery p99 {v['recovery_p99_s']}s >= "
                    f"drain deadline {run['drain_deadline_s']}s")
            if v["leaked_processes"]:
                errors.append(f"{name}: leaked replica processes "
                              f"{v['leaked_processes']}")
        sigkill = run["variants"].get("sigkill")
        if sigkill is not None and sigkill.get("failovers", 0) < 1:
            errors.append("sigkill variant recorded no failover — the "
                          "kill landed on an idle fleet (not mid-load)")
        sigterm = run["variants"].get("sigterm")
        if sigterm is not None:
            if sigterm.get("drain_exitcode") != 0:
                errors.append(f"sigterm victim exit code "
                              f"{sigterm.get('drain_exitcode')!r} != 0")
            if sigterm.get("drain_exit_s", 1e9) >= \
                    run["drain_deadline_s"] + 10:
                errors.append(
                    f"sigterm victim took {sigterm.get('drain_exit_s')}s "
                    "to exit — past the drain deadline + grace")
    if errors:
        print("serving_fleet schema check FAILED:")
        for e in errors:
            print(f"  - {e}")
        return 1
    worst = max(v["recovery_p99_s"] for v in run["variants"].values())
    print(f"serving_fleet schema OK: {len(run['variants'])} chaos "
          f"variant(s), zero lost requests, recovery p99 {worst:.2f}s "
          f"< {run['drain_deadline_s']}s deadline")
    return 0


_DISAGG_SCHEMA = {
    # key -> accepted types; every key is required
    "metric": str,
    "value": (int, float),
    "unit": str,
    "ttft_p99_improvement": (int, float),
    "decode_p50_improvement": (int, float),
    "symmetric": dict,
    "disagg": dict,
    "flip": dict,
    "greedy_mismatches": int,
    "num_replicas": int,
    "long_prompts": int,
    "chat_prompts": int,
    "parallel_host": bool,
    "host_cores": int,
    "smoke": bool,
    "platform": str,
}
_DISAGG_SIDE_KEYS = ("ttft_p99_ms", "decode_p50_ms", "tokens_per_sec",
                     "wall_s", "requests")
_DISAGG_FLIP_KEYS = ("victim", "new_role", "lost_requests",
                     "greedy_mismatches", "resubmissions", "converged",
                     "gen_bumped")
# acceptance floors (ISSUE 14): at EQUAL chip count on the mixed
# long-prompt/chat workload, the disaggregated fleet must beat the
# symmetric fleet on BOTH tail TTFT (prefill replicas run chunk rounds
# without decode steps in the way) and median inter-token latency (the
# decode replica's hot loop never pays a prefill chunk), migrated
# outputs must be bit-equal to the single-replica greedy reference,
# and a mid-load role flip must lose zero requests.
#
# The improvement floors apply on a `parallel_host` (>= 3 cores or
# TPU): with the two replicas timesliced onto 1 core, total work is
# conserved and wall-clock deltas measure the OS scheduler, not the
# architecture — there the lane still gates bit-equality, actual
# migration, and the lossless role flip, and records latencies
# observationally (benchmarks/README.md: "a regression canary, never
# a hardware claim").
_DISAGG_MIN_IMPROVEMENT = 1.0


def check_disagg_bench(run):
    """Schema + improvement/bit-equality/flip gates for the
    prefill/decode disaggregation lane of
    benchmarks/serving_fleet_bench.py (--workload disagg, ISSUE 14)."""
    errors = []
    for key, types in _DISAGG_SCHEMA.items():
        if key not in run:
            errors.append(f"missing key {key!r}")
        elif run[key] is None or not isinstance(run[key], types):
            errors.append(f"{key!r} has type {type(run[key]).__name__}, "
                          f"expected {types}")
    if not errors:
        for side in ("symmetric", "disagg"):
            for k in _DISAGG_SIDE_KEYS:
                v = run[side].get(k)
                if not isinstance(v, (int, float)) or v <= 0:
                    errors.append(f"{side}.{k} must be a positive "
                                  f"number, got {v!r}")
        for k in _DISAGG_FLIP_KEYS:
            if k not in run["flip"]:
                errors.append(f"flip missing {k!r}")
    if not errors:
        if run.get("parallel_host", True):
            if run["ttft_p99_improvement"] <= _DISAGG_MIN_IMPROVEMENT:
                errors.append(
                    f"ttft_p99_improvement "
                    f"{run['ttft_p99_improvement']:.3f}"
                    f"x <= {_DISAGG_MIN_IMPROVEMENT}x — disaggregation "
                    "did not improve tail TTFT vs the symmetric fleet")
            if run["decode_p50_improvement"] <= _DISAGG_MIN_IMPROVEMENT:
                errors.append(
                    f"decode_p50_improvement "
                    f"{run['decode_p50_improvement']:.3f}x <= "
                    f"{_DISAGG_MIN_IMPROVEMENT}x — disaggregation did "
                    "not improve median inter-token latency")
        if run["greedy_mismatches"] != 0:
            errors.append(
                f"{run['greedy_mismatches']} outputs diverged from the "
                "single-replica greedy reference — migrated KV pages "
                "must be bit-exact")
        if run["disagg"].get("migrated_requests", 0) < 1:
            errors.append("no request actually migrated — the "
                          "disaggregated lane measured nothing")
        flip = run["flip"]
        if flip["lost_requests"] != 0:
            errors.append(f"{flip['lost_requests']} requests LOST "
                          "through the mid-load role flip")
        if flip["greedy_mismatches"] != 0:
            errors.append(f"{flip['greedy_mismatches']} outputs "
                          "diverged across the role flip")
        if not flip["converged"]:
            errors.append("fleet never converged after the role flip "
                          "(victim not back ready under its new role)")
        if not flip["gen_bumped"]:
            errors.append("role flip rejoined WITHOUT a bumped "
                          "generation — the anti-flap protocol was "
                          "bypassed")
    if errors:
        print("serving_disagg schema check FAILED:")
        for e in errors:
            print(f"  - {e}")
        return 1
    gated = "" if run.get("parallel_host", True) else \
        " (observational: timesliced host)"
    print(f"serving_disagg schema OK: ttft p99 "
          f"{run['ttft_p99_improvement']:.2f}x, decode p50 "
          f"{run['decode_p50_improvement']:.2f}x vs symmetric{gated}, "
          f"{run['disagg'].get('migrated_requests')} migrated, "
          "flip lost 0")
    return 0


_DATA_SCHEMA = {
    # key -> accepted types; every key is required
    "metric": str,
    "throughput": dict,
    "resume": dict,
    "resume_compiled": dict,
    "resize": dict,
    "goodput_drill": dict,
    "calibration": dict,
    "parallel_host": bool,
    "host_cores": int,
    "batch": int,
    "smoke": bool,
}

# acceptance floors (ISSUE 18): on an input-heavy fit (per-batch host
# fetch calibrated to ~1.2x the step time), device_prefetch must
# deliver >= 1.3x steps/sec over the synchronous loader at equal
# model/batch — enforced only on a `parallel_host` (>= 2 cores): with
# producer and trainer timesliced onto 1 core total work is conserved
# and the delta measures the OS scheduler, not the overlap (the disagg
# bench convention).  Resume must be BIT-equal in the eager lane; the
# compiled lane tolerates 5e-6 (whole-step jit reassociates
# reductions).  The 4->2 dp resize must lose and duplicate exactly
# zero sample ids.  The data_slow drill must actually move the
# starvation counter and the input-bound gauge.
_DATA_MIN_SPEEDUP = 1.3
_DATA_MAX_COMPILED_DIFF = 5e-6


def check_data_bench(run):
    """Schema + overlap/determinism/resize gates for
    benchmarks/data_pipeline_bench.py (DATA_PIPELINE_BENCH.json)."""
    errors = []
    for key, types in _DATA_SCHEMA.items():
        if key not in run:
            errors.append(f"missing key {key!r}")
        elif run[key] is None or not isinstance(run[key], types):
            errors.append(f"{key!r} has type {type(run[key]).__name__}, "
                          f"expected {types}")
    if not errors:
        thr = run["throughput"]
        for k in ("sync_steps_per_sec", "prefetch_steps_per_sec",
                  "speedup"):
            v = thr.get(k)
            if not isinstance(v, (int, float)) or v <= 0:
                errors.append(f"throughput.{k} must be a positive "
                              f"number, got {v!r}")
        if not errors and run["parallel_host"] and \
                thr["speedup"] < _DATA_MIN_SPEEDUP:
            errors.append(
                f"throughput.speedup {thr['speedup']:.3f} < required "
                f"{_DATA_MIN_SPEEDUP}x on a parallel host "
                f"({run['host_cores']} cores)")
        res = run["resume"]
        if res.get("bitwise_equal") is not True:
            errors.append(
                "resume.bitwise_equal is not True — the eager mid-epoch "
                f"save->restore diverged (max abs diff "
                f"{res.get('max_abs_diff')!r}, "
                f"{res.get('steps_resumed')!r} of "
                f"{res.get('steps_ref')!r} steps)")
        resc = run["resume_compiled"]
        diff = resc.get("max_abs_diff")
        if not isinstance(diff, (int, float)) or \
                diff > _DATA_MAX_COMPILED_DIFF:
            errors.append(
                f"resume_compiled.max_abs_diff {diff!r} > "
                f"{_DATA_MAX_COMPILED_DIFF} tolerance")
        if resc.get("steps_resumed") != resc.get("steps_ref"):
            errors.append(
                f"resume_compiled ran {resc.get('steps_resumed')!r} "
                f"steps vs {resc.get('steps_ref')!r} in the reference")
        rez = run["resize"]
        if rez.get("lost") != 0 or rez.get("duplicated") != 0:
            errors.append(
                f"resize {rez.get('from_degree')}->{rez.get('to_degree')}"
                f" lost {rez.get('lost')!r} and duplicated "
                f"{rez.get('duplicated')!r} sample ids (both must be 0)")
        if not isinstance(rez.get("checked_samples"), int) or \
                rez.get("checked_samples", 0) <= 0:
            errors.append("resize.checked_samples missing or not a "
                          "positive int — the audit checked nothing")
        drill = run["goodput_drill"]
        if not drill.get("starved_steps"):
            errors.append("goodput_drill.starved_steps is 0 under "
                          "data_slow injection — the starvation counter "
                          "never moved")
        ib = drill.get("input_bound")
        if not isinstance(ib, (int, float)) or not 0.0 < ib <= 1.0:
            errors.append(f"goodput_drill.input_bound {ib!r} outside "
                          "(0, 1] under data_slow injection")
    if errors:
        print("data_pipeline schema check FAILED:")
        for e in errors:
            print(f"  - {e}")
        return 1
    gated = "" if run["parallel_host"] else \
        " (observational: timesliced host)"
    print(f"data_pipeline schema OK: prefetch "
          f"{run['throughput']['speedup']:.2f}x vs sync loader{gated}, "
          f"resume bit-equal, compiled diff "
          f"{run['resume_compiled']['max_abs_diff']:.1e}, resize "
          f"{run['resize']['from_degree']}->{run['resize']['to_degree']} "
          "lost 0 / dup 0")
    return 0


_RECOVERY_SCHEMA = {
    # key -> accepted types; every key is required
    "metric": str,
    "value": (int, float),
    "latency_ratio": (int, float),
    "peer_restore_ms": (int, float),
    "peer_recovery_ms": (int, float),
    "peer_steps_lost": int,
    "disk_restore_ms": (int, float),
    "disk_replay_ms": (int, float),
    "disk_recovery_ms": (int, float),
    "disk_steps_lost": int,
    "snapshot_overhead_ratio": (int, float),
    "guarded_step_ms_p50": (int, float),
    "unguarded_step_ms_p50": (int, float),
    "crash_step": int,
    "state_bytes": int,
    "snap_every": int,
    "disk_every": int,
    "smoke": bool,
    "platform": str,
    "parallel_host": bool,
    "host_cores": int,
}

# acceptance floors (ISSUE 20): recovering the SAME injected crash from
# the buddy's RAM snapshot (restore + zero replay) must cost <= 0.5x the
# disk ladder rung (restore newest ckpt-N + re-train the steps since),
# must lose STRICTLY fewer steps, and arming the hot-spare agent must
# keep the steady-state guarded step p50 within 1.05x of unguarded.
# The overhead floor needs the stream thread to actually OVERLAP the
# step, so it is enforced only on a `parallel_host` (>= 2 cores): on a
# 1-core timesliced box total work is conserved and the ratio measures
# the OS scheduler, not the overlap (the data/disagg bench convention) —
# there the overhead is recorded observationally under a loose sanity
# cap.  The latency gate applies everywhere: both recovery lanes are
# serial, so timeslicing is fair to them.
# FLAGS_hot_spare=0 bitwise identity is gated in tests/test_hot_spare.py.
_RECOVERY_MAX_LATENCY_RATIO = 0.5
_RECOVERY_MAX_OVERHEAD = 1.05
_RECOVERY_MAX_OVERHEAD_TIMESLICED = 1.5


def check_recovery_bench(run):
    """Schema + latency/steps-lost/overhead gates for
    benchmarks/recovery_bench.py (RECOVERY_BENCH.json)."""
    errors = []
    for key, types in _RECOVERY_SCHEMA.items():
        if key not in run:
            errors.append(f"missing key {key!r}")
        elif run[key] is None or not isinstance(run[key], types):
            errors.append(f"{key!r} has type {type(run[key]).__name__}, "
                          f"expected {types}")
    if not errors:
        for k in ("peer_restore_ms", "disk_restore_ms",
                  "disk_recovery_ms", "guarded_step_ms_p50",
                  "unguarded_step_ms_p50", "state_bytes"):
            if run[k] <= 0:
                errors.append(f"{k} must be positive, got {run[k]!r}")
        if run["latency_ratio"] > _RECOVERY_MAX_LATENCY_RATIO:
            errors.append(
                f"latency_ratio {run['latency_ratio']:.3f} > "
                f"{_RECOVERY_MAX_LATENCY_RATIO} — peer restore did not "
                "beat the disk rung by 2x on the same failure")
        if run["peer_steps_lost"] >= run["disk_steps_lost"]:
            errors.append(
                f"peer_steps_lost {run['peer_steps_lost']} >= "
                f"disk_steps_lost {run['disk_steps_lost']} — the RAM "
                "replica was no fresher than the newest ckpt-N")
        if run["parallel_host"] and \
                run["snapshot_overhead_ratio"] > _RECOVERY_MAX_OVERHEAD:
            errors.append(
                f"snapshot_overhead_ratio "
                f"{run['snapshot_overhead_ratio']:.3f} > "
                f"{_RECOVERY_MAX_OVERHEAD} on a parallel host "
                f"({run['host_cores']} cores) — arming the agent "
                "slowed the guarded training step")
        if not run["parallel_host"] and \
                run["snapshot_overhead_ratio"] > \
                _RECOVERY_MAX_OVERHEAD_TIMESLICED:
            errors.append(
                f"snapshot_overhead_ratio "
                f"{run['snapshot_overhead_ratio']:.3f} > sanity cap "
                f"{_RECOVERY_MAX_OVERHEAD_TIMESLICED} even for a "
                "timesliced 1-core host — the snapshot path is doing "
                "way too much synchronous work")
    if errors:
        print("recovery_ladder schema check FAILED:")
        for e in errors:
            print(f"  - {e}")
        return 1
    gated = "" if run["parallel_host"] else \
        f" (observational: {run['host_cores']}-core host)"
    print(f"recovery_ladder schema OK: peer {run['peer_recovery_ms']:.0f}ms "
          f"({run['peer_steps_lost']} steps lost) vs disk "
          f"{run['disk_recovery_ms']:.0f}ms ({run['disk_steps_lost']} "
          f"lost), ratio {run['latency_ratio']:.2f}, snapshot overhead "
          f"{run['snapshot_overhead_ratio']:.3f}x{gated}")
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("bench_json")
    ap.add_argument("--baseline", default=os.path.join(
        os.path.dirname(__file__), "..", "BENCH_BASELINE.json"))
    ap.add_argument("--threshold", type=float, default=0.9,
                    help="fail if value < threshold * recorded best")
    args = ap.parse_args()

    with open(args.bench_json) as f:
        run = json.load(f)
    if "parsed" in run:          # driver-recorded BENCH_rN.json wrapper
        run = run["parsed"]
    if str(run.get("metric", "")).startswith("recovery"):
        return check_recovery_bench(run)
    if str(run.get("metric", "")).startswith("data_pipeline"):
        return check_data_bench(run)
    if str(run.get("metric", "")).startswith("eager_op_dispatch"):
        return check_eager_overhead(run)
    if str(run.get("metric", "")).startswith("train_step"):
        return check_train_step_bench(run)
    if str(run.get("metric", "")).startswith("mfu_sweep"):
        return check_mfu_sweep(run)
    if str(run.get("metric", "")).startswith("serving_disagg"):
        return check_disagg_bench(run)
    if str(run.get("metric", "")).startswith("serving_fleet"):
        return check_fleet_bench(run)
    if str(run.get("metric", "")).startswith("serving_lora"):
        return check_lora_bench(run)
    if str(run.get("metric", "")).startswith("serving_tick"):
        return check_tick_bench(run)
    if str(run.get("metric", "")).startswith("serving_speculative"):
        return check_spec_bench(run)
    if str(run.get("metric", "")).startswith("serving_paged"):
        return check_paged_bench(run)
    if str(run.get("metric", "")).startswith("serving_"):
        return check_serving_bench(run)
    value = float(run["value"])
    platform = "cpu" if "cpu" in run.get("metric", "") else "tpu"

    try:
        with open(args.baseline) as f:
            base = json.load(f)
    except OSError:
        print("no baseline recorded — pass (first run)")
        return 0
    entry = base.get(platform) or {}
    best = entry.get("tokens_per_sec")
    if not best:
        print(f"no {platform} baseline recorded — pass")
        return 0
    ratio = value / best
    print(f"{run['metric']}: {value:.1f} vs best {best:.1f} "
          f"(ratio {ratio:.3f}, threshold {args.threshold})")
    if ratio < args.threshold:
        print("benchmark regression gate FAILED")
        return 1
    print("benchmark gate passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
