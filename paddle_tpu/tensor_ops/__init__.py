from . import math, reduction, linalg, manipulation, logic, search, creation, random  # noqa: F401
