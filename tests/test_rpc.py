"""RPC agent (reference: python/paddle/distributed/rpc/rpc.py +
paddle/fluid/distributed/rpc/rpc_agent.cc; VERDICT: the path had no
coverage)."""
import multiprocessing as mp
import os

import pytest


def _worker_main(master, q):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    from paddle_tpu.distributed import rpc
    rpc.init_rpc("worker1", rank=1, world_size=2, master_endpoint=master)
    # wait until master calls us, then exit on its signal
    q.get(timeout=60)
    rpc.shutdown()


def _double(x):
    return 2 * x


def _boom():
    raise ValueError("remote failure")


def test_rpc_cross_process():
    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.launch.context import free_port
    master = f"127.0.0.1:{free_port()}"
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    old = {k: os.environ.get(k) for k in ("JAX_PLATFORMS",
                                          "PALLAS_AXON_POOL_IPS")}
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    proc = ctx.Process(target=_worker_main, args=(master, q))
    try:
        proc.start()
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    try:
        rpc.init_rpc("master", rank=0, world_size=2,
                     master_endpoint=master)
        # wait for the worker to register
        import time
        for _ in range(100):
            if "worker1" in {w.name for w in rpc.get_all_worker_infos()}:
                break
            time.sleep(0.2)
        infos = {w.name for w in rpc.get_all_worker_infos()}
        assert {"master", "worker1"} <= infos

        assert rpc.rpc_sync("worker1", _double, args=(21,)) == 42
        fut = rpc.rpc_async("worker1", _double, args=(5,))
        assert fut.result(timeout=30) == 10
        with pytest.raises(ValueError, match="remote failure"):
            rpc.rpc_sync("worker1", _boom)
        assert rpc.get_worker_info("worker1").rank == 1
        assert rpc.get_current_worker_info().name == "master"
    finally:
        q.put("done")
        proc.join(timeout=30)
        rpc.shutdown()
    assert proc.exitcode == 0
