"""One compiled program per scheduler tick (ISSUE 13).

PR 8 fused the whole training step into ONE donated-buffer jit program
and the Python-dispatch ceiling disappeared (4.18x).  The serving
scheduler iteration was still on the wrong side of that line:
``Engine._decode_step`` orchestrated the batched decode call, per-slot
host sampling (an ``np.asarray`` host sync per non-greedy slot per
iteration), offset/page-table flushes, and eos/length bookkeeping as
separate compiled calls with host round-trips between them — at high
occupancy the Python glue WAS the tokens/sec ceiling.

:class:`CompiledServingTick` captures the full tick as one program over
device-resident scheduler state:

- **state** — last tokens, generated-token ring buffers, per-slot
  counts/limits/eos ids, alive masks, cache offsets, per-slot sampling
  params (temperature/top-k/top-p/repetition-penalty vectors + seen
  masks + per-request RNG keys) all live as fixed-shape device arrays;
  the page pools and page table are the ``PagedKVCache``'s own device
  arrays, donated through the program each tick;
- **program** — one jitted call runs the [num_slots, 1] model forward
  (replayed through the shared two-phase capture core,
  ``framework/capture.py``), the vectorized per-slot logit-processor
  chain + sampling, the token append, eos/max-length finish codes, and
  the offset advance; the batched-argmax fast path compiles its own
  leaner variant so an all-greedy batch stays bitwise the old argmax;
- **host boundary** — per tick the host reads back ONE small
  ``[num_slots]`` finish-code vector.  Request admission and completion
  (and deadline eviction — a wall-clock decision) are the only times
  token buffers cross to the host.

Fallbacks latch the uncompiled scheduler byte-identically and warn once
with the typed :class:`TickFallbackWarning`: flag off
(``FLAGS_compiled_tick``), slot (non-paged) cache layout, speculative
decoding configured, layer hooks installed, and non-greedy sampling
without a per-request ``SamplingParams.seed`` (the vectorized chain
derives each slot's stream from ``fold_in(PRNGKey(seed), n_generated)``
— without a seed the old path's global-RNG draws cannot be reproduced
in-program).  See docs/SERVING.md "Compiled scheduler tick".
"""
from __future__ import annotations

import time
import warnings

import numpy as np
import jax
import jax.numpy as jnp

from . import stats
from ..core import state as _state
from ..core.tensor import Tensor
from ..framework.capture import (TRACE_LOCK, BindTracer, Installed,
                                 TraceEscape, run_discovery)
from ..utils.flags import flag as _flag


class TickFallbackWarning(UserWarning):
    """Warned once per reason when the compiled serving tick cannot host
    the current scheduler state and the engine latches the uncompiled
    (byte-identical) iteration instead."""


# ---------------------------------------------------------------------------
# vectorized per-slot sampling chain (shared by the compiled tick and the
# uncompiled lane's fused per-iteration sampling call)
# ---------------------------------------------------------------------------

def process_logits_rows(logits, temp, top_k, top_p, penalty, seen):
    """Per-row logit-processor chain over a whole batch at once —
    ``models.generation.apply_logit_processors`` semantics (HF order:
    repetition penalty → temperature → top-k → top-p), vectorized with
    per-slot knob vectors so every slot's chain runs inside one program.

    ``logits`` [ns, V] float; ``temp`` [ns] (0.0 = greedy: the row
    bypasses temperature/top-k/top-p and keeps its penalized logits for
    the argmax); ``top_k`` [ns] int32 (0 = off); ``top_p`` [ns] (>= 1.0
    = off); ``penalty`` [ns] (1.0 = off); ``seen`` [ns, V] bool emitted
    mask.  Off knobs reproduce the reference chain's skipped branches
    exactly (the k-th/threshold values are the same elements the
    reference's ``topk``/``masked_fill`` select)."""
    neg_inf = jnp.asarray(float("-inf"), logits.dtype)
    vocab = logits.shape[-1]
    pen = penalty[:, None].astype(logits.dtype)
    pen_on = (penalty != 1.0)[:, None]
    pos = logits > 0
    penalized = jnp.where(pos, logits / pen, logits * pen)
    logits = jnp.where(pen_on & seen, penalized, logits)
    greedy = temp == 0.0
    safe_t = jnp.where(greedy, 1.0, temp).astype(logits.dtype)
    x = logits / safe_t[:, None]
    # top-k: threshold at the row's k-th largest value (same element
    # topk()'s vals[:, -1] selects), k clamped to the vocab
    k = jnp.clip(top_k.astype(jnp.int32), 0, vocab)
    sorted_desc = jnp.sort(x, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(sorted_desc,
                              jnp.clip(k - 1, 0, vocab - 1)[:, None],
                              axis=-1)
    x = jnp.where((k > 0)[:, None] & (x < kth), neg_inf, x)
    # top-p: smallest prefix of the sorted row whose EXCLUSIVE mass is
    # below top_p survives (the first token always does)
    p_on = (top_p < 1.0)[:, None]
    sorted_p = jnp.sort(x, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_p, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_p[:, None].astype(probs.dtype)
    minv = jnp.min(jnp.where(keep, sorted_p,
                             jnp.asarray(float("inf"), x.dtype)),
                   axis=-1, keepdims=True)
    x = jnp.where(p_on & (x < minv), neg_inf, x)
    return jnp.where(greedy[:, None], logits, x)


def choose_tokens(logits, temp, top_k, top_p, penalty, seen, keys, counts):
    """[ns, V] logits → [ns] int32 next tokens under per-slot params.

    Greedy rows (temp == 0) take the argmax of their (penalized) logits
    — bitwise the reference ``sample_next_token`` path.  Sampled rows
    draw ``jax.random.categorical`` from the processed logits under the
    slot's own key stream ``fold_in(base_key, n_generated)`` — the
    per-request seed makes the stream identical whichever lane (fused
    uncompiled call or compiled tick) executes the draw."""
    processed = process_logits_rows(logits, temp, top_k, top_p, penalty,
                                    seen)
    greedy_tok = jnp.argmax(processed, axis=-1).astype(jnp.int32)

    def draw(key, count, row):
        return jax.random.categorical(
            jax.random.fold_in(key, count), row)

    sampled_tok = jax.vmap(draw)(keys, counts, processed).astype(jnp.int32)
    return jnp.where(temp == 0.0, greedy_tok, sampled_tok)


@jax.jit
def fused_sample_call(logits, temp, top_k, top_p, penalty, seen, keys,
                      counts):
    """The uncompiled lane's ONE per-iteration sampling program: every
    active slot's processor chain + draw in a single jitted call instead
    of a host round-trip per non-greedy slot (ISSUE 13 satellite)."""
    return choose_tokens(logits, temp, top_k, top_p, penalty, seen,
                         keys, counts)


def sampling_hostable(sp):
    """Whether the vectorized chain can host this request's sampling:
    greedy always (penalty included — the chain penalizes before the
    argmax exactly like ``_sample_row``); non-greedy only with a
    per-request ``seed`` (the in-program stream is key-derived — global
    framework-RNG draws cannot be replayed inside one program)."""
    return sp.greedy or sp.seed is not None


def request_key(sp):
    """[2] uint32 base key for a seeded request's sampling stream."""
    return np.asarray(jax.random.PRNGKey(int(sp.seed)))


# ---------------------------------------------------------------------------
# the compiled tick
# ---------------------------------------------------------------------------

class CompiledServingTick:
    """Owns the device-resident scheduler state and the per-mode jitted
    tick programs for one :class:`~paddle_tpu.serving.engine.Engine`.

    ``step()`` runs one compiled tick and returns True, or returns False
    after latching/flushing so the engine's uncompiled iteration (the
    byte-identical fallback) runs instead."""

    def __init__(self, engine):
        self.eng = engine
        self._built = False
        self._disabled = None          # permanent fallback reason
        self._warned = set()           # reason kinds already warned
        self._caps = []                # captured model tensors (params)
        self._jits = {}                # (mode, donating) -> jitted fn
        self._dev = None               # device state dict
        self._rep = {}                 # slot -> req at last rebuild
        self._mut_seen = -1            # engine mutation counter synced
        self._h_counts = None          # host mirror of generated counts
        self._ahead = False            # device tokens not yet on host
        self._sublayers = None
        # static blockers (cache layout, speculation) are known at
        # construction: warn right away — an all-greedy speculative
        # engine never even consults the tick (the spec step runs), so
        # an iteration-time warning would stay silent forever
        blk = self._static_blocker()
        if blk is not None:
            self._note_fallback(*blk)

    # ------------------------------------------------------------------
    # eligibility / fallback accounting
    # ------------------------------------------------------------------

    def _note_fallback(self, kind, reason, permanent=False):
        stats.incr("tick.fallbacks")
        if permanent:
            self._disabled = reason
        if kind not in self._warned:
            self._warned.add(kind)
            warnings.warn(
                f"compiled serving tick disabled ({reason}); running the "
                "uncompiled scheduler iteration", TickFallbackWarning)

    def _static_blocker(self):
        """(kind, reason, permanent) for configuration the tick can
        never host, known at engine start; None otherwise."""
        eng = self.eng
        if not eng._paged:
            return ("layout", "kv_layout='slots' — the compiled tick "
                    "runs on the paged cache", True)
        if eng._spec:
            return ("spec", "speculative decoding configured "
                    "(draft_model + speculation_k > 0)", True)
        return None

    def _blocker(self):
        """(kind, reason, permanent) for the current scheduler state, or
        None when this tick can run compiled."""
        eng = self.eng
        blk = self._static_blocker()
        if blk is not None:
            return blk
        if _state.STATE.tracer is not None:
            return ("tracer", "a framework tracer is active", False)
        if self._sublayers is None and hasattr(eng.model, "sublayers"):
            self._sublayers = list(
                eng.model.sublayers(include_self=True))
        for layer in self._sublayers or ():
            if layer._forward_pre_hooks or layer._forward_post_hooks:
                return ("hooks", "layer forward hooks installed", False)
        for req in eng._active.values():
            if not sampling_hostable(req.sampling):
                return ("sampling", "non-greedy sampling without a "
                        "per-request SamplingParams.seed — the "
                        "vectorized in-program chain cannot reproduce "
                        "global-RNG draws", False)
        return None

    @property
    def fallback_reason(self):
        return self._disabled

    # ------------------------------------------------------------------
    # capture (phase 1): discover the model forward's reads
    # ------------------------------------------------------------------

    def _capture(self):
        eng = self.eng
        cache = eng.cache
        views = [dict(lay) for lay in cache.layer_caches()]
        tok = Tensor(np.zeros((cache.num_slots, 1), np.int32))
        exclude = {id(tok)}
        for view in views:
            for v in view.values():
                if isinstance(v, Tensor):
                    exclude.add(id(v))
        with TRACE_LOCK:
            # discovery runs under the SAME adapter activation as the
            # live tick, so the pool's A/B stacks, scales, and per-slot
            # index vector are read through op dispatch and join the
            # re-gathered captures — hot-loads and admission re-points
            # flow into the compiled program with no retrace, and the
            # identity slot 0 keeps base-only batches on this one program
            def _fwd():
                with eng._lora_ctx():
                    return eng.model(tok, caches=views)
            disc = run_discovery(_fwd)
        if disc.uses_rng:
            raise TraceEscape(
                "model forward draws framework RNG (dropout in eval?) — "
                "the tick program feeds randomness only through "
                "per-slot sampling keys")
        self._caps = [t for t in disc.capture_list
                      if id(t) not in exclude]
        self._built = True

    # ------------------------------------------------------------------
    # the traced tick body (phase 2)
    # ------------------------------------------------------------------

    def _traced(self, mode, pools, pt, off, last, counts, alive, seen,
                out, limits, eos, temp, topk, topp, pen, keys, caps):
        eng = self.eng
        cache = eng.cache
        quant = cache.quant_dtype is not None
        tracer = BindTracer(rng_key=None)
        _state.STATE.tracer = tracer
        try:
            with Installed(list(zip(self._caps, caps))):
                # dead/prefilling rows feed token 0 exactly like the
                # uncompiled step's zero-filled tok_in; their scratch
                # writes are causally masked (and prefill re-writes its
                # positions next chunk) either way
                tok_in = jnp.where(alive, last,
                                   jnp.zeros_like(last))[:, None]
                pt_t, off_t = Tensor(pt), Tensor(off)
                views = []
                i = 0
                for _ in range(len(cache.layers)):
                    view = {"k_pool": Tensor(pools[i]),
                            "v_pool": Tensor(pools[i + 1]),
                            "page_table": pt_t, "offset": off_t,
                            "page_size": cache.page_size}
                    i += 2
                    if quant:
                        view["k_scale"] = Tensor(pools[i])
                        view["v_scale"] = Tensor(pools[i + 1])
                        i += 2
                    views.append(view)
                with eng._lora_ctx():
                    logits_t = eng.model(Tensor(tok_in), caches=views)
                logits = logits_t._data_[:, -1, :]
                new_pools = []
                for view in views:
                    new_pools += [view["k_pool"]._data_,
                                  view["v_pool"]._data_]
                    if quant:
                        new_pools += [view["k_scale"]._data_,
                                      view["v_scale"]._data_]
        finally:
            _state.STATE.tracer = None
            tracer.rollback_mutations()

        ns = logits.shape[0]
        if mode == "greedy":
            # the batched-argmax fast path, bitwise the uncompiled
            # lane's S.argmax over raw last-position logits
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            tok = choose_tokens(logits, temp, topk, topp, pen, seen,
                                keys, counts)
        tok = jnp.where(alive, tok, last)
        rows = jnp.arange(ns)
        idx = jnp.clip(counts, 0, out.shape[1] - 1)
        new_out = out.at[rows, idx].set(
            jnp.where(alive, tok, out[rows, idx]))
        new_seen = seen.at[rows, tok].set(seen[rows, tok] | alive)
        new_counts = counts + alive.astype(counts.dtype)
        eos_hit = alive & (eos >= 0) & (tok == eos)
        len_hit = alive & (new_counts >= limits)
        fin = jnp.where(eos_hit, 1,
                        jnp.where(len_hit, 2, 0)).astype(jnp.int32)
        new_alive = alive & (fin == 0)
        new_last = jnp.where(alive, tok, last)
        new_off = off + alive.astype(off.dtype)
        return (tuple(new_pools), new_off, new_last, new_counts,
                new_alive, new_seen, new_out, fin)

    def _build_jit(self, mode, donating):
        from ..core.op_cache import ensure_compile_cache
        ensure_compile_cache()      # tier-2 persistent XLA compile cache

        def fn(pools, pt, off, last, counts, alive, seen, out, limits,
               eos, temp, topk, topp, pen, keys, caps):
            return self._traced(mode, pools, pt, off, last, counts,
                                alive, seen, out, limits, eos, temp,
                                topk, topp, pen, keys, caps)

        # the pools (the big buffers) are donated and replaced in place
        # each tick.  The small token/seen state buffers are NOT — on
        # this jaxlib, donating them alongside the persistent
        # compilation cache (conftest arms it suite-wide) corrupts the
        # CPU client's buffer bookkeeping and aborts the process; their
        # per-tick copy is a few KB, noise next to the pool bytes.
        donate = (0,) if donating else ()
        return jax.jit(fn, donate_argnums=donate)

    # ------------------------------------------------------------------
    # host <-> device state sync
    # ------------------------------------------------------------------

    def flush_to_host(self):
        """Materialize device-side token progress back into the request
        objects (the step the uncompiled lane needs before it can take
        over mid-request).  Token/seen/last bookkeeping only — stats
        were already counted per tick."""
        if not self._ahead or self._dev is None:
            return
        self._ahead = False
        eng = self.eng
        out_np = np.asarray(self._dev["out"])
        for slot, req in self._rep.items():
            if eng._active.get(slot) is not req:
                continue
            have = len(req.tokens)
            count = int(self._h_counts[slot])
            for tok in out_np[slot, have:count].tolist():
                req.tokens.append(int(tok))
                req.last_token = int(tok)
                if req.seen is not None:
                    req.seen[int(tok)] = True
        self._dev = None            # force a rebuild before the next tick

    def _rebuild(self):
        """(Re)upload the scheduler state from the request objects —
        the admission/completion host boundary."""
        eng = self.eng
        cache = eng.cache
        ns = cache.num_slots
        vocab = eng.cfg.vocab_size
        width = eng.max_len
        last = np.zeros(ns, np.int32)
        counts = np.zeros(ns, np.int32)
        limits = np.full(ns, np.iinfo(np.int32).max, np.int32)
        eos = np.full(ns, -1, np.int32)
        alive = np.zeros(ns, bool)
        temp = np.zeros(ns, np.float32)
        topk = np.zeros(ns, np.int32)
        topp = np.ones(ns, np.float32)
        pen = np.ones(ns, np.float32)
        keys = np.zeros((ns, 2), np.uint32)
        seen = np.zeros((ns, vocab), bool)
        out = np.zeros((ns, width), np.int32)
        for slot, req in eng._active.items():
            alive[slot] = True
            last[slot] = req.last_token
            n = len(req.tokens)
            counts[slot] = n
            out[slot, :n] = req.tokens
            limits[slot] = min(req.max_new_tokens,
                               eng.max_len - req.prompt.size)
            if req.eos_token_id is not None:
                eos[slot] = req.eos_token_id
            sp = req.sampling
            temp[slot] = sp.temperature
            topk[slot] = sp.top_k or 0
            if sp.top_p is not None:
                topp[slot] = sp.top_p
            if sp.repetition_penalty is not None:
                pen[slot] = sp.repetition_penalty
            if not sp.greedy and sp.seed is not None:
                keys[slot] = request_key(sp)
            if req.seen is not None:
                seen[slot] = req.seen
        self._dev = {
            "last": jnp.asarray(last), "counts": jnp.asarray(counts),
            "limits": jnp.asarray(limits), "eos": jnp.asarray(eos),
            "alive": jnp.asarray(alive), "temp": jnp.asarray(temp),
            "topk": jnp.asarray(topk), "topp": jnp.asarray(topp),
            "pen": jnp.asarray(pen), "keys": jnp.asarray(keys),
            "seen": jnp.asarray(seen), "out": jnp.asarray(out),
            "off": None,
        }
        self._h_counts = counts.copy()
        self._rep = dict(eng._active)
        self._mut_seen = eng._mut

    # ------------------------------------------------------------------
    # one tick
    # ------------------------------------------------------------------

    def step(self):
        eng = self.eng
        if not _flag("FLAGS_compiled_tick", True):
            self.flush_to_host()        # flag flipped mid-run
            return False
        if self._disabled is not None:
            stats.incr("tick.fallbacks")
            return False
        blk = self._blocker()
        if blk is not None:
            self.flush_to_host()
            self._note_fallback(blk[0], blk[1], blk[2])
            return False
        if not self._built:
            try:
                self._capture()
            except TraceEscape as e:
                self._note_fallback("capture", str(e), True)
                return False
            except Exception as e:  # noqa: BLE001 — any failure → eager
                self._note_fallback(
                    "capture", f"capture failed: "
                    f"{type(e).__name__}: {e}", True)
                return False
        if eng._mut != self._mut_seen or self._dev is None:
            self.flush_to_host()
            self._rebuild()
        return self._run()

    def _run(self):
        eng = self.eng
        cache = eng.cache
        t0 = time.monotonic()
        active = dict(eng._active)
        n_active = len(active)
        eng._max_active = max(eng._max_active, n_active)
        stats.set_value("max_active_slots", eng._max_active)
        # page-by-page growth exactly like the uncompiled step: the
        # admission reservation guarantees the host-side pop succeeds
        for slot in active:
            cache.ensure_capacity(slot, int(cache.offsets[slot]))
        # page table / offsets: host mutations (admission, release,
        # growth) flow through the cache's own lazy flush; steady-state
        # ticks ride the previous program's device outputs
        if cache._dirty or self._dev["off"] is None:
            lay0 = cache.layer_caches()[0]
            pt = lay0["page_table"]._data_
            off = lay0["offset"]._data_
        else:
            pt = cache.layers[0]["page_table"]._data_
            off = self._dev["off"]
        quant = cache.quant_dtype is not None
        mode = "greedy" if all(
            r.sampling.greedy and not r.sampling.uses_penalty
            for r in active.values()) else "mixed"
        donating = bool(_flag("FLAGS_jit_donate_buffers", True))
        key = (mode, donating)
        first = key not in self._jits
        if first:
            self._jits[key] = self._build_jit(mode, donating)
        jit = self._jits[key]
        d = self._dev
        from ..profiler import RecordEvent
        rids = sorted(r.id for r in active.values())
        try:
            # TRACE_LOCK covers reading the (possibly shared) parameter
            # slots AND the program call: while ANOTHER engine's tick
            # traces, those slots hold tracer arrays — gathering them
            # here would bake a leaked tracer into this engine's call
            with TRACE_LOCK, \
                    RecordEvent("serving::decode",
                                args={"request_ids": rids,
                                      "compiled_tick": True}):
                pools = []
                for lay in cache.layers:
                    pools += [lay["k_pool"]._data_, lay["v_pool"]._data_]
                    if quant:
                        pools += [lay["k_scale"]._data_,
                                  lay["v_scale"]._data_]
                caps = tuple(t._data_ for t in self._caps)
                (new_pools, new_off, new_last, new_counts, new_alive,
                 new_seen, new_out, fin) = jit(
                    tuple(pools), pt, off, d["last"], d["counts"],
                    d["alive"], d["seen"], d["out"], d["limits"],
                    d["eos"], d["temp"], d["topk"], d["topp"], d["pen"],
                    d["keys"], caps)
            fin_np = np.asarray(fin)    # the per-tick host sync point
        except TraceEscape as e:
            self.flush_to_host()
            self._dev = None
            self._note_fallback("trace", str(e), True)
            return False
        except Exception as e:  # noqa: BLE001
            burned = any(
                getattr(a, "is_deleted", lambda: False)()
                for lay in cache.layers for a in
                (lay["k_pool"]._data_, lay["v_pool"]._data_))
            if first and not burned:
                # the model body cannot be traced (host reads of raw
                # array slots, data-dependent control flow): latch the
                # uncompiled scheduler permanently — serving never dies
                # on the compiler
                self.flush_to_host()
                self._dev = None
                self._note_fallback(
                    "trace", f"tick trace/compile failed: "
                    f"{type(e).__name__}: {e}", True)
                return False
            # a post-donation execution failure poisoned the pools —
            # propagate so the scheduler's restart wrapper rebuilds the
            # cache (the same crash semantics as any step failure)
            raise
        # adopt the functionally-updated pools + offsets back into the
        # cache (device stays current; the host offset mirror advances
        # in lockstep so fallbacks/admission see the truth)
        offsets_np = cache.offsets.copy()
        offsets_np[list(active)] += 1
        cache.absorb_tick(new_pools, new_off, offsets_np)
        d.update(off=new_off, last=new_last, counts=new_counts,
                 alive=new_alive, seen=new_seen, out=new_out)
        self._h_counts[list(active)] += 1
        self._ahead = True

        wall_ms = (time.monotonic() - t0) * 1e3
        stats.observe("decode_ms", wall_ms)
        stats.incr("decode_steps")
        stats.incr("tick.compiled_hits")
        stats.incr("slot_steps", cache.num_slots)
        stats.incr("slot_steps_active", n_active)
        stats.incr("tokens_generated", n_active)

        now = time.monotonic()
        evict = eng.scfg.deadline_policy == "evict"
        out_np = None
        for slot, req in active.items():
            if evict and req.deadline is not None and now > req.deadline:
                # same per-token deadline granularity (and precedence
                # over eos/length) as the uncompiled _append_token
                from .api import DeadlineExceededError
                self.flush_to_host()
                eng._fail(req, DeadlineExceededError(
                    f"request {req.id} exceeded its deadline after "
                    f"{len(req.tokens)} token(s)"))
                stats.incr("requests_evicted_deadline")
                eng._release(req)
                continue
            code = int(fin_np[slot])
            if code == 0:
                continue
            if out_np is None:
                out_np = np.asarray(new_out)
            count = int(self._h_counts[slot])
            req.tokens = [int(t) for t in out_np[slot, :count]]
            req.last_token = req.tokens[-1]
            eng._complete(req, "eos" if code == 1 else "length", now)
            eng._release(req)
        stats.set_value("active_slots", len(eng._active))
        return True
