from .moe_layer import MoELayer, ExpertFFN  # noqa: F401
from .gate import BaseGate, NaiveGate, GShardGate, SwitchGate  # noqa: F401
from .grad_clip import ClipGradForMOEByGlobalNorm  # noqa: F401
