"""Prefill/decode disaggregation with live KV-page migration (ISSUE 14):
page export/adopt round-trips (fp32 + int8, attention bit-equal), the
rpc raw-bytes fast path, engine-level handoff/resume/fallback, prefix
-tree copy semantics across replicas, the role-aware router, and the
drain-time migration + role-flip rejoin protocol.  Thread-mode replicas
keep these fast; the process-mode perf gate lives in
benchmarks/serving_fleet_bench.py --workload disagg."""
import pickle
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import rpc
from paddle_tpu.distributed.rpc import rpc as rpc_mod
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.models import GPTForCausalLM, gpt_config
from paddle_tpu.serving import (Engine, PagedKVCache, PageMigrationError,
                                ReplicaConfig, ReplicaServer,
                                RouterConfig, SamplingParams,
                                ServingConfig, ServingRouter,
                                serving_stats)
from paddle_tpu.serving import migration


def _np(t):
    return np.asarray(t._data_)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_config(
        "gpt2-124m", num_layers=2, hidden_size=64, num_heads=4,
        vocab_size=256, max_seq_len=64))
    m.eval()
    return m


def _prompts(lens, seed=0, vocab=256):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (n,)).astype("int32") for n in lens]


def _ref_greedy(model, prompt, max_new):
    ids = model.generate(paddle.to_tensor(prompt[None, :]),
                         max_new_tokens=max_new, temperature=0.0)
    return _np(ids)[0, prompt.size:]


def _fill_cache(cache, rng, dtype):
    """Random recognizable contents in every pool page (and scale)."""
    import jax.numpy as jnp
    from paddle_tpu.core.tensor import Tensor
    for lay in cache.layers:
        shp = lay["k_pool"]._data_.shape
        if dtype == "int8":
            lay["k_pool"] = Tensor(jnp.asarray(
                rng.integers(-127, 127, shp), jnp.int8))
            lay["v_pool"] = Tensor(jnp.asarray(
                rng.integers(-127, 127, shp), jnp.int8))
            sshp = lay["k_scale"]._data_.shape
            lay["k_scale"] = Tensor(jnp.asarray(
                rng.random(sshp), jnp.float32))
            lay["v_scale"] = Tensor(jnp.asarray(
                rng.random(sshp), jnp.float32))
        else:
            lay["k_pool"] = Tensor(jnp.asarray(
                rng.normal(size=shp), jnp.float32))
            lay["v_pool"] = Tensor(jnp.asarray(
                rng.normal(size=shp), jnp.float32))


# ------------------------------------------------------------------
# page serialization round trip
# ------------------------------------------------------------------

@pytest.mark.parametrize("dtype", ["float32", "int8"])
def test_page_payload_roundtrip_bitwise(dtype):
    """export_slot -> raw frames -> unpack -> adopt_pages lands every
    page (and per-page scale) bit-exact in the receiving pool."""
    rng = np.random.default_rng(0)
    a = PagedKVCache(2, 2, 64, 2, 4, page_size=16, dtype=dtype)
    slot = a.allocate(4)
    a.ensure_capacity(slot, 47)           # 3 pages assigned
    a.set_offset(slot, 37)
    _fill_cache(a, rng, dtype)
    header, blobs = migration.export_slot(a, slot)
    assert header["num_pages"] == 3 and header["offset"] == 37
    # the frames are Blob-wrapped: pickling them must refuse
    with pytest.raises(TypeError, match="raw-bytes fast path"):
        pickle.dumps(blobs[0])
    pages = migration.unpack(header, *blobs)
    b = PagedKVCache(2, 2, 64, 2, 4, page_size=16, num_pages=8,
                     dtype=dtype)
    s2 = b.adopt_pages(1, pages["offset"], pages["k_pages"],
                       pages["v_pages"], pages["k_scales"],
                       pages["v_scales"])
    assert s2 is not None and int(b.offsets[s2]) == 37
    for li in range(2):
        for kind in ("k_pool", "v_pool"):
            pa = np.asarray(a.layers[li][kind]._data_)
            pb = np.asarray(b.layers[li][kind]._data_)
            for j in range(3):
                np.testing.assert_array_equal(
                    pb[b.table[s2, j]], pa[a.table[slot, j]])
        if dtype == "int8":
            for kind in ("k_scale", "v_scale"):
                sa = np.asarray(a.layers[li][kind]._data_)
                sb = np.asarray(b.layers[li][kind]._data_)
                for j in range(3):
                    np.testing.assert_array_equal(
                        sb[b.table[s2, j]], sa[a.table[slot, j]])
    # adopted pages are slot-private with the growth reservation intact
    assert b._shared[s2] == 0 and len(b._private[s2]) == 3
    assert b._reserved[s2] == 1


@pytest.mark.parametrize("dtype", ["float32", "int8"])
def test_adopted_pages_attention_bit_equal(dtype):
    """`paged_masked_multihead_attention` over the adopted pool reads
    bit-identically to the source pool — the engine's migrated-output
    bit-equality guarantee reduces to this."""
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.incubate.nn import functional as IF
    rng = np.random.default_rng(1)
    a = PagedKVCache(1, 2, 64, 2, 4, page_size=16, dtype=dtype)
    slot = a.allocate(4)
    a.ensure_capacity(slot, 40)
    a.set_offset(slot, 41)
    _fill_cache(a, rng, dtype)
    header, blobs = migration.export_slot(a, slot)
    pages = migration.unpack(header, *blobs)
    b = PagedKVCache(1, 2, 64, 2, 4, page_size=16, num_pages=9,
                     dtype=dtype)
    s2 = b.adopt_pages(0, pages["offset"], pages["k_pages"],
                       pages["v_pages"], pages["k_scales"],
                       pages["v_scales"])
    q = rng.normal(size=(2, 1, 4, 4)).astype(np.float32)
    k = rng.normal(size=(2, 1, 2, 4)).astype(np.float32)
    v = rng.normal(size=(2, 1, 2, 4)).astype(np.float32)
    outs = []
    for cache, s in ((a, slot), (b, s2)):
        lay = cache.layer_caches()[0]
        args = [Tensor(q), Tensor(k), Tensor(v), lay["k_pool"],
                lay["v_pool"], lay["page_table"], lay["offset"],
                cache.page_size]
        kw = {}
        if dtype == "int8":
            kw = {"k_scale": lay["k_scale"], "v_scale": lay["v_scale"]}
        res = IF.paged_masked_multihead_attention(*args, **kw)
        outs.append(_np(res[0])[s])
    np.testing.assert_array_equal(outs[0], outs[1])


def test_adopt_pages_backpressure_and_validation():
    rng = np.random.default_rng(2)
    a = PagedKVCache(2, 2, 64, 2, 4, page_size=16)
    slot = a.allocate(4)
    a.ensure_capacity(slot, 47)
    a.set_offset(slot, 40)
    _fill_cache(a, rng, "float32")
    header, blobs = migration.export_slot(a, slot)
    pages = migration.unpack(header, *blobs)
    # pool too small: None (backpressure), never a crash
    tiny = PagedKVCache(2, 1, 64, 2, 4, page_size=16, num_pages=2)
    assert tiny.adopt_pages(0, pages["offset"], pages["k_pages"],
                            pages["v_pages"]) is None
    # wrong layer count
    with pytest.raises(PageMigrationError, match="pool"):
        PagedKVCache(3, 2, 64, 2, 4, page_size=16).adopt_pages(
            0, pages["offset"], pages["k_pages"], pages["v_pages"])
    # wrong page size
    with pytest.raises(PageMigrationError, match="pool"):
        PagedKVCache(2, 2, 64, 2, 4, page_size=8).adopt_pages(
            0, pages["offset"], pages["k_pages"], pages["v_pages"])
    # scales against a float pool
    with pytest.raises(PageMigrationError, match="scales"):
        PagedKVCache(2, 2, 64, 2, 4, page_size=16).adopt_pages(
            0, pages["offset"], pages["k_pages"], pages["v_pages"],
            np.ones((2, 3, 16), np.float32),
            np.ones((2, 3, 16), np.float32))
    # offset past the migrated pages
    with pytest.raises(PageMigrationError, match="offset"):
        PagedKVCache(2, 2, 64, 2, 4, page_size=16).adopt_pages(
            0, 49, pages["k_pages"], pages["v_pages"])
    # wire-version guard
    bad = dict(header, version=99)
    with pytest.raises(PageMigrationError, match="wire version"):
        migration.unpack(bad, *blobs)


def test_prefix_tree_pages_migrate_as_copies():
    """Tree-owned (shared) pages export by value: the receiving slot
    owns plain private copies, and the sender's tree keeps its pages,
    refcounts and free-list accounting untouched."""
    rng = np.random.default_rng(3)
    a = PagedKVCache(1, 2, 64, 2, 4, page_size=16)
    slot = a.allocate(4)
    a.ensure_capacity(slot, 40)
    a.set_offset(slot, 41)
    _fill_cache(a, rng, "float32")
    shared_page = a.make_shared(slot, 0)     # tree takes page 0
    free_before = a.free_page_count
    header, blobs = migration.export_slot(a, slot)
    pages = migration.unpack(header, *blobs)
    b = PagedKVCache(1, 2, 64, 2, 4, page_size=16, num_pages=9)
    s2 = b.adopt_pages(0, pages["offset"], pages["k_pages"],
                       pages["v_pages"])
    # receiver: every adopted page is private, nothing shared
    assert b._shared[s2] == 0 and len(b._private[s2]) == 3
    # sender: the tree page never moved; releasing the slot returns
    # only the private pages, the shared one stays tree-owned
    a.release(slot)
    assert a.free_page_count == free_before + 2
    a.reclaim(shared_page)
    assert a.free_page_count == free_before + 3


# ------------------------------------------------------------------
# rpc raw-bytes fast path
# ------------------------------------------------------------------

def _blob_probe(small, blob, big_bytes):
    """rpc target (top-level: the wire pickles the callable)."""
    assert isinstance(blob, rpc.Blob), type(blob)
    assert isinstance(big_bytes, rpc.Blob), type(big_bytes)
    assert isinstance(small, bytes)
    arr = np.frombuffer(blob.data, np.float32)
    return {"nbytes": len(blob), "sum": float(arr.sum()),
            "big_head": big_bytes.tobytes()[:4], "small": small}


def test_rpc_raw_bytes_fast_path_roundtrip_and_no_copy():
    """bytes in == bytes out over the raw path; the send side passes
    the caller's own buffer (no copy: the sent memoryview wraps the
    original array); large bytes-like args auto-promote past
    RAW_THRESHOLD while small ones keep the pickle path."""
    srv = rpc.RpcServer("blob-probe")
    try:
        arr = np.arange(50000, dtype=np.float32)   # ~200 KB
        big = b"\x01\x02\x03\x04" * (rpc.RAW_THRESHOLD // 4 + 1)
        sent = []
        orig = rpc_mod._send_blob

        def spy(conn, blob):
            sent.append(blob)
            return orig(conn, blob)

        rpc_mod._send_blob = spy
        try:
            out = rpc.rpc_sync("blob-probe", _blob_probe,
                               args=(b"tiny", rpc.Blob(arr), big))
        finally:
            rpc_mod._send_blob = orig
        assert out["nbytes"] == arr.nbytes
        assert out["sum"] == float(arr.sum())
        assert out["big_head"] == b"\x01\x02\x03\x04"
        assert out["small"] == b"tiny"
        # explicit Blob + auto-promoted big bytes rode raw frames...
        assert len(sent) == 2
        # ...and the Blob frame IS the caller's buffer, not a copy
        assert sent[0].data.obj is arr
        # a Blob that leaks into pickle fails loudly, never silently
        with pytest.raises(TypeError, match="raw-bytes fast path"):
            pickle.dumps(rpc.Blob(arr))
        # non-contiguous buffers are refused up front
        with pytest.raises(ValueError, match="contiguous"):
            rpc.Blob(np.ones((8, 8), np.float32)[:, ::2])
    finally:
        srv.close()


# ------------------------------------------------------------------
# engine-level handoff / resume / fallback
# ------------------------------------------------------------------

def _local_migrator(target_engine, name="peer"):
    """Single-phase in-process migrator: unpack + resume on the target
    engine, return the completed payload."""
    def migrate(req, header, blobs, target):
        pages = migration.unpack(header, *blobs)
        fut = target_engine.submit_resume(
            req.prompt, list(req.tokens), pages,
            max_new_tokens=req.max_new_tokens, sampling=req.sampling,
            eos_token_id=req.eos_token_id, ttft_ms=req.ttft_ms)
        out = fut.result(timeout=120)
        return {"request_id": req.id, "replica": name,
                "output_ids": out.output_ids,
                "finish_reason": out.finish_reason}
    return migrate


def test_engine_handoff_bit_equal_greedy_and_seeded(model):
    """A handed-off request's full stream — first token from the
    prefill engine, the rest decoded from adopted pages — is bit-equal
    to a single-engine run, greedy AND seeded-sampled."""
    eng_p = Engine(model, ServingConfig(num_slots=2,
                                        role="prefill")).start()
    eng_d = Engine(model, ServingConfig(num_slots=2,
                                        role="decode")).start()
    eng_c = Engine(model, ServingConfig(num_slots=2)).start()
    try:
        eng_p.migrator = _local_migrator(eng_d)
        p = _prompts([9], seed=4)[0]
        out = eng_p.submit(p, max_new_tokens=8,
                           handoff={"name": "peer"}).result(timeout=180)
        np.testing.assert_array_equal(out.output_ids,
                                      _ref_greedy(model, p, 8))
        assert out.decoded_by == "peer"
        sp = SamplingParams(temperature=0.8, top_k=20, seed=123)
        out_m = eng_p.submit(p, max_new_tokens=8, sampling=sp,
                             handoff={"name": "peer"}).result(timeout=180)
        out_ref = eng_c.generate(p, max_new_tokens=8, sampling=sp,
                                 timeout=180)
        np.testing.assert_array_equal(out_m.output_ids,
                                      out_ref.output_ids)
        snap = serving_stats()
        assert snap["migrations"] >= 2
        assert snap["migration_pages_sent"] >= 2
        assert snap["migration_resumed_requests"] >= 2
    finally:
        eng_p.shutdown()
        eng_d.shutdown()
        eng_c.shutdown()


def test_engine_handoff_fallback_decodes_locally(model):
    """A dead migration target must cost latency, never the request:
    the engine falls back to its own decode batch, bit-equal."""
    eng = Engine(model, ServingConfig(num_slots=2,
                                      role="prefill")).start()
    try:
        def dead(req, header, blobs, target):
            raise ConnectionError("target died mid-transfer")
        eng.migrator = dead
        p = _prompts([7], seed=5)[0]
        out = eng.submit(p, max_new_tokens=6,
                         handoff={"name": "x"}).result(timeout=180)
        np.testing.assert_array_equal(out.output_ids,
                                      _ref_greedy(model, p, 6))
        assert out.decoded_by is None
        assert serving_stats()["migration_fallbacks"] >= 1
        assert eng.cache.pages_in_use == 0        # nothing leaked
    finally:
        eng.shutdown()


def test_submit_resume_validation(model):
    eng = Engine(model, ServingConfig(num_slots=2)).start()
    try:
        pages = {"offset": 5, "k_pages": np.zeros((2, 1, 16, 4, 16),
                                                  np.float32),
                 "v_pages": np.zeros((2, 1, 16, 4, 16), np.float32),
                 "k_scales": None, "v_scales": None}
        p = _prompts([5], seed=6)[0]
        with pytest.raises(ValueError, match="prior token"):
            eng.submit_resume(p, [], pages, max_new_tokens=4)
        with pytest.raises(ValueError, match="exhaust"):
            eng.submit_resume(p, [1, 2, 3, 4], pages, max_new_tokens=4)
        with pytest.raises(PageMigrationError, match="inconsistent"):
            eng.submit_resume(p, [1], dict(pages, offset=9),
                              max_new_tokens=4)
    finally:
        eng.shutdown()


# ------------------------------------------------------------------
# fleet: role-aware routing + migration over the rpc plane
# ------------------------------------------------------------------

_FAST = dict(heartbeat_interval_s=0.15, heartbeat_ttl_s=1.2)


class _RoleFleet:
    """Thread-mode disaggregated fleet: named (role, ServingConfig)
    replicas + a role-aware router on one TCPStore."""

    def __init__(self, model, specs, disaggregation=True):
        self.master = TCPStore(is_master=True)
        rcfg = ReplicaConfig(**_FAST).validate()
        self.reps = {}
        for name, scfg in specs.items():
            self.reps[name] = ReplicaServer(
                name, model, TCPStore("127.0.0.1", self.master.port),
                scfg, rcfg)
        self.router = ServingRouter(
            TCPStore("127.0.0.1", self.master.port),
            RouterConfig(heartbeat_ttl_s=1.2, poll_interval_s=0.1,
                         disaggregation=disaggregation)).start()
        deadline = time.monotonic() + 30
        while len(self.router.ring.members) < len(specs):
            assert time.monotonic() < deadline, \
                f"ring never filled: {self.router.replicas()}"
            time.sleep(0.05)

    def close(self):
        self.router.close()
        for rep in self.reps.values():
            rep.close()
        self.master.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def test_fleet_disagg_routes_prefill_and_migrates(model):
    """Requests land on the prefill replica, their pages migrate, and
    the decode replica finishes them — outputs bit-equal, counters and
    per-role telemetry advancing."""
    specs = {"rep-p": ServingConfig(num_slots=2, role="prefill"),
             "rep-d": ServingConfig(num_slots=4, role="decode")}
    with _RoleFleet(model, specs) as f:
        base = serving_stats()
        prompts = _prompts([5, 9, 6], seed=7)
        futs = [f.router.submit(p, max_new_tokens=5, session_id=i)
                for i, p in enumerate(prompts)]
        outs = [fut.result(timeout=300) for fut in futs]
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(o.output_ids,
                                          _ref_greedy(model, p, 5))
            assert o.decoded_by == "rep-d"
        snap = serving_stats()
        assert snap["migrations"] - base["migrations"] >= 3
        assert snap["migration_pages_sent"] >= 3
        assert snap["migration_resumed_requests"] >= 3
        assert snap["migration_fallbacks"] == base["migration_fallbacks"]
        # both engines returned every page
        assert f.reps["rep-p"].engine.cache.pages_in_use == 0
        assert f.reps["rep-d"].engine.cache.pages_in_use == 0
        # per-role routed series reached the registry
        from paddle_tpu import observability as obs
        prom = obs.render_prometheus()
        assert 'serving_router_requests_routed_role{role="prefill"}' \
            in prom


def test_fleet_disagg_no_decode_replica_degrades_to_local(model):
    """A prefill-only fleet (no decode target in the gossip) decodes
    locally — disaggregation degrades to mixed, never to a failure."""
    specs = {"rep-p": ServingConfig(num_slots=2, role="prefill")}
    with _RoleFleet(model, specs) as f:
        p = _prompts([6], seed=8)[0]
        out = f.router.submit(p, max_new_tokens=4,
                              session_id="solo").result(timeout=300)
        np.testing.assert_array_equal(out.output_ids,
                                      _ref_greedy(model, p, 4))
        assert out.decoded_by == "rep-p"


def test_fleet_disagg_off_ignores_roles(model):
    """RouterConfig.disaggregation=False: roles gossip but routing is
    the PR 9 ring order — no handoff, no migration, byte-identical
    symmetric behavior."""
    specs = {"rep-p": ServingConfig(num_slots=2, role="prefill"),
             "rep-d": ServingConfig(num_slots=2, role="decode")}
    with _RoleFleet(model, specs, disaggregation=False) as f:
        base = serving_stats()
        prompts = _prompts([5, 7, 6, 8], seed=9)
        futs = [f.router.submit(p, max_new_tokens=4, session_id=i)
                for i, p in enumerate(prompts)]
        outs = [fut.result(timeout=300) for fut in futs]
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(o.output_ids,
                                          _ref_greedy(model, p, 4))
            # each request decoded where it was routed: no migration
            assert o.decoded_by in ("rep-p", "rep-d")
        snap = serving_stats()
        assert snap["migrations"] == base["migrations"]
        assert snap["migration_pages_sent"] == \
            base["migration_pages_sent"]


def test_drain_migrates_active_requests_to_survivor(model):
    """Preemption recovery: draining a role-specialized replica streams
    its mid-decode slots to the survivor, which resumes them with KV
    intact — streams complete bit-equal, never recomputing prompts."""
    specs = {"rep-a": ServingConfig(num_slots=2, role="prefill"),
             "rep-b": ServingConfig(num_slots=4, role="decode")}
    with _RoleFleet(model, specs, disaggregation=False) as f:
        base = serving_stats()
        # pin requests to rep-a (disagg off: ring routing by session)
        key = next(f"s{i}" for i in range(1000)
                   if f.router.ring.lookup(f"s{i}") == "rep-a")
        prompts = _prompts([6, 8], seed=10)
        futs = [f.router.submit(p, max_new_tokens=48, session_id=key)
                for p in prompts]
        # drain as soon as BOTH are decoding (don't outwait the decode)
        eng = f.reps["rep-a"].engine
        deadline = time.monotonic() + 60
        while len(eng._active) < 2:
            assert time.monotonic() < deadline, "never started decoding"
            time.sleep(0.02)
        drainer = threading.Thread(
            target=f.reps["rep-a"].drain, kwargs={"deadline_s": 60.0})
        drainer.start()
        outs = [fut.result(timeout=300) for fut in futs]
        drainer.join(120)
        assert not drainer.is_alive()
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(o.output_ids,
                                          _ref_greedy(model, p, 48))
        snap = serving_stats()
        migrated = [o for o in outs if o.decoded_by == "rep-b"]
        assert migrated, "drain never migrated a request"
        assert snap["migration_resumed_requests"] \
            - base["migration_resumed_requests"] >= len(migrated)


def test_role_flip_rejoins_with_bumped_generation(model):
    """A replica leaves as prefill and rejoins as decode under the same
    name: the store generation bumps, the router admits the rejoin
    (anti-flap), and the gossiped role flips."""
    specs = {"rep-f": ServingConfig(num_slots=2, role="prefill"),
             "rep-g": ServingConfig(num_slots=2, role="decode")}
    with _RoleFleet(model, specs) as f:
        rep = f.reps["rep-f"]
        gen0 = rep.gen
        rep.drain(deadline_s=30.0)
        deadline = time.monotonic() + 15
        while "rep-f" in f.router.ring.members:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        flipped = ReplicaServer(
            "rep-f", model, TCPStore("127.0.0.1", f.master.port),
            ServingConfig(num_slots=2, role="decode"),
            ReplicaConfig(**_FAST))
        f.reps["rep-f"] = flipped
        assert flipped.gen > gen0
        deadline = time.monotonic() + 30
        while "rep-f" not in f.router.ring.members:
            assert time.monotonic() < deadline, f.router.replicas()
            time.sleep(0.05)
        with f.router._lock:
            assert f.router._replicas["rep-f"].role == "decode"
        # the flipped replica serves as a migration target now
        p = _prompts([5], seed=11)[0]
        out = f.router.submit(p, max_new_tokens=4,
                              session_id="postflip").result(timeout=300)
        np.testing.assert_array_equal(out.output_ids,
                                      _ref_greedy(model, p, 4))


def test_config_validation():
    with pytest.raises(ValueError, match="role"):
        ServingConfig(role="bogus").validate()
    assert RouterConfig().disaggregation is False
    assert ReplicaConfig().migrate_on_drain is True
    assert ServingConfig().role == "mixed"


# ------------------------------------------------------------------
# deadline propagation across the migration path (ISSUE 17)
# ------------------------------------------------------------------

def test_deadline_propagates_through_migration(model):
    """A client deadline bounds the WHOLE migrated request — prefill,
    transfer, and the resumed decode on the target replica.  A generous
    deadline rides through the handoff untouched; one that expires
    while the (slowed) decode replica holds the request must surface
    `DeadlineExceededError` instead of a late answer."""
    from paddle_tpu.serving import DeadlineExceededError
    from paddle_tpu.utils.flags import set_flags
    specs = {"rep-p": ServingConfig(num_slots=2, role="prefill"),
             "rep-d": ServingConfig(num_slots=4, role="decode")}
    with _RoleFleet(model, specs) as f:
        p = _prompts([6], seed=20)[0]
        # warm both engines so compile time can't eat the deadline
        f.router.submit(p, max_new_tokens=4,
                        session_id="warm").result(timeout=300)
        out = f.router.submit(p, max_new_tokens=5, deadline_s=60.0,
                              session_id="ok").result(timeout=300)
        np.testing.assert_array_equal(out.output_ids,
                                      _ref_greedy(model, p, 5))
        assert out.decoded_by == "rep-d"        # migrated AND bounded
        # now stall the decode replica's scheduler (gray failure: its
        # heartbeats stay healthy) so the resumed decode blows the
        # propagated deadline on the FAR side of the migration
        set_flags({"FLAGS_fault_inject":
                   "engine_slow:to=rep-d,delay_s=0.4,count=200"})
        try:
            with pytest.raises(DeadlineExceededError):
                f.router.submit(
                    p, max_new_tokens=24, deadline_s=1.5,
                    session_id="late").result(timeout=120)
        finally:
            set_flags({"FLAGS_fault_inject": ""})
        # the evicted request released every page on BOTH replicas
        deadline = time.monotonic() + 60
        for name in ("rep-p", "rep-d"):
            eng = f.reps[name].engine
            while eng.cache.pages_in_use or eng._active:
                assert time.monotonic() < deadline, \
                    f"{name} leaked pages after deadline evict"
                time.sleep(0.05)
        assert serving_stats()["requests_evicted_deadline"] >= 1


def test_mid_transfer_deadline_leaves_no_pages_on_either_side(model):
    """The deadline expires DURING the page transfer (the migration rpc
    itself is stalled in-call): wherever the request dies — evicted on
    the target, or fallback-decoded past its deadline at the source —
    it must resolve loudly and strand zero KV pages on either replica."""
    from paddle_tpu.serving import DeadlineExceededError
    from paddle_tpu.utils.flags import set_flags
    specs = {"rep-p": ServingConfig(num_slots=2, role="prefill"),
             "rep-d": ServingConfig(num_slots=4, role="decode")}
    with _RoleFleet(model, specs) as f:
        p = _prompts([7], seed=21)[0]
        f.router.submit(p, max_new_tokens=4,
                        session_id="warm").result(timeout=300)
        # every rpc INTO rep-d now sleeps 2s in-call: the transfer
        # straddles the 1.2s deadline
        set_flags({"FLAGS_fault_inject":
                   "rpc_slow:to=rep-d,delay_s=2.0,count=8"})
        try:
            with pytest.raises(DeadlineExceededError):
                f.router.submit(
                    p, max_new_tokens=16, deadline_s=1.2,
                    session_id="midxfer").result(timeout=120)
        finally:
            set_flags({"FLAGS_fault_inject": ""})
        deadline = time.monotonic() + 60
        for name in ("rep-p", "rep-d"):
            eng = f.reps[name].engine
            while eng.cache.pages_in_use or eng._active:
                assert time.monotonic() < deadline, \
                    f"{name} leaked pages after mid-transfer deadline"
                time.sleep(0.05)
        # the fleet is fully serviceable afterwards
        out = f.router.submit(p, max_new_tokens=4,
                              session_id="after").result(timeout=300)
        np.testing.assert_array_equal(out.output_ids,
                                      _ref_greedy(model, p, 4))
