"""FLOPs estimators for compute-relevant registry ops + a step counter.

Reference capability: the `flops` op metadata the reference wires into op
definitions and its op-benchmark table driving the profiler/auto-parallel
cost model (reference: paddle/phi/api/yaml/legacy_ops.yaml:679-688 op
metadata fields; tools/check_op_benchmark_result.py).  TPU-native
realization: estimators keyed by registry name; `FlopsCounter` hooks the
dispatch funnel (core/dispatch.apply_op) so ONE eagerly-executed step
yields the model's analytic FLOPs — that number feeds profiler MFU
(profiler/timer.py:mfu) for ANY model, replacing per-model hand formulas.

Counting convention: estimators count FORWARD multiply-add FLOPs (2·MACs
for matmul-family).  A train step is ~3x forward (backward ≈ 2x), the
standard accounting used by the PaLM/Chinchilla MFU literature.
"""
from __future__ import annotations

import numpy as np

from .registry import OPS


def _numel(shape):
    return int(np.prod(shape)) if len(shape) else 1


def _attach(name, fn):
    op = OPS.get(name)
    if op is not None:
        op.flops = fn


# ---- matmul family: 2 * batch * m * k * n ----
def _matmul_like(shapes, **kw):
    xs, ys = shapes[0], shapes[1]
    if len(xs) < 2 or len(ys) < 1:
        return 2 * _numel(xs)
    m, k = xs[-2], xs[-1]
    n = ys[-1] if len(ys) >= 2 else 1
    batch = _numel(xs[:-2])
    return 2 * batch * m * k * n


def _linear_flops(shapes, **kw):
    xs, ws = shapes[0], shapes[1]
    return 2 * _numel(xs[:-1]) * xs[-1] * ws[-1]


def _conv_flops(shapes, **kw):
    """2 * out_numel * (Cin/groups) * prod(kernel).  Output spatial size
    is not in `shapes`; approximate with input spatial size (stride 1,
    same padding) — an upper bound adequate for MFU accounting."""
    xs, ws = shapes[0], shapes[1]
    cout = ws[0]
    kernel = _numel(ws[2:])
    cin_per_group = ws[1]
    spatial = _numel(xs[2:])
    batch = xs[0]
    return 2 * batch * cout * spatial * cin_per_group * kernel


def _attention_flops(shapes, causal=True, **kw):
    """QK^T + PV: 2 * 2 * B*H*S^2*D (halved when causal)."""
    qs = shapes[0]
    if len(qs) == 4:            # [B, S, H, D]
        b, s, h, d = qs
    else:
        b, s, h, d = 1, qs[0], qs[1], qs[2]
    full = 4 * b * h * s * s * d
    return full // 2 if causal else full


def _norm_flops(shapes, **kw):
    return 8 * _numel(shapes[0])     # mean/var/normalize/affine passes


def _softmax_flops(shapes, **kw):
    return 5 * _numel(shapes[0])     # max, sub, exp, sum, div


def _xent_flops(shapes, **kw):
    return 6 * _numel(shapes[0])


def _embedding_flops(shapes, **kw):
    return 0                          # gather: no multiply-adds


def _elementwise(k):
    def fn(shapes, **kw):
        return k * _numel(shapes[0])
    return fn


_ESTIMATORS = {
    "matmul": _matmul_like,
    "bmm": _matmul_like,
    "mv": _matmul_like,
    "dot": _elementwise(2),
    "linear": _linear_flops,
    "conv1d": _conv_flops,
    "conv2d": _conv_flops,
    "conv3d": _conv_flops,
    "conv2d_transpose": _conv_flops,
    "flash_attention": _attention_flops,
    "ring_flash_attention": _attention_flops,
    "ulysses_attention": _attention_flops,
    "scaled_dot_product_attention": _attention_flops,
    "layer_norm": _norm_flops,
    "rms_norm": _norm_flops,
    "fused_rms_norm": _norm_flops,
    "group_norm": _norm_flops,
    "instance_norm": _norm_flops,
    "batch_norm_infer": _norm_flops,
    "batch_norm": _norm_flops,
    "softmax": _softmax_flops,
    "log_softmax": _softmax_flops,
    "cross_entropy": _xent_flops,
    "softmax_with_cross_entropy": _xent_flops,
    "binary_cross_entropy": _xent_flops,
    "binary_cross_entropy_with_logits": _xent_flops,
    "embedding": _embedding_flops,
    "gelu": _elementwise(10),
    "silu": _elementwise(5),
    "relu": _elementwise(1),
    "tanh": _elementwise(5),
    "sigmoid": _elementwise(4),
    "add": _elementwise(1),
    "multiply": _elementwise(1),
    "mean": _elementwise(1),
    "sum": _elementwise(1),
    "dropout": _elementwise(2),
    "fused_rope": _elementwise(6),
    "fused_rotary_position_embedding": _elementwise(6),
    "fused_bias_act": _elementwise(11),
}


def attach_all():
    """Populate registry `flops` metadata (idempotent)."""
    for name, fn in _ESTIMATORS.items():
        _attach(name, fn)


def flops_of(name, shapes, static):
    """Analytic FLOPs for one op call, or None when no estimator fits."""
    op = OPS.get(name)
    est = op.flops if op is not None else None
    if est is None:
        est = _ESTIMATORS.get(name)
    if est is None:
        return None
    try:
        return int(est(shapes, **static))
    except Exception:
        return None


class FlopsCounter:
    """Accumulates per-op forward FLOPs through the dispatch funnel.

    Usage:
        with FlopsCounter() as fc:
            loss = model(x, labels=y)     # one EAGER forward
        fc.forward_flops     # analytic fwd FLOPs
        fc.train_step_flops  # 3x (fwd + ~2x bwd)
        fc.by_op             # {op name: flops}
        fc.uncounted         # op names seen with no estimator
    """

    def __init__(self):
        self.by_op = {}
        self.uncounted = set()

    def add(self, name, shapes, static):
        # ops invoked through bare apply_op (flash_attention, the fused
        # pack) have no registry entry — flops_of falls back to the
        # estimator table directly so their FLOPs still count
        f = flops_of(name, shapes, static)
        if f is None:
            self.uncounted.add(name)
            return
        self.by_op[name] = self.by_op.get(name, 0) + f

    @property
    def forward_flops(self):
        return sum(self.by_op.values())

    @property
    def train_step_flops(self):
        return 3 * self.forward_flops

    def __enter__(self):
        from ..core import state as _state
        self._prev = getattr(_state.STATE, "flops_counter", None)
        _state.STATE.flops_counter = self
        return self

    def __exit__(self, *exc):
        from ..core import state as _state
        _state.STATE.flops_counter = self._prev
        return False


def count_flops(fn, *args, **kwargs):
    """Run `fn` eagerly under a FlopsCounter; return (result, counter)."""
    with FlopsCounter() as fc:
        out = fn(*args, **kwargs)
    return out, fc
