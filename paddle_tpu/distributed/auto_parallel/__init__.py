"""Auto-parallel: semi-automatic SPMD training.

Reference capability: python/paddle/distributed/auto_parallel/ — dygraph
API (shard_tensor/reshard/shard_layer, api.py:94,165,198) and the static
`Engine` (static/engine.py:55 — fit/evaluate/predict over a program that
Completer+Partitioner+Resharder rewrite per rank).

TPU-native realization: sharding PROPAGATION is XLA GSPMD — the entire
Completer/Partitioner/Resharder pipeline (completion.py:181,
partitioner.py:40, reshard.py:978) compiles away: user annotations
(shard_tensor / mp_placement) seed the solver and XLA materializes the
per-device program with collectives.  The Engine keeps the reference's
high-level surface: prepare/fit/evaluate/predict with a dp-sharded input
pipeline and a to_static-compiled step.
"""
from __future__ import annotations

import numpy as np

from ..api import (  # noqa: F401 — dygraph semi-auto surface
    shard_tensor, dtensor_from_fn, reshard, shard_layer, shard_constraint,
    unshard_dtensor,
)
from ..mesh import ProcessMesh, get_mesh, init_mesh, set_mesh  # noqa: F401
from ..placement import Shard, Replicate, Partial  # noqa: F401
from ...core.tensor import Tensor


class Strategy:
    """reference: auto_parallel/strategy.py — typed config bag."""

    def __init__(self):
        from ..fleet.base import DistributedStrategy
        self._inner = DistributedStrategy()
        self.auto_mode = "semi"

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner"], name)


def shard_optimizer(optimizer, shard_fn=None):
    """Dygraph semi-auto: optimizer states inherit parameter placements
    (reference: api.py shard_optimizer)."""
    from ..fleet.sharding import shard_optimizer_states
    mesh = get_mesh()
    if mesh is not None and "dp" in mesh.dim_names \
            and mesh.get_dim_size("dp") > 1:
        shard_optimizer_states(optimizer, axis="dp", mesh=mesh)
    return optimizer


def shard_dataloader(dataloader, meshes=None, shard_dims="dp",
                     input_keys=None):
    """Wrap a DataLoader so every yielded batch is committed dp-sharded
    (reference: api.py shard_dataloader)."""
    mesh = meshes if isinstance(meshes, ProcessMesh) else get_mesh()
    axis = shard_dims if isinstance(shard_dims, str) else "dp"

    class _Sharded:
        def __init__(self, dl):
            self._dl = dl

        def __len__(self):
            return len(self._dl)

        def __iter__(self):
            for batch in self._dl:
                yield self._shard(batch)

        def _shard(self, item):
            if isinstance(item, (list, tuple)):
                return type(item)(self._shard(x) for x in item)
            if isinstance(item, Tensor) and mesh is not None \
                    and axis in mesh.dim_names:
                placements = [Shard(0) if n == axis else Replicate()
                              for n in mesh.dim_names]
                return shard_tensor(item, mesh, placements,
                                    stop_gradient=item.stop_gradient)
            return item

    return _Sharded(dataloader)


class Engine:
    """reference: static/engine.py:55 — prepare/fit/evaluate/predict."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics or []
        self._strategy = strategy or Strategy()
        self._prepared = False
        self.history = {"loss": []}

    def plan(self, global_batch=None, seq_len=None, n_devices=None,
             device=None, mode="predict", max_trials=3):
        """Cost-based parallel planning (the reference's
        rule_based_tuner/parallel_tuner step, static/tuner/
        parallel_tuner.py:36): enumerate dp×mp×pp×sharding factorizations
        of the device count — INCLUDING pipeline configs when the model
        can execute them — prune by HBM capacity, rank with the roofline
        cost model, and install the best config as the fleet strategy.
        Call before prepare()/fit().

        mode="trial" confirms the roofline's top `max_trials` candidates
        by profiled tiny-shape trial steps in subprocesses (reference:
        static/tuner/optimization_tuner.py:194) before choosing.

        Returns the winning config dict (also stored on the strategy)."""
        import jax

        from ..auto_tuner.tuner import AutoTuner, TunerConfig
        from ...cost_model import DEVICE_SPECS

        n_dev = n_devices or jax.device_count()
        if device is None:
            plat = jax.devices()[0].platform
            import os
            device = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e") \
                if plat in ("tpu", "axon") else "cpu"
        if device not in DEVICE_SPECS:
            device = "v5e"
        # model statistics straight from the parameters — the planner is
        # model-agnostic (no per-model hand formula).  hidden = the mode
        # over all weight dims (the model width recurs in every norm/proj;
        # FFN- and vocab-sized dims appear far less often); layer count
        # from the standard 12·L·h² transformer budget.
        from collections import Counter

        params = (list(self._model.parameters())
                  if self._model is not None else [])
        n_params = float(sum(int(np.prod(p.shape)) for p in params)) \
            or 1.3e9
        dim_counts = Counter(int(d) for p in params for d in p.shape
                             if int(d) > 1)
        hidden = dim_counts.most_common(1)[0][0] if dim_counts else 1024
        # prefer the model's declared depth (pp pruning needs exact
        # stage divisibility); fall back to the 12·L·h² estimate
        model_cfg = getattr(self._model, "config", None)
        n_layers = getattr(model_cfg, "num_layers", None) or \
            max(int(round(n_params / (12.0 * hidden * hidden))), 1)
        # pipeline plans are in the space when the model can execute a
        # pipeline schedule (PipelineLayer.train_batch) or when planning
        # without a concrete model; a plain layer stays single-program
        from ..fleet.meta_parallel.pp_layers import PipelineLayer
        pipeline_capable = (self._model is None
                            or isinstance(self._model, PipelineLayer)
                            or hasattr(self._model, "train_batch"))
        cfg = TunerConfig(
            n_devices=n_dev, device=device, n_params=n_params,
            n_layers=n_layers, hidden=hidden,
            global_batch=global_batch or 8 * n_dev,
            seq_len=seq_len or 1024,
            pp_candidates=[] if pipeline_capable else [1],
        )
        tuner = AutoTuner(cfg)
        if mode == "trial":
            best = tuner.tune_by_spmd_trial(n_devices=n_dev,
                                            max_trials=max_trials)
        else:
            best = tuner.tune(mode="predict")
        if best is None:
            best = {"dp": n_dev, "mp": 1, "pp": 1, "sharding": 1}
        # write through to the inner DistributedStrategy: Strategy only
        # forwards attribute READS, and fleet.init consumes the inner one
        inner = self._strategy._inner if hasattr(self._strategy, "_inner") \
            else self._strategy
        inner.hybrid_configs = {
            "dp_degree": best.get("dp", 1),
            "mp_degree": best.get("mp", 1),
            "pp_degree": best.get("pp", 1),
            "sharding_degree": best.get("sharding", 1),
        }
        self._planned = {k: v for k, v in best.items()
                         if not k.startswith("_")}
        return self._planned

    def prepare(self, *args, **kwargs):
        """Commit model placements over the current mesh (the Completer+
        Partitioner step — here a single commit, GSPMD does the rest)."""
        from ..fleet import base as fleet_base
        if get_mesh() is None:
            from .. import fleet
            inner = getattr(self._strategy, "_inner", self._strategy)
            fleet.init(strategy=inner
                       if getattr(self, "_planned", None) else None)
        mesh = get_mesh()
        from ..fleet.meta_parallel.pp_layers import PipelineLayer
        if isinstance(self._model, PipelineLayer) and \
                getattr(self, "_planned", {}).get("pp", 1) > 1:
            # pipeline plan: re-stage to the planned pp degree if the
            # model was built before the mesh existed, then wrap into
            # the schedule executor (the loss lives inside the pipe
            # model).  Re-staging rebuilds layers — plan before loading
            # pretrained weights.
            from .. import fleet
            m = self._model
            pp_deg = mesh.get_dim_size("pp") if "pp" in mesh.dim_names \
                else 1
            if m._num_stages != pp_deg:
                m = PipelineLayer(
                    m._descs, num_stages=None,
                    seg_method=m._seg_method, loss_fn=m._loss_fn,
                    num_virtual_pipeline_stages=m._num_chunks)
            self._model = fleet.distributed_model(m)
            if self._optimizer is not None:
                self._optimizer._parameter_list = \
                    list(self._model.parameters())
        else:
            fleet_base._commit_params(self._model, mesh)
        if self._optimizer is not None:
            shard_optimizer(self._optimizer)
        self._prepared = True
        return self

    def _step(self, x, y):
        if hasattr(self._model, "train_batch"):
            return self._model.train_batch((x, y), self._optimizer)
        out = self._model(x)
        loss = self._loss(out, y)
        loss.backward()
        self._optimizer.step()
        self._optimizer.clear_grad()
        return loss

    def fit(self, train_data=None, epochs=1, batch_size=1, steps_per_epoch=None,
            valid_data=None, log_freq=10, verbose=0, **kwargs):
        from ...io import DataLoader
        if not self._prepared:
            self.prepare()
        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=True)
        loader = shard_dataloader(loader)
        for epoch in range(epochs):
            last = None
            for step, batch in enumerate(loader):
                x, y = batch[0], batch[1]
                loss = self._step(x, y)
                last = float(np.asarray(loss._data_))
                if steps_per_epoch and step + 1 >= steps_per_epoch:
                    break
            self.history["loss"].append(last)
            if verbose:
                print(f"epoch {epoch}: loss={last:.4f}")
        return self.history

    def evaluate(self, valid_data, batch_size=1, steps=None, **kwargs):
        from ...io import DataLoader
        from ...core.state import no_grad
        if not self._prepared:
            self.prepare()
        loader = valid_data if isinstance(valid_data, DataLoader) else \
            DataLoader(valid_data, batch_size=batch_size)
        loader = shard_dataloader(loader)
        losses = []
        with no_grad():
            for i, batch in enumerate(loader):
                out = self._model(batch[0])
                losses.append(float(np.asarray(
                    self._loss(out, batch[1])._data_)))
                if steps and i + 1 >= steps:
                    break
        return {"loss": float(np.mean(losses))}

    def predict(self, test_data, batch_size=1, steps=None, **kwargs):
        from ...io import DataLoader
        from ...core.state import no_grad
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size)
        outs = []
        with no_grad():
            for i, batch in enumerate(loader):
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                outs.append(self._model(x))
                if steps and i + 1 >= steps:
                    break
        return outs

    def save(self, path, training=True):
        from ..checkpoint import save_model_and_optimizer
        return save_model_and_optimizer(
            self._model, self._optimizer if training else None, path)

    def load(self, path, strict=True, load_optimizer=True):
        from ..checkpoint import load_model_and_optimizer
        return load_model_and_optimizer(
            self._model, self._optimizer if load_optimizer else None, path)


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """reference: auto_parallel to_static entry — compile the step."""
    from ...jit import to_static as jit_to_static
    return jit_to_static(layer)
