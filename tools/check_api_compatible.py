#!/usr/bin/env python
"""API-compatibility gate.

Reference capability: tools/check_api_compatible.py — CI compares the
public API surface against a recorded spec and fails on silent
removals/signature breaks.

Usage:
    python tools/check_api_compatible.py            # check vs api_spec.json
    python tools/check_api_compatible.py --update   # re-record the spec
"""
from __future__ import annotations

import argparse
import importlib
import inspect
import json
import os
import sys

SPEC_PATH = os.path.join(os.path.dirname(__file__), "api_spec.json")

# the public modules whose surfaces are contract
MODULES = [
    "paddle_tpu",
    "paddle_tpu.amp",
    "paddle_tpu.audio",
    "paddle_tpu.audio.features",
    "paddle_tpu.audio.functional",
    "paddle_tpu.autograd",
    "paddle_tpu.cost_model",
    "paddle_tpu.data",
    "paddle_tpu.device",
    "paddle_tpu.distributed",
    "paddle_tpu.distributed.fleet",
    "paddle_tpu.distribution",
    "paddle_tpu.distribution.transform",
    "paddle_tpu.fft",
    "paddle_tpu.geometric",
    "paddle_tpu.hub",
    "paddle_tpu.incubate",
    "paddle_tpu.incubate.nn",
    "paddle_tpu.incubate.nn.functional",
    "paddle_tpu.incubate.optimizer",
    "paddle_tpu.inference",
    "paddle_tpu.io",
    "paddle_tpu.jit",
    "paddle_tpu.linalg",
    "paddle_tpu.metric",
    "paddle_tpu.nn",
    "paddle_tpu.nn.functional",
    "paddle_tpu.nn.initializer",
    "paddle_tpu.nn.utils",
    "paddle_tpu.observability",
    "paddle_tpu.optimizer",
    "paddle_tpu.optimizer.lr",
    "paddle_tpu.regularizer",
    "paddle_tpu.serving",
    "paddle_tpu.signal",
    "paddle_tpu.sparse",
    "paddle_tpu.static",
    "paddle_tpu.static.nn",
    "paddle_tpu.text",
    "paddle_tpu.vision",
    "paddle_tpu.vision.datasets",
    "paddle_tpu.vision.models",
    "paddle_tpu.vision.ops",
    "paddle_tpu.vision.transforms",
]

# reference tree for __all__ parity (parsed with ast — never imported)
REFERENCE_ROOT = "/root/reference/python/paddle"


def _reference_all(modname):
    """Parse the reference counterpart's __all__ (None when the module or
    its __all__ doesn't exist — no contract)."""
    import ast
    rel = modname.replace("paddle_tpu", "").strip(".").replace(".", "/")
    for cand in (os.path.join(REFERENCE_ROOT, rel, "__init__.py"),
                 os.path.join(REFERENCE_ROOT, rel + ".py"),
                 os.path.join(REFERENCE_ROOT, "__init__.py") if not rel
                 else ""):
        if cand and os.path.exists(cand):
            try:
                tree = ast.parse(open(cand).read())
            except SyntaxError:
                return None
            for node in ast.walk(tree):
                if isinstance(node, ast.Assign) and any(
                        getattr(t, "id", "") == "__all__"
                        for t in node.targets):
                    try:
                        return sorted(
                            e.value for e in node.value.elts
                            if isinstance(e, ast.Constant))
                    except AttributeError:
                        return None
            return None
    return None


def _sig_of(obj):
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return None


def snapshot():
    spec = {}
    for modname in MODULES:
        mod = importlib.import_module(modname)
        entries = {}
        for name in sorted(dir(mod)):
            if name.startswith("_"):
                continue
            obj = getattr(mod, name)
            kind = ("class" if inspect.isclass(obj)
                    else "function" if callable(obj)
                    else "module" if inspect.ismodule(obj)
                    else "value")
            entries[name] = {"kind": kind}
            if kind == "function":
                entries[name]["sig"] = _sig_of(obj)
        spec[modname] = entries
    return spec


def reference_parity():
    """Every name in each reference module's __all__ must resolve on the
    corresponding paddle_tpu module (the single source of truth for the
    parity assertions formerly scattered across test files)."""
    problems = []
    checked = 0
    for modname in MODULES:
        ref_all = _reference_all(modname)
        if not ref_all:
            continue
        mod = importlib.import_module(modname)
        for name in ref_all:
            checked += 1
            if not hasattr(mod, name):
                problems.append(f"{modname}.{name}: MISSING "
                                f"(in reference __all__)")
    return checked, problems


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--update", action="store_true")
    args = ap.parse_args()

    current = snapshot()
    if args.update or not os.path.exists(SPEC_PATH):
        with open(SPEC_PATH, "w") as f:
            json.dump(current, f, indent=1, sort_keys=True)
        print(f"recorded API spec → {SPEC_PATH}")
        return 0

    with open(SPEC_PATH) as f:
        recorded = json.load(f)
    problems = []
    for modname, entries in recorded.items():
        cur = current.get(modname, {})
        for name, meta in entries.items():
            if name not in cur:
                problems.append(f"{modname}.{name}: REMOVED")
            elif meta.get("sig") and cur[name].get("sig") and \
                    meta["sig"] != cur[name]["sig"]:
                problems.append(
                    f"{modname}.{name}: signature changed "
                    f"{meta['sig']} -> {cur[name]['sig']}")
    ref_checked, ref_problems = reference_parity()
    problems += ref_problems
    if problems:
        print("API compatibility check FAILED:")
        for p in problems:
            print(" ", p)
        print("(intentional removal/signature change? re-record with "
              "--update; reference-parity MISSING entries must be fixed)")
        return 1
    n = sum(len(v) for v in recorded.values())
    print(f"API compatibility check passed ({n} symbols recorded, "
          f"{ref_checked} reference-__all__ names verified)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
