"""Tensor-parallel (Megatron-style) layer library.

Reference capability: fleet/layers/mpu/mp_layers.py — VocabParallelEmbedding
(:47), ColumnParallelLinear (:325), RowParallelLinear (:532),
ParallelCrossEntropy (:733) — and the comm primitives in mp_ops.py
(_c_identity/_c_concat/_mp_allreduce).

TPU-native realization: the layers carry *sharding annotations* instead of
explicit NCCL calls.  Weights are committed to the mesh (column → Shard(1),
row → Shard(0) over the "mp" axis); forward applies
`with_sharding_constraint` on activations; XLA GSPMD then inserts the exact
all-reduce/all-gather/reduce-scatter the reference calls by hand — fused and
overlapped by the compiler.  The identity/allreduce pair that implements
column×row composition falls out of the constraint solver.

Sequence-parallel variants (reference: fleet/utils/sequence_parallel_utils.py
:228,338) keep activations sharded over seq×mp between blocks, turning the
mp all-reduce into all-gather + reduce-scatter at the linear boundaries —
expressed here purely as different activation constraints.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...nn.layer import Layer
from ...nn import functional as F
from ...nn.initializer import XavierNormal, Normal
from ...core.tensor import Tensor
from ...core.dispatch import apply_op
from ..placement import Shard, Replicate
from ..api import shard_constraint
from ..mesh import get_mesh


def _mark(param, placements):
    """Record intended placements; committed by distributed_model/shard_layer."""
    param.placements = placements
    param.is_dist_param = True


def _activation_spec(x_ndim, mesh=None, last_axis=None, seq_axis=None):
    """Spec for [batch, (seq,) ..., features] activations: batch sharded over
    dp, optionally seq over sep/mp (sequence parallel), features over mp.
    Axes absent from the mesh are dropped so standalone TP layers work on
    meshes without a dp/sep axis."""
    mesh = mesh or get_mesh()
    names = mesh.dim_names if mesh is not None else ()
    entries = [None] * x_ndim
    if "dp" in names:
        entries[0] = "dp"
    if seq_axis is not None and seq_axis in names and x_ndim >= 2:
        entries[1] = seq_axis
    if last_axis is not None and last_axis in names:
        entries[-1] = last_axis
    return P(*entries)


class ColumnParallelLinear(Layer):
    """Linear with output features sharded over mp
    (reference: fleet/layers/mpu/mp_layers.py:325)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierNormal())
        # placements indexed by mesh axis; filled for the canonical hybrid
        # mesh at commit time: Shard over "mp" on the out dim
        self.weight.mp_placement = ("mp", Shard(1))
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter(
                (out_features,), attr=None, is_bias=True)
            self.bias.mp_placement = ("mp", Shard(0))

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        mesh = get_mesh()
        if mesh is not None and "mp" in mesh.dim_names:
            if self.gather_output:
                y = shard_constraint(
                    y, mesh, spec=_activation_spec(len(y.shape)))
            else:
                y = shard_constraint(
                    y, mesh, spec=_activation_spec(len(y.shape),
                                                   last_axis="mp"))
        return y


class RowParallelLinear(Layer):
    """Linear with input features sharded over mp; output needs the mp
    all-reduce, which GSPMD inserts from the constraints
    (reference: fleet/layers/mpu/mp_layers.py:532)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierNormal())
        self.weight.mp_placement = ("mp", Shard(0))
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter(
                (out_features,), attr=None, is_bias=True)
            # bias replicated; added after the implicit all-reduce

    def forward(self, x):
        mesh = get_mesh()
        if mesh is not None and "mp" in mesh.dim_names \
                and self.input_is_parallel:
            x = shard_constraint(
                x, mesh, spec=_activation_spec(len(x.shape), last_axis="mp"))
        y = F.linear(x, self.weight, self.bias)
        if mesh is not None and "mp" in mesh.dim_names:
            y = shard_constraint(y, mesh, spec=_activation_spec(len(y.shape)))
        return y


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over mp
    (reference: fleet/layers/mpu/mp_layers.py:47)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=Normal(0.0, 0.02))
        self.weight.mp_placement = ("mp", Shard(0))

    def forward(self, x):
        y = F.embedding(x, self.weight)
        mesh = get_mesh()
        if mesh is not None and "mp" in mesh.dim_names:
            y = shard_constraint(y, mesh,
                                 spec=_activation_spec(len(y.shape)))
        return y


class ParallelCrossEntropy(Layer):
    """Cross entropy over mp-sharded logits
    (reference: fleet/layers/mpu/mp_layers.py:733).

    GSPMD computes the log-softmax reduction over the sharded class dim with
    an mp all-reduce of max/sum — the same algorithm the reference hand-writes in
    c_softmax_with_cross_entropy; here it falls out of the constraint.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        mesh = get_mesh()
        if mesh is not None and "mp" in mesh.dim_names:
            input = shard_constraint(
                input, mesh,
                spec=_activation_spec(len(input.shape), last_axis="mp"))
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


# ---------------------------------------------------------------------------
# Sequence-parallel variants
# (reference: fleet/utils/sequence_parallel_utils.py:228,338)
# ---------------------------------------------------------------------------

class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Input arrives seq-sharded [b, s/mp, h]; output leaves feature-sharded.
    The all-gather at entry is inserted by GSPMD from the constraints."""

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("gather_output", False)
        super().__init__(*args, **kwargs)

    def forward(self, x):
        mesh = get_mesh()
        if mesh is not None and "mp" in mesh.dim_names:
            x = shard_constraint(
                x, mesh, spec=_activation_spec(len(x.shape), seq_axis="mp"))
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    """Output leaves seq-sharded — the mp all-reduce becomes the cheaper
    reduce-scatter, inserted by GSPMD."""

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("input_is_parallel", True)
        super().__init__(*args, **kwargs)

    def forward(self, x):
        y = super().forward(x)
        mesh = get_mesh()
        if mesh is not None and "mp" in mesh.dim_names:
            y = shard_constraint(
                y, mesh, spec=_activation_spec(len(y.shape), seq_axis="mp"))
        return y


# sequence-parallel activation ops (reference:
# sequence_parallel_utils.py:83-125) — pure re-layout constraints on TPU
def scatter(x, axis="mp"):
    mesh = get_mesh()
    return shard_constraint(
        x, mesh, spec=_activation_spec(len(x.shape), seq_axis=axis))


def all_gather_seq(x):
    mesh = get_mesh()
    return shard_constraint(x, mesh, spec=_activation_spec(len(x.shape)))


GatherOp = all_gather_seq
ScatterOp = scatter


def mark_as_sequence_parallel_parameter(param):
    param.is_sequence_parallel = True
