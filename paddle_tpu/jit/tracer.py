"""Trace-to-XLA compiler for dygraph code (`to_static` analogue).

Reference capability: paddle.jit.to_static (reference: python/paddle/jit/api.py:234
— AST transform / SOT bytecode capture into a static program executed by
run_program + InterpreterCore).  TPU-native realization: a two-phase
lazy-tensor capture —

1. **Discovery call** (first call per input signature): the function runs
   eagerly while a tracer records (a) every pre-existing Tensor whose data is
   read (parameter/buffer capture → becomes a compiled-program input) and
   (b) host-scalar providers (learning rate, RNG key) that must be re-fed
   each step.  The caller gets real results — the first call IS a real step.

2. **Bind trace**: `jax.jit` traces a pure wrapper that installs JAX tracers
   into the captured tensors' data slots, re-runs the python function (tape
   autograd, optimizer update and all — everything composes because every op
   bottoms out in jnp), then collects returned tensors + every mutated
   tensor's final value as program outputs.  Subsequent calls execute one
   fused XLA program — the analogue of the reference's whole-program
   InterpreterCore run, but compiled.

Data-dependent control flow (SOT analog, reference python/paddle/jit/sot/):
`bool(tensor)` branch conditions compile into GUARDED programs — the bool
is evaluated in-graph, returned as a guard output, and checked against the
recorded branch on every compiled call; a mismatch re-specializes (one
compiled entry per guard tuple, like SOT's guard-keyed compile cache).
Other host reads of traced values (float()/item()/numpy() — values that
escape into python effects the program can't replay) trigger a GRAPH BREAK:
the function falls back to eager for that signature with a warning, the
analog of SOT's piecewise fallback.
"""
from __future__ import annotations

import functools
import warnings

import numpy as np
import jax
import jax.numpy as jnp

from ..core import state as _state
from ..core.tensor import Tensor


class GraphBreak(Exception):
    """Raised during a bind trace when the program cannot represent a host
    interaction; the signature falls back to eager execution."""


class _DiscoveryTracer:
    """Records captures + host providers during the eager first call."""

    def __init__(self, fn_code=None):
        self.created = set()          # id(Tensor) made during trace
        self.captured = {}            # id(Tensor) -> Tensor (ordered via list)
        self.capture_list = []
        self.providers = []           # host-value providers, call order
        self.host_reads = []          # (is_bool_read, value, lineno-in-fn)
        self.fn_code = fn_code        # code object of the traced function
        self.rng_counter = 0
        self._rng_provider_registered = False
        self._rng_base_val = None

    def on_create(self, t):
        self.created.add(id(t))

    def on_read(self, t):
        i = id(t)
        if i not in self.created and i not in self.captured:
            self.captured[i] = t
            self.capture_list.append(t)

    def on_write(self, t):
        # writes don't need recording at discovery; mutation targets are
        # collected during the bind trace
        pass

    def host_read(self, t, bool_read=False):
        """A host read during discovery: record the value so the bind trace
        can replay the same control-flow path (and guard it), plus the
        source line WITHIN the traced function where the read happened —
        the split points for piecewise compilation (jit/sot.py) if this
        read later escapes at bind time."""
        val = np.asarray(t._data)     # property read → capture bookkeeping
        lineno = None
        if self.fn_code is not None:
            import sys
            f = sys._getframe(1)
            while f is not None:
                if f.f_code is self.fn_code:
                    lineno = f.f_lineno
                    break
                f = f.f_back
        self.host_reads.append((bool_read, val.copy(), lineno))
        return val

    def host_input(self, provider):
        self.providers.append(provider)
        return provider()

    def rng_base(self):
        if not self._rng_provider_registered:
            self._rng_provider_registered = True

            def provider():
                k = jax.random.fold_in(_state.STATE.rng_key,
                                       _state.STATE.rng_counter)
                _state.STATE.rng_counter += 1
                return k
            self._rng_base_val = self.host_input(provider)
        return self._rng_base_val


class _BindTracer:
    """Active while jax.jit traces the pure wrapper."""

    def __init__(self, host_tracers, capture_ids=frozenset(),
                 host_reads=()):
        self.created = set()
        self.mutated = {}             # id(Tensor) -> pre-write concrete data
        self.mutated_list = []
        self.host_tracers = host_tracers
        self.host_idx = 0
        self.rng_counter = 0
        self._rng_base_val = None
        self.capture_ids = capture_ids
        self.host_reads = list(host_reads)
        self.read_idx = 0
        self.guard_arrays = []        # traced bool-read values → outputs

    def on_create(self, t):
        self.created.add(id(t))

    def on_read(self, t):
        # a concrete (non-tracer) read of a tensor that is neither a declared
        # capture nor created inside this trace would be silently baked into
        # the program as a constant — a stale-state bug.  Discovery should
        # have captured it; graph-break to eager instead of erroring.
        if (id(t) not in self.capture_ids and id(t) not in self.created
                and id(t) not in self.mutated
                and not isinstance(t._data_, jax.core.Tracer)):
            raise GraphBreak(
                "bind trace read a concrete tensor that was not captured "
                f"at discovery (shape {tuple(t._data_.shape)}, "
                f"name={t.name!r}): control flow diverged between calls")

    def on_write(self, t):
        i = id(t)
        if i not in self.created and i not in self.mutated:
            self.mutated[i] = t._data_  # original value, pre-write
            self.mutated_list.append(t)

    def host_read(self, t, bool_read=False):
        """Replay a discovery-recorded host read.  bool reads become guard
        outputs of the compiled program; other traced reads graph-break."""
        arr = t._data_
        if self.read_idx >= len(self.host_reads):
            raise GraphBreak("host-read sequence diverged from discovery")
        rec_bool, rec_val = self.host_reads[self.read_idx][:2]
        self.read_idx += 1
        if bool_read:
            # every discovery bool read must yield exactly one guard output
            # (guard_bools and guard_arrays are compared positionally); a
            # read that binds concrete becomes a constant guard output
            self.guard_arrays.append(
                arr if isinstance(arr, jax.core.Tracer)
                else jax.numpy.asarray(arr))
            return (rec_val if isinstance(arr, jax.core.Tracer)
                    else np.asarray(arr))
        if not isinstance(arr, jax.core.Tracer):
            return np.asarray(arr)
        gb = GraphBreak(
            "host read of a traced value (float()/item()/numpy()) — the "
            "value escapes into python, which a compiled program cannot "
            "replay; falling back to eager for this signature")
        gb.splittable = True   # the recorded read lines ARE the cause —
        # piecewise sub-graph compilation (jit/sot.py) can remove it
        raise gb

    def host_input(self, provider):
        v = self.host_tracers[self.host_idx]
        self.host_idx += 1
        return v

    def rng_base(self):
        if self._rng_base_val is None:
            self._rng_base_val = self.host_input(None)
        return self._rng_base_val


def host_scalar(provider):
    """Fetch a host-computed value as a traced input under tracing, or the
    plain value eagerly.  Used for learning rates / step counters that change
    between compiled calls."""
    tr = _state.STATE.tracer
    if tr is not None:
        return tr.host_input(provider)
    return provider()


def _flatten_args(args, kwargs):
    leaves, treedef = jax.tree.flatten((args, kwargs),
                                       is_leaf=lambda x: isinstance(x, Tensor))
    arrays, spec = [], []
    for leaf in leaves:
        if isinstance(leaf, Tensor):
            arrays.append(leaf._data_)
            spec.append(None)
        else:
            spec.append(leaf)
    return arrays, (treedef, tuple(spec))


def _unflatten_args(arrays, struct):
    treedef, spec = struct
    arrays = iter(arrays)
    leaves = [Tensor(next(arrays)) if s is None else s for s in spec]
    return jax.tree.unflatten(treedef, leaves)


def _signature(args, kwargs):
    leaves, treedef = jax.tree.flatten((args, kwargs),
                                       is_leaf=lambda x: isinstance(x, Tensor))
    sig = []
    for leaf in leaves:
        if isinstance(leaf, Tensor):
            sig.append(("T", tuple(leaf._data_.shape), str(leaf._data_.dtype)))
        else:
            try:
                hash(leaf)
                sig.append(leaf)
            except TypeError:
                sig.append(repr(leaf))
    return treedef, tuple(sig)


_WARMUP = object()

_DONATED_FAILURE_MSG = (
    "compiled step failed after buffer donation; parameters/optimizer "
    "state backing this step are invalid — reload them from a checkpoint, "
    "or set FLAGS_jit_donate_buffers=False to trade memory for failure "
    "recovery")


def _donation_unsafe(cap_arrays, mut_idx):
    """Donation is unsound when a to-be-donated buffer is aliased by
    another capture: two mut targets sharing one array would donate it
    twice; a const capture aliasing it would read a deleted buffer."""
    buf = [id(a) for a in cap_arrays]
    mut_set = set(mut_idx)
    mut_buf = {buf[i] for i in mut_idx}
    return (len(mut_buf) != len(mut_idx)
            or any(buf[i] in mut_buf for i in range(len(buf))
                   if i not in mut_set))


def _apply_entry_results(entry, out_arrays, mut_arrays, grad_arrays):
    """Write a compiled step's results back into the live tensors
    (mutations in place, escaped grads) and rebuild the python outputs
    from the recorded structure.  Shared by the dynamic compiled path and
    the static-graph training executor (static._TrainExecutor)."""
    for t, arr in zip(entry.mut_targets, mut_arrays):
        t._data_ = arr
    for t, arr in zip(entry.grad_targets, grad_arrays):
        if t.grad is None:
            t.grad = Tensor(arr)
        else:
            t.grad._data_ = arr
    out_tree, out_spec = entry.out_struct
    arrays = iter(out_arrays)
    leaves = [Tensor(next(arrays)) if s is None else s for s in out_spec]
    return jax.tree.unflatten(out_tree, leaves)


class _CompiledEntry:
    __slots__ = ("captures", "providers", "jitted", "mut_targets",
                 "grad_targets", "out_struct", "host_reads", "guard_bools",
                 "pure", "jitted_donate", "mut_idx")

    def __init__(self):
        self.captures = []
        self.providers = []
        self.jitted = None
        self.mut_targets = []     # Tensors whose data is replaced after call
        self.grad_targets = []    # Tensors whose .grad is materialized
        self.out_struct = None
        self.host_reads = []      # discovery-recorded (is_bool, value, line)
        self.guard_bools = ()     # the branch bits this entry specializes on
        self.pure = None          # the traced body (shared by both jits)
        self.jitted_donate = None  # donating variant, built after 1st run
        self.mut_idx = None       # capture positions donated to XLA


class _SigState:
    """Per-input-signature compile state: guard-keyed entries (SOT's
    guard-keyed compile cache analog) + eager fallback bookkeeping."""

    __slots__ = ("entries", "last", "eager_only", "rediscoveries",
                 "piecewise")

    def __init__(self):
        self.entries = {}         # guard tuple -> _CompiledEntry
        self.last = None
        self.eager_only = False
        self.rediscoveries = 0
        self.piecewise = None     # sub-graph driver after a graph break


class StaticFunction:
    """Callable wrapper produced by @to_static."""

    def __init__(self, fn, input_spec=None, build_strategy=None,
                 full_graph=True):
        self._fn = fn
        self._cache = {}
        # InputSpec list with None dims = batch-polymorphic signature:
        # warmup/discovery run once (typically on a small batch) and
        # jax.jit re-traces the same bound program per concrete shape.
        # Caveat: Python-level host reads of *shapes* specialize to the
        # discovery call's values (data guards still re-dispatch).
        self._input_spec = list(input_spec) if input_spec else None
        for attr in ("__name__", "__qualname__", "__doc__"):
            try:
                setattr(self, attr, getattr(fn, attr))
            except AttributeError:
                pass

    @property
    def __wrapped__(self):
        return self._fn

    def __get__(self, instance, owner):
        if instance is None:
            return self
        return functools.partial(self.__call__, instance)

    def concrete_cache_size(self):
        return len(self._cache)

    def guard_cache_size(self):
        """Total compiled guard entries across input signatures.  Bounded:
        a signature whose guards keep flipping into undiscovered tuples
        respecializes at most 4 times before falling back to eager (with a
        warning), so entries per signature never exceed ~6."""
        return sum(len(s.entries) for s in self._cache.values()
                   if isinstance(s, _SigState))

    def compiled_hlo(self, *args, **kwargs):
        """Optimized (post-XLA) HLO text of the compiled entry matching
        these args — the input to the communication-budget analyzer
        (profiler/comm_budget.py).  None if not yet compiled."""
        state = self._cache.get(self._canon_key(args, kwargs))
        entry = state.last if state is not None else None
        if entry is None or entry.jitted is None:
            return None
        arg_arrays, arg_struct = _flatten_args(args, kwargs)
        cap_arrays = [t._data_ for t in entry.captures]
        host_vals = [p() for p in entry.providers]
        lowered = entry.jitted.lower(arg_arrays, cap_arrays, host_vals,
                                     arg_struct)
        return lowered.compile().as_text()

    def hlo_fingerprint(self, *args, **kwargs):
        """sha256 (first 16 hex) of the StableHLO of the compiled entry
        matching these args — the auditable program identity a benchmark
        run records so a number can be tied to the exact computation.
        None if this signature hasn't compiled yet or lowering fails."""
        import hashlib
        state = self._cache.get(self._canon_key(args, kwargs))
        entry = state.last if state is not None else None
        if entry is None or entry.jitted is None:
            return None
        try:
            arg_arrays, arg_struct = _flatten_args(args, kwargs)
            cap_arrays = [t._data_ for t in entry.captures]
            host_vals = [p() for p in entry.providers]
            text = entry.jitted.lower(arg_arrays, cap_arrays, host_vals,
                                      arg_struct).as_text()
        except Exception:
            return None
        return hashlib.sha256(text.encode()).hexdigest()[:16]

    def _canon_key(self, args, kwargs):
        treedef, sig = _signature(args, kwargs)
        if not self._input_spec:
            return treedef, sig
        specs = self._input_spec
        out, ti = [], 0
        for leaf in sig:
            if isinstance(leaf, tuple) and len(leaf) == 3 and leaf[0] == "T":
                spec = specs[ti] if ti < len(specs) else None
                ti += 1
                shape = getattr(spec, "shape", None)
                if shape is not None and len(shape) == len(leaf[1]):
                    leaf = ("T", tuple(None if s is None else d
                                       for d, s in zip(leaf[1], shape)),
                            leaf[2])
            out.append(leaf)
        return treedef, tuple(out)

    def __call__(self, *args, **kwargs):
        from . import _TO_STATIC_ENABLED
        if not _TO_STATIC_ENABLED:
            # jit.enable_to_static(False): run the original eagerly
            return self._fn(*args, **kwargs)
        if _state.STATE.tracer is not None:
            # nested to_static: inline into the enclosing trace
            return self._fn(*args, **kwargs)
        key = self._canon_key(args, kwargs)
        state = self._cache.get(key)
        if state is None:
            # warm-up: run once fully eager so lazily-initialized persistent
            # state (optimizer moments, step counters, buffers) exists BEFORE
            # discovery — otherwise discovery marks it "created" and the bind
            # trace would bake its current value in as a constant.  The
            # sentinel is recorded only after a successful eager run: if the
            # warm-up raises, the next call with this signature warms up
            # again instead of discovering against half-initialized state.
            result = self._fn(*args, **kwargs)
            self._cache[key] = _WARMUP
            return result
        from ..utils import monitor as _monitor
        if state is _WARMUP:
            _monitor.incr("jit.cache_miss")
            return self._discover(key, args, kwargs)
        if state.piecewise is not None:
            _monitor.incr("jit.piecewise_call")
            return state.piecewise(*args, **kwargs)
        if state.eager_only:
            _monitor.incr("jit.eager_fallback")
            return self._fn(*args, **kwargs)
        _monitor.incr("jit.cache_hit")
        return self._run_compiled(key, state, args, kwargs)

    # ---------------- phase 1: discovery (eager) ----------------
    def _discover(self, key, args, kwargs):
        entry = _CompiledEntry()
        tracer = _DiscoveryTracer(
            fn_code=getattr(self._fn, "__code__", None))
        _state.STATE.tracer = tracer
        try:
            out = self._fn(*args, **kwargs)
        finally:
            _state.STATE.tracer = None
        entry.captures = tracer.capture_list
        entry.providers = tracer.providers
        entry.host_reads = tracer.host_reads
        entry.guard_bools = tuple(bool(rec[1]) for rec in tracer.host_reads
                                  if rec[0])
        self._build(entry, args, kwargs)
        state = self._cache.get(key)
        if not isinstance(state, _SigState):
            state = _SigState()
            self._cache[key] = state
        state.entries[entry.guard_bools] = entry
        state.last = entry
        return out

    # ---------------- phase 2: bind + compile ----------------
    def _build(self, entry, args, kwargs):
        fn = self._fn

        def pure(arg_arrays, cap_arrays, host_vals, arg_struct):
            tracer = _BindTracer(host_vals,
                                 frozenset(id(t) for t in entry.captures),
                                 host_reads=entry.host_reads)
            saved = [(t, t._data_) for t in entry.captures]
            bound_args, bound_kwargs = _unflatten_args(arg_arrays, arg_struct)
            for t, arr in zip(entry.captures, cap_arrays):
                t._data_ = arr
            _state.STATE.tracer = tracer
            captured_ids = {id(t) for t in entry.captures}
            try:
                out = fn(*bound_args, **bound_kwargs)
                # collect outputs
                out_leaves, out_tree = jax.tree.flatten(
                    out, is_leaf=lambda x: isinstance(x, Tensor))
                out_arrays, out_spec = [], []
                for leaf in out_leaves:
                    if isinstance(leaf, Tensor):
                        out_arrays.append(leaf._data_)
                        out_spec.append(None)
                    else:
                        out_spec.append(leaf)
                entry.out_struct = (out_tree, tuple(out_spec))
                # mutated tensors -> outputs
                entry.mut_targets = list(tracer.mutated_list)
                mut_arrays = [t._data_ for t in entry.mut_targets]
                # escaped gradients on captured tensors -> outputs
                entry.grad_targets = []
                grad_arrays = []
                for t in entry.captures:
                    g = t.grad
                    # grads accumulated IN PLACE into a pre-existing grad
                    # tensor are already mut_targets — collecting them
                    # here too would null the object and break the
                    # stable-identity contract piecewise segments rely on
                    if (g is not None and isinstance(g._data_,
                                                     jax.core.Tracer)
                            and id(g) not in tracer.mutated):
                        entry.grad_targets.append(t)
                        grad_arrays.append(g._data_)
                for t in entry.grad_targets:
                    t.grad = None
                return (tuple(out_arrays), tuple(mut_arrays),
                        tuple(grad_arrays), tuple(tracer.guard_arrays))
            finally:
                # ALWAYS restore concrete state — a GraphBreak raised
                # mid-trace must not leak JAX tracers into live tensors
                # (mutations are applied by the caller from returned arrays)
                _state.STATE.tracer = None
                for t, orig in saved:
                    t._data_ = orig
                for t in tracer.mutated_list:
                    if id(t) not in captured_ids:
                        # mutated without prior read: restore the pre-write
                        # value recorded by the tracer
                        t._data_ = tracer.mutated[id(t)]
                for t in entry.captures:
                    g = t.grad
                    if g is not None and isinstance(g._data_,
                                                    jax.core.Tracer):
                        t.grad = None

        entry.pure = pure
        from ..core.op_cache import ensure_compile_cache
        ensure_compile_cache()   # tier-2 persistent XLA compilation cache
        entry.jitted = jax.jit(pure, static_argnums=(3,))

    def _build_donating(self, entry):
        """Donating variant: the mutated captures (params, optimizer
        moments, accumulated grads) are donated to XLA, so the update
        aliases their buffers in place instead of holding old+new copies —
        the in-place-update behavior the reference's executors get from
        explicit inplace ops.  Only for guard-free entries: on a guard
        mismatch the non-donating path discards outputs and keeps the
        inputs, which donation makes impossible."""
        mut_ids = {id(t) for t in entry.mut_targets}
        entry.mut_idx = [i for i, t in enumerate(entry.captures)
                         if id(t) in mut_ids]
        mut_pos = {ci: k for k, ci in enumerate(entry.mut_idx)}
        n_caps = len(entry.captures)
        pure = entry.pure

        def pure_donated(arg_arrays, mut_caps, const_caps, host_vals,
                         arg_struct):
            caps, ci = [], 0
            for i in range(n_caps):
                if i in mut_pos:
                    caps.append(mut_caps[mut_pos[i]])
                else:
                    caps.append(const_caps[ci])
                    ci += 1
            return pure(arg_arrays, caps, host_vals, arg_struct)

        from ..core.op_cache import ensure_compile_cache
        ensure_compile_cache()
        entry.jitted_donate = jax.jit(pure_donated, static_argnums=(4,),
                                      donate_argnums=(1,))

    def _run_compiled(self, key, state, args, kwargs, _depth=0):
        from ..utils import flags as _flags

        entry = state.last
        arg_arrays, arg_struct = _flatten_args(args, kwargs)
        cap_arrays = [t._data_ for t in entry.captures]
        host_vals = [p() for p in entry.providers]
        donate_ok = (not entry.guard_bools
                     and _flags.flag("FLAGS_jit_donate_buffers", True))
        use_donate = entry.jitted_donate is not None and donate_ok
        if use_donate:
            mut_set = set(entry.mut_idx)
            mut_caps = [cap_arrays[i] for i in entry.mut_idx]
            const_caps = [a for i, a in enumerate(cap_arrays)
                          if i not in mut_set]
            # donation is unsound when a to-be-donated buffer is aliased
            # by another capture (two mut_targets sharing one array would
            # donate it twice; a const capture aliasing it would read a
            # deleted buffer) — fall back to the copying path for this call
            if _donation_unsafe(cap_arrays, entry.mut_idx):
                use_donate = False
        try:
            if use_donate:
                try:
                    out_arrays, mut_arrays, grad_arrays, guard_arrays = \
                        entry.jitted_donate(arg_arrays, mut_caps,
                                            const_caps, host_vals,
                                            arg_struct)
                except GraphBreak:
                    raise
                except Exception as e:
                    # the donated buffers may already be gone — unlike the
                    # non-donating path, inputs cannot be preserved here
                    if any(getattr(a, "is_deleted", lambda: False)()
                           for a in mut_caps):
                        raise RuntimeError(_DONATED_FAILURE_MSG) from e
                    raise
            else:
                out_arrays, mut_arrays, grad_arrays, guard_arrays = \
                    entry.jitted(arg_arrays, cap_arrays, host_vals,
                                 arg_struct)
                if (donate_ok and entry.jitted_donate is None
                        and entry.mut_targets):
                    self._build_donating(entry)
        except GraphBreak as e:
            # the program cannot represent the whole function.  First try
            # a piecewise split (SOT sub-graph analog, jit/sot.py): compile
            # the statement runs around the escaping host reads and run
            # the breaking statements eagerly between them.
            pw = None
            if (getattr(e, "splittable", False)
                    and not getattr(self, "_no_piecewise", False)):
                lines = sorted({rec[2] for rec in entry.host_reads
                                if not rec[0] and len(rec) > 2 and rec[2]})
                if lines:
                    from .sot import build_piecewise
                    try:
                        pw = build_piecewise(self._fn, lines)
                    except Exception:
                        pw = None
            if pw is not None:
                state.piecewise = pw
                warnings.warn(
                    f"to_static graph break ({e}); split "
                    f"{getattr(self._fn, '__name__', '?')} into "
                    f"{pw._n_pieces} pieces "
                    f"({len(pw._segments)} compiled sub-graphs) for this "
                    f"input signature")
                return pw(*args, **kwargs)
            # unsplittable — eager fallback for this signature from now on
            state.eager_only = True
            warnings.warn(f"to_static graph break ({e}); running "
                          f"{getattr(self._fn, '__name__', '?')} eagerly "
                          f"for this input signature")
            return self._fn(*args, **kwargs)

        # guard check BEFORE applying mutations: a mismatch means the
        # compiled program followed the wrong branch and its outputs are
        # invalid for this call
        actual = tuple(bool(np.asarray(g)) for g in guard_arrays)
        if actual != entry.guard_bools:
            alt = state.entries.get(actual)
            if alt is None:
                # nested data-dependent branches: entries can have guard
                # tuples of different LENGTHS (each branch records its own
                # downstream guards), so exact lookup misses — match on
                # the longest consistent prefix; the re-dispatch below
                # verifies the candidate with its own guards
                best = None
                for gb, cand in state.entries.items():
                    if cand is entry:
                        continue
                    n = min(len(gb), len(actual))
                    if gb[:n] == actual[:n] and (
                            best is None
                            or len(gb) > len(best.guard_bools)):
                        best = cand
                alt = best
            if alt is not None and alt is not entry and _depth < 2:
                state.last = alt
                return self._run_compiled(key, state, args, kwargs,
                                          _depth=_depth + 1)
            state.rediscoveries += 1
            if state.rediscoveries > 4:
                state.eager_only = True
                warnings.warn(
                    f"to_static: branch guards keep flipping for "
                    f"{getattr(self._fn, '__name__', '?')}; running this "
                    f"input signature eagerly")
                return self._fn(*args, **kwargs)
            # re-specialize on the new branch (runs eagerly this call)
            return self._discover(key, args, kwargs)

        return _apply_entry_results(entry, out_arrays, mut_arrays,
                                    grad_arrays)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True, **kwargs):
    """Compile a dygraph function/Layer into one XLA program per input
    signature (reference API: python/paddle/jit/api.py:234)."""
    from ..nn.layer import Layer

    def decorate(fn):
        if isinstance(fn, Layer):
            layer = fn
            # input_spec matches Tensor leaves positionally, so the bound
            # self (a non-Tensor leaf) needs no placeholder in the spec
            if hasattr(layer.forward, "__func__"):
                static_fwd = StaticFunction(layer.forward.__func__,
                                            input_spec=input_spec)
                bound = functools.partial(static_fwd, layer)
            else:
                static_fwd = StaticFunction(layer.forward,
                                            input_spec=input_spec)
                bound = static_fwd
            layer.forward = bound
            return layer
        return StaticFunction(fn, input_spec=input_spec)

    if function is not None:
        return decorate(function)
    return decorate
