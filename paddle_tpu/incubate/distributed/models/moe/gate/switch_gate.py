"""Switch (top-1) gate with capacity + load-balance loss.

Reference capability: moe/gate/switch_gate.py — top-1 routing, capacity
factor differing between train/eval, load-balance auxiliary loss.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ......core.dispatch import apply_op
from .naive_gate import NaiveGate


def _switch_dispatch(logits, capacity):
    n, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    mask = jax.nn.one_hot(idx, e, dtype=logits.dtype)
    p = jnp.sum(probs * mask, axis=-1)

    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(mask, axis=0)
    aux = jnp.sum(me * ce) * e

    pos = jnp.cumsum(mask, axis=0) * mask - mask
    mask = mask * (pos < capacity)
    oh = jax.nn.one_hot((pos * mask).sum(-1).astype(jnp.int32), capacity, dtype=logits.dtype)
    combine = (p[:, None] * mask)[:, :, None] * oh[:, None, :]
    dispatch = combine > 0.0
    return combine, dispatch, aux


class SwitchGate(NaiveGate):
    def __init__(self, d_model, num_expert, world_size, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        if topk != 1:
            raise ValueError("Switch gate is top-1 (reference asserts topk==1)")
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.switch_eps = switch_eps
        self.capacity_factor = capacity

    def dispatch_info(self, inp, train=True):
        logits = self.gate(inp)
        if train and self.switch_eps > 0:
            from ......tensor_ops import random as R
            noise = R.uniform(logits.shape, min=1.0 - self.switch_eps,
                              max=1.0 + self.switch_eps)
            logits = logits * noise
        n = logits.shape[0]
        factor = self.capacity_factor[0 if train else 1]
        cap = int(max(1, factor * n / self.tot_expert))

        combine, dispatch, aux = apply_op(
            "switch_gate", lambda lg: _switch_dispatch(lg, cap), (logits,))
        self.set_loss(aux)
        return combine, dispatch, aux
