"""Elastic world-size resharding (docs/FAULT_TOLERANCE.md "Elastic
resize"): shard-overlap math, layout manifests, reshard-on-restore, and
the subprocess resize drills (train on 4 procs → SIGTERM → resume on 2,
and 2 → 4), reference pattern: auto_parallel/static/converter.py re-slice
+ the fleet elastic relaunch flow."""
import json
import os
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.framework.checkpoint_manager import (
    CheckpointManager, CheckpointError,
)
from paddle_tpu.distributed.reshard import (
    LayoutError, LayoutMismatchError, MeshSpec, ShardedCheckpointer,
    offer_shards, overlap_slices, read_layout, replicated,
    restore_latest_resharded, restore_resharded, shard_slices,
    split_bounds,
)
from paddle_tpu.utils.flags import set_flags

WORKER = os.path.join(os.path.dirname(__file__), "_reshard_worker.py")


# ---------------------------------------------------------------------------
# shard math
# ---------------------------------------------------------------------------

def test_split_bounds_uneven():
    # np.array_split semantics: first n % parts chunks get +1
    assert [split_bounds(7, 4, i) for i in range(4)] == \
        [(0, 2), (2, 4), (4, 6), (6, 7)]
    assert [split_bounds(3, 4, i) for i in range(4)] == \
        [(0, 1), (1, 2), (2, 3), (3, 3)]      # empty tail chunk
    assert split_bounds(8, 2, 1) == (4, 8)
    with pytest.raises(ValueError):
        split_bounds(4, 2, 2)


def test_shard_slices_and_overlap():
    mesh = MeshSpec(("dp", "mp"), (2, 2))
    # rank 3 = coords dp=1, mp=1
    assert shard_slices((8, 6), ("dp", "mp"), mesh, 3) == \
        (slice(4, 8), slice(3, 6))
    assert shard_slices((8, 6), (None, "mp"), mesh, 1) == \
        (slice(0, 8), slice(3, 6))
    # uneven: 7 rows over dp=2 → 4 + 3
    assert shard_slices((7,), ("dp",), mesh, 2) == (slice(4, 7),)
    # overlap is expressed in each side's local coordinates
    src = (slice(2, 6),)
    dst = (slice(4, 9),)
    sel_src, sel_dst = overlap_slices(src, dst)
    assert sel_src == (slice(2, 4),) and sel_dst == (slice(0, 2),)
    assert overlap_slices((slice(0, 2),), (slice(2, 4),)) is None
    # unknown axis in partition → mismatch error naming the mesh
    with pytest.raises(LayoutMismatchError):
        shard_slices((8,), ("pp",), mesh, 0)


def _mesh_coords_cover():
    mesh = MeshSpec(("dp", "mp"), (3, 2))
    return [mesh.coords(r) for r in range(mesh.world)]


def test_mesh_coords_row_major():
    coords = _mesh_coords_cover()
    assert coords[0] == {"dp": 0, "mp": 0}
    assert coords[1] == {"dp": 0, "mp": 1}
    assert coords[5] == {"dp": 2, "mp": 1}


# ---------------------------------------------------------------------------
# save/restore helpers
# ---------------------------------------------------------------------------

def _state(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "model": {
            "w": paddle.to_tensor(
                rng.standard_normal((7, 6)).astype("float32")),
            "b": paddle.to_tensor(
                rng.standard_normal((6,)).astype("float32")),
        },
        "optimizer": {
            "moment1.0": paddle.to_tensor(
                rng.standard_normal((7, 6)).astype("float32")),
            "step_count": 3,
        },
        "losses": [0.5, 0.25],
        "step": 1,
    }


def _moment_partition(key, arr):
    if "moment" in key and arr.ndim >= 1:
        return ("dp",) + (None,) * (arr.ndim - 1)
    return replicated(arr.ndim)


def _save_world(root, state, mesh, partition_fn=None, step=0):
    """Simulate a lockstep multi-rank save with one thread per rank."""
    errs = []

    def _one(rank):
        try:
            ShardedCheckpointer(root, mesh, rank,
                                partition_fn=partition_fn).save(
                state, step=step)
        except BaseException as e:  # noqa: BLE001
            errs.append((rank, e))
    ts = [threading.Thread(target=_one, args=(r,))
          for r in range(mesh.world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    assert not errs, errs


def _np(t):
    return np.asarray(t._data_) if hasattr(t, "_data_") else np.asarray(t)


# ---------------------------------------------------------------------------
# resharding restores
# ---------------------------------------------------------------------------

def test_reshard_4_to_2_and_3_roundtrip(tmp_path):
    root = str(tmp_path / "ck")
    state = _state()
    mesh4 = MeshSpec(("dp",), (4,))
    _save_world(root, state, mesh4, _moment_partition, step=0)
    layout = read_layout(os.path.join(root, "ckpt-00000000"))
    assert layout["world_size"] == 4
    assert layout["arrays"]["optimizer.moment1.0"]["partition"] == \
        ["dp", None]
    assert layout["arrays"]["model.w"]["partition"] == [None, None]

    want_m1 = _np(state["optimizer"]["moment1.0"])
    for new_world in (2, 3, 1, 5):
        meshN = MeshSpec(("dp",), (new_world,))
        for rank in range(new_world):
            ck = ShardedCheckpointer(root, meshN, rank)
            restored, step = ck.restore_latest()
            assert step == 0
            # replicated arrays byte-equal; sharded moments reassembled
            np.testing.assert_array_equal(_np(restored["model"]["w"]),
                                          _np(state["model"]["w"]))
            np.testing.assert_array_equal(
                _np(restored["optimizer"]["moment1.0"]), want_m1)
            assert restored["losses"] == [0.5, 0.25]
            assert restored["optimizer"]["step_count"] == 3
            assert ck.last_report["arrays_resharded"] >= 1
            assert not ck.last_report["fast_path"]


def test_reshard_2d_mesh_uneven(tmp_path):
    root = str(tmp_path / "ck")
    rng = np.random.default_rng(3)
    arr = rng.standard_normal((7, 5)).astype("float32")
    state = {"a": paddle.to_tensor(arr)}
    mesh = MeshSpec(("dp", "mp"), (2, 2))

    def pf(key, a):
        return ("dp", "mp")
    _save_world(root, state, mesh, pf, step=0)
    # every saved shard file holds only its 2-D tile
    layout = read_layout(os.path.join(root, "ckpt-00000000"))
    from paddle_tpu.framework.io import load
    s3 = load(os.path.join(root, "ckpt-00000000",
                           layout["rank_files"]["3"]))
    np.testing.assert_array_equal(_np(s3["arrays"]["a"]), arr[4:7, 3:5])
    # reassemble on a 3-rank dp-only mesh
    mesh3 = MeshSpec(("dp",), (3,))
    for rank in range(3):
        state_r, report = restore_resharded(
            os.path.join(root, "ckpt-00000000"), mesh3, rank)
        np.testing.assert_array_equal(_np(state_r["a"]), arr)
        assert report["files_read"] == 4        # all tiles needed


def test_fast_path_same_layout_bit_equal(tmp_path):
    root = str(tmp_path / "ck")
    state = _state()
    mesh2 = MeshSpec(("dp",), (2,))
    _save_world(root, state, mesh2, _moment_partition, step=0)
    # identical mesh + identical (saved) partition target → fast path:
    # the rank's own file, nothing else
    path = os.path.join(root, "ckpt-00000000")
    layout = read_layout(path)

    def same_part(key, meta):
        return tuple(layout["arrays"][key]["partition"]) \
            if key in layout["arrays"] else tuple(meta["partition"])
    for rank in range(2):
        st, report = restore_resharded(
            path, mesh2, rank,
            target_partition_fn=lambda k, m: tuple(m["partition"]))
        assert report["fast_path"] and report["files_read"] == 1
        np.testing.assert_array_equal(_np(st["model"]["w"]),
                                      _np(state["model"]["w"]))
        # fast path returns the rank's own moment SLICE verbatim
        lo, hi = split_bounds(7, 2, rank)
        np.testing.assert_array_equal(
            _np(st["optimizer"]["moment1.0"]),
            _np(state["optimizer"]["moment1.0"])[lo:hi])
    # replicated-only state: default (replicate) target also fast-paths
    root2 = str(tmp_path / "ck2")
    _save_world(root2, {"w": state["model"]["w"]}, mesh2, None, step=0)
    st, report = restore_resharded(
        os.path.join(root2, "ckpt-00000000"), mesh2, 1)
    assert report["fast_path"] and report["files_read"] == 1
    np.testing.assert_array_equal(_np(st["w"]), _np(state["model"]["w"]))


def test_pre_layout_checkpoint_loads_and_errors(tmp_path):
    """Satellite: a pre-PR-6 checkpoint (no layout section) still loads
    whole via the latest-valid scan, and an explicit reshard request
    raises the versioned LayoutError — never a KeyError."""
    root = str(tmp_path / "legacy")
    state = {"model": {"w": paddle.to_tensor(np.ones((3, 2), "float32"))},
             "next_epoch": 2}
    CheckpointManager(root).save(state, step=0)

    mesh = MeshSpec(("dp",), (1,))
    out = restore_latest_resharded(root, mesh, 0)
    assert out is not None
    st, step, report = out
    assert report["format"] == "legacy" and step == 0
    np.testing.assert_array_equal(_np(st["model"]["w"]),
                                  np.ones((3, 2), "float32"))

    path = os.path.join(root, "ckpt-00000000")
    with pytest.raises(LayoutError) as ei:
        restore_resharded(path, MeshSpec(("dp",), (2,)), 0)
    assert not isinstance(ei.value, KeyError)
    assert "layout" in str(ei.value) and "version" in str(ei.value)

    with pytest.raises(LayoutError):
        restore_latest_resharded(root, mesh, 0, strict_layout=True)


def test_layout_mismatch_names_both_layouts(tmp_path):
    root = str(tmp_path / "ck")
    state = {"a": paddle.to_tensor(
        np.arange(24, dtype="float32").reshape(6, 4))}
    mesh22 = MeshSpec(("dp", "mp"), (2, 2))

    def pf(key, a):
        return ("dp", "mp")
    _save_world(root, state, mesh22, pf, step=0)
    path = os.path.join(root, "ckpt-00000000")
    # requesting the SAVED partition on a mesh without the mp axis
    with pytest.raises(LayoutMismatchError) as ei:
        restore_resharded(path, MeshSpec(("dp",), (2,)), 0,
                          target_partition_fn=lambda k, m: ("dp", "mp"))
    msg = str(ei.value)
    assert "dp=2×mp=2" in msg and "dp=2" in msg  # names both layouts


def test_reshard_on_resume_flag_off_fails_loudly(tmp_path):
    root = str(tmp_path / "ck")
    state = {"a": paddle.to_tensor(np.ones((4, 2), "float32"))}
    mesh2 = MeshSpec(("dp",), (2,))
    _save_world(root, state, mesh2, None, step=0)
    path = os.path.join(root, "ckpt-00000000")
    set_flags({"FLAGS_reshard_on_resume": False})
    try:
        # same layout still restores (fast path needs no resharding) …
        st, report = restore_resharded(
            path, mesh2, 0,
            target_partition_fn=lambda k, m: tuple(m["partition"]))
        assert report["fast_path"]
        # … but a topology change now fails loudly, naming both sides
        with pytest.raises(LayoutMismatchError) as ei:
            restore_resharded(path, MeshSpec(("dp",), (4,)), 0)
        msg = str(ei.value)
        assert "dp=2" in msg and "dp=4" in msg
        assert "FLAGS_reshard_on_resume" in msg
    finally:
        set_flags({"FLAGS_reshard_on_resume": True})


def test_optimizer_state_roundtrip_through_reshard(tmp_path):
    """AdamW moments sharded to disk on world 4, reassembled on world 1:
    continuing training must match the uninterrupted run exactly (same
    process, same arithmetic — byte-for-byte)."""
    def build():
        paddle.seed(11)
        m = nn.Sequential(nn.Linear(5, 9), nn.Tanh(), nn.Linear(9, 3))
        o = paddle.optimizer.AdamW(1e-2, parameters=m.parameters())
        return m, o

    def step(m, o, i):
        rng = np.random.default_rng(i)
        x = paddle.to_tensor(rng.standard_normal((4, 5)).astype("float32"))
        y = paddle.to_tensor(rng.standard_normal((4, 3)).astype("float32"))
        loss = ((m(x) - y) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        return float(loss.numpy())

    # uninterrupted reference
    m_ref, o_ref = build()
    ref = [step(m_ref, o_ref, i) for i in range(6)]

    # train 3, save sharded over a virtual 4-rank mesh, restore, continue
    m, o = build()
    first = [step(m, o, i) for i in range(3)]
    root = str(tmp_path / "ck")
    mesh4 = MeshSpec(("dp",), (4,))
    _save_world(root, {"model": m.state_dict(),
                       "optimizer": o.state_dict()},
                mesh4, _moment_partition, step=2)

    m2, o2 = build()
    ck = ShardedCheckpointer(root, MeshSpec(("dp",), (1,)), 0)
    restored, _step = ck.restore_latest()
    assert ck.last_report["arrays_resharded"] >= 1
    m2.set_state_dict(restored["model"])
    o2.set_state_dict(restored["optimizer"])
    rest = first + [step(m2, o2, i) for i in range(3, 6)]
    assert rest == ref                      # byte-equal continuation


def test_shard_fetch_via_guardian_store(tmp_path):
    """A shard file unreadable on this host rides the PR 5 guardian-store
    substrate: a peer offers it, the restorer fetches it."""
    from paddle_tpu.distributed.store import FileKVStore
    root = str(tmp_path / "ck")
    rng = np.random.default_rng(5)
    arr = rng.standard_normal((6, 3)).astype("float32")
    state = {"a": paddle.to_tensor(arr)}
    mesh2 = MeshSpec(("dp",), (2,))

    def pf(key, a):
        return ("dp",) + (None,) * (a.ndim - 1)
    _save_world(root, state, mesh2, pf, step=0)
    path = os.path.join(root, "ckpt-00000000")
    store = FileKVStore(str(tmp_path / "kv"))
    assert offer_shards(store, path) == 2   # both files posted

    # delete rank 1's shard file locally; crc check would now fail, so
    # restore the directory directly (the cross-host case: the manifest
    # is readable, one payload file is not)
    layout = read_layout(path)
    os.remove(os.path.join(path, layout["rank_files"]["1"]))
    st, report = restore_resharded(path, MeshSpec(("dp",), (1,)), 0,
                                   store=store, fetch_timeout_s=5)
    np.testing.assert_array_equal(_np(st["a"]), arr)

    # no store, missing file → clear CheckpointError, not a hang
    with pytest.raises(CheckpointError):
        restore_resharded(path, MeshSpec(("dp",), (1,)), 0,
                          store=FileKVStore(str(tmp_path / "kv2")),
                          fetch_timeout_s=0.2)


def test_sharded_retention_and_torn_dir_skipped(tmp_path):
    root = str(tmp_path / "ck")
    mesh1 = MeshSpec(("dp",), (1,))
    ck = ShardedCheckpointer(root, mesh1, 0, max_to_keep=2)
    for s in range(4):
        ck.save({"v": paddle.to_tensor(np.full((2,), s, "float32"))},
                step=s)
    names = sorted(os.listdir(root))
    assert names == ["ckpt-00000002", "ckpt-00000003"]
    # tear the newest (drop its manifest) → restore falls back to older
    os.remove(os.path.join(root, "ckpt-00000003", "manifest.json"))
    st, step = ck.restore_latest()
    assert step == 2 and float(_np(st["v"])[0]) == 2.0


def test_barrier_timeout_leaves_torn_dir(tmp_path):
    root = str(tmp_path / "ck")
    mesh2 = MeshSpec(("dp",), (2,))
    ck0 = ShardedCheckpointer(root, mesh2, 0, barrier_timeout_s=0.4)
    with pytest.raises(CheckpointError):
        ck0.save({"v": paddle.to_tensor(np.ones((2,), "float32"))},
                 step=0)                    # rank 1 never shows up
    # no manifest committed → scan treats it as torn
    assert ck0.restore_latest() is None


def test_hapi_fit_resumes_resharded_checkpoint(tmp_path):
    """A checkpoint written by a (simulated) 2-rank hapi job resumes on a
    single process: Model.fit(resume=...) reshards model + optimizer and
    continues at the recorded epoch."""
    class Data:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            rng = np.random.default_rng(i)
            return (rng.normal(size=(4,)).astype(np.float32),
                    np.array([i % 2], dtype=np.int64))

    def build():
        paddle.seed(3)
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        model = paddle.Model(net)
        model.prepare(optimizer=paddle.optimizer.SGD(
            learning_rate=0.1, parameters=net.parameters()),
            loss=nn.CrossEntropyLoss())
        return net, model

    net, model = build()
    model.fit(Data(), batch_size=4, epochs=1, verbose=0)
    save_dir = str(tmp_path / "ck")
    state = {"model": net.state_dict(),
             "optimizer": model._optimizer.state_dict(),
             "next_epoch": 1}
    _save_world(save_dir, state, MeshSpec(("dp",), (2,)), None, step=0)

    net2, model2 = build()
    hist = model2.fit(Data(), batch_size=4, epochs=2, verbose=0,
                      resume=save_dir)
    # epoch 0 was skipped (resumed at 1) and weights came from the ckpt
    assert len(hist["loss"]) == 1
    for k, v in net.state_dict().items():
        got = net2.state_dict()[k]
        # weights continued FROM the checkpoint; equality not expected
        # after another epoch — just assert the restore happened by
        # shape/dtype and that training progressed
        assert _np(got).shape == _np(v).shape


# ---------------------------------------------------------------------------
# subprocess resize drills
# ---------------------------------------------------------------------------

def _launch(nproc, outdir, fault=None, max_restart=0):
    from paddle_tpu.distributed.launch.context import Context, parse_args
    from paddle_tpu.distributed.launch.controller import \
        CollectiveController
    args = parse_args(["--nproc_per_node", str(nproc),
                       "--max_restart", str(max_restart),
                       WORKER, str(outdir)])
    old = os.environ.get("FLAGS_fault_inject")
    if fault is not None:
        os.environ["FLAGS_fault_inject"] = fault
    else:
        os.environ.pop("FLAGS_fault_inject", None)
    try:
        return CollectiveController(Context(args=args)).run()
    finally:
        if old is None:
            os.environ.pop("FLAGS_fault_inject", None)
        else:
            os.environ["FLAGS_fault_inject"] = old


def _reference_losses(tmp_path):
    d = tmp_path / "ref"
    d.mkdir()
    assert _launch(1, d) == 0
    with open(d / "losses.json") as f:
        return json.load(f)


def _assert_drill(tmp_path, ref, w_before, w_after):
    from paddle_tpu.distributed.fleet.elastic import ELASTIC_EXIT_CODE
    d = tmp_path / f"resize_{w_before}_{w_after}"
    d.mkdir()
    # incarnation 1: SIGTERM at step 3 → save-at-boundary → exit 101
    code = _launch(w_before, d, fault="step:sigterm_at=3")
    assert code == ELASTIC_EXIT_CODE
    assert not (d / "losses.json").exists()
    # incarnation 2: the slice came back a different size
    assert _launch(w_after, d) == 0
    with open(d / "losses.json") as f:
        got = json.load(f)
    assert len(got) == len(ref)
    np.testing.assert_allclose(got, ref, rtol=0, atol=5e-4)
    lines = [ln.split(":") for ln in
             (d / "incarnations.log").read_text().splitlines()]
    first = [ln for ln in lines if ln[1] == str(w_before)]
    second = [ln for ln in lines if ln[1] == str(w_after)]
    assert len(first) == w_before and len(second) == w_after
    assert all(ln[2] == "0" for ln in first)       # fresh start
    assert all(ln[2] == "4" for ln in second)      # resumed after step 3
    # the resumed incarnation really RESHARDED (no fast path, moments
    # reassembled from the old world's shards)
    assert all(ln[3] == "0" and int(ln[4]) >= 1 for ln in second)
    return got


def test_resize_4_to_2_drill(tmp_path):
    ref = _reference_losses(tmp_path)
    assert len(ref) == 6
    _assert_drill(tmp_path, ref, 4, 2)


def test_resize_2_to_4_drill(tmp_path):
    ref = _reference_losses(tmp_path)
    _assert_drill(tmp_path, ref, 2, 4)
