"""FleetExecutor: interceptor/actor-based host runtime.

Reference capability: paddle/fluid/distributed/fleet_executor/ —
`FleetExecutor` (fleet_executor.h:36) runs a `TaskNode` graph; a `Carrier`
(carrier.h:50) owns `Interceptor` actors (interceptor.h:51) that exchange
`InterceptorMessage`s (compute_interceptor.cc drives per-micro-batch
execution with upstream/downstream buffer credits; message_bus.cc does
inter-rank brpc).

TPU-native realization: XLA owns the device schedule, so the actor
runtime's remaining role is HOST orchestration — driving per-stage
compiled programs (or IO / checkpoint / eval tasks) concurrently with
bounded buffers.  Interceptors are threads with credit-based queues; the
in-process message bus maps 1:1 onto the reference's message protocol and
would ride the RPC agent (distributed/rpc) across hosts.
"""
from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field

__all__ = ["TaskNode", "FleetExecutor", "Carrier", "Interceptor"]

_STOP = object()


@dataclass
class TaskNode:
    """One actor's work description (reference: task_node.h).

    fn(micro_batch_index, inputs_from_upstreams: list) -> output
    """
    task_id: int
    fn: callable = None
    upstreams: list = field(default_factory=list)    # task ids
    downstreams: list = field(default_factory=list)  # task ids
    max_run_times: int = 1                           # micro-batch count
    buffer_size: int = 2                             # downstream credits


class Interceptor(threading.Thread):
    """Actor: waits for one message per upstream per micro-batch, computes,
    sends to downstreams (reference: compute_interceptor.cc Compute())."""

    def __init__(self, node: TaskNode, carrier):
        super().__init__(daemon=True)
        self.node = node
        self.carrier = carrier
        # unbounded inbox + a pending map: out-of-order messages are held
        # aside, never re-queued (a bounded requeue can deadlock against
        # blocked producers and busy-spins while waiting); backpressure
        # comes from the per-edge credit semaphores in the Carrier
        self.inbox = queue.Queue()
        self._pending: dict = {}
        self.error = None

    def _recv(self, mb):
        """Block until every upstream's message for micro-batch mb is in."""
        ups = self.node.upstreams
        while any((u, mb) not in self._pending for u in ups):
            msg = self.inbox.get()
            if msg is _STOP:
                return None
            src, idx, payload = msg
            self._pending[(src, idx)] = payload
        out = [self._pending.pop((u, mb)) for u in ups]
        for u in ups:
            self.carrier.release_credit(u, self.node.task_id)
        return out

    def run(self):
        node = self.node
        try:
            for mb in range(node.max_run_times):
                inputs = []
                if node.upstreams:
                    inputs = self._recv(mb)
                    if inputs is None:   # aborted
                        return
                out = node.fn(mb, inputs) if node.fn else None
                self.carrier.record(node.task_id, mb, out)
                for d in node.downstreams:
                    self.carrier.send(d, (node.task_id, mb, out),
                                      src=node.task_id)
        except Exception as e:   # surface actor failures to the driver
            self.error = e
            self.carrier.abort()


class Carrier:
    """Owns this rank's interceptors and the in-process message bus
    (reference: carrier.h:50 + message_bus.cc)."""

    def __init__(self, nodes):
        self.nodes = {n.task_id: n for n in nodes}
        self.interceptors = {tid: Interceptor(n, self)
                             for tid, n in self.nodes.items()}
        self.results = {}
        self._aborted = threading.Event()
        # per-edge credits bound how far a producer runs ahead
        # (reference: compute_interceptor.cc upstream/downstream buffers)
        self._credits = {}
        for n in nodes:
            for u in n.upstreams:
                self._credits[(u, n.task_id)] = threading.Semaphore(
                    max(n.buffer_size, 1))

    def send(self, task_id, msg, src=None):
        sem = self._credits.get((src, task_id))
        if sem is not None:
            while not sem.acquire(timeout=0.1):
                if self._aborted.is_set():
                    return
        self.interceptors[task_id].inbox.put(msg)

    def release_credit(self, src, dst):
        sem = self._credits.get((src, dst))
        if sem is not None:
            sem.release()

    def record(self, task_id, mb, out):
        self.results[(task_id, mb)] = out

    def abort(self):
        self._aborted.set()
        for it in self.interceptors.values():
            try:
                it.inbox.put_nowait(_STOP)
            except queue.Full:
                pass

    def run(self, timeout=None):
        import time as _time
        deadline = None if timeout is None else _time.time() + timeout
        for it in self.interceptors.values():
            it.start()
        for it in self.interceptors.values():
            # shared deadline: N sequential joins must not multiply the
            # timeout, and the task blamed is whichever is alive at expiry
            remaining = None if deadline is None else \
                max(deadline - _time.time(), 0.0)
            it.join(timeout=remaining)
            if it.is_alive():
                self.abort()
                raise TimeoutError(
                    f"interceptor {it.node.task_id} did not finish")
        for it in self.interceptors.values():
            if it.error is not None:
                raise it.error
        return self.results


class FleetExecutor:
    """Builds a Carrier from TaskNodes and runs the graph
    (reference: fleet_executor.h:36 Init/Run)."""

    def __init__(self, task_nodes):
        self._nodes = list(task_nodes)
        self.carrier = None

    def run(self, timeout=60.0):
        self.carrier = Carrier(self._nodes)
        return self.carrier.run(timeout=timeout)

    def fetch(self, task_id):
        """Outputs of one task across micro-batches, in order."""
        n = self.carrier.nodes[task_id].max_run_times
        return [self.carrier.results.get((task_id, mb)) for mb in range(n)]
