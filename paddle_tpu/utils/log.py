"""Structured logging: rank-aware framework logger.

Reference capability: `fleet/utils/log_util.py` logger +
`base/log_helper.py` (per-rank prefixes, level from env) and glog VLOG
levels on the C++ side.

TPU-native realization: one `logging.Logger` ("paddle_tpu") with a
rank-stamped formatter (rank read lazily — before jax.distributed init it
shows rank -).  `set_log_level` maps the reference's VLOG-style levels;
`log_every_n` mirrors the common glog idiom used in training loops.
"""
from __future__ import annotations

import logging
import os
import sys

import threading

_LOGGERS: dict = {}
_COUNTS: dict[str, int] = {}
_COUNTS_LOCK = threading.Lock()


class _RankFormatter(logging.Formatter):
    def format(self, record):
        rank = os.environ.get("PADDLE_TRAINER_ID")
        if rank is None:
            try:
                import jax
                rank = str(jax.process_index())
            except Exception:
                rank = "-"
        record.rank = rank
        return super().format(record)


class _DynamicStderrHandler(logging.StreamHandler):
    """StreamHandler that resolves sys.stderr at EMIT time, not handler
    creation: the process-global logger is created lazily by whichever
    subsystem logs first, and binding the stream then would strand later
    output on a stale redirected/captured stderr."""

    def __init__(self):
        logging.Handler.__init__(self)

    @property
    def stream(self):
        return sys.stderr


def get_logger(name="paddle_tpu"):
    logger = _LOGGERS.get(name)
    if logger is None:
        logger = logging.getLogger(name)
        if not logger.handlers:
            h = _DynamicStderrHandler()
            h.setFormatter(_RankFormatter(
                "%(asctime)s [rank %(rank)s] %(levelname)s "
                "%(name)s: %(message)s"))
            logger.addHandler(h)
        logger.setLevel(os.environ.get("PADDLE_LOG_LEVEL", "INFO").upper())
        logger.propagate = False
        _LOGGERS[name] = logger
    return logger


def set_log_level(level):
    get_logger().setLevel(
        level.upper() if isinstance(level, str) else level)


def log_every_n(level, msg, n=100, *args):
    """Emit every n-th occurrence of this message site (glog idiom)."""
    key = f"{level}:{msg}"
    with _COUNTS_LOCK:
        c = _COUNTS.get(key, 0)
        _COUNTS[key] = c + 1
    if c % n == 0:
        get_logger().log(getattr(logging, level.upper(), logging.INFO),
                         msg, *args)
