"""Device mesh abstraction.

Reference capability: `ProcessMesh` (reference:
paddle/phi/core/distributed/auto_parallel/process_mesh.h:31 and
python/paddle/distributed/auto_parallel/process_mesh.py) — an N-D cartesian
arrangement of ranks with named axes, the substrate every parallelism
strategy shards over.

TPU-native realization: a thin, pickle-friendly wrapper over
`jax.sharding.Mesh`.  Axis layout matters on TPU: the *last* mesh axis is
laid out over the fastest-varying (adjacent-on-ICI) device order, so model
axes that carry heavy collectives ("mp"/"sp") should come last — the JAX
convention — while slow axes ("pp", then "dp") come first and may ride DCN
across slices.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh


class ProcessMesh:
    """N-D named device mesh (reference: process_mesh.h:31).

    `mesh` — array of device ids (or jax devices) shaped like the topology.
    `dim_names` — one name per mesh axis, e.g. ["dp", "mp"].
    """

    def __init__(self, mesh, dim_names=None, process_ids=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError(
                f"dim_names {dim_names} does not match mesh ndim {arr.ndim}")
        self._shape = tuple(arr.shape)
        self._dim_names = tuple(dim_names)
        if arr.dtype == object:  # already jax devices
            devices = arr
            self._process_ids = np.array(
                [d.id for d in arr.flat]).reshape(arr.shape)
        else:
            all_devices = {d.id: d for d in jax.devices()}
            self._process_ids = arr.astype(np.int64)
            devices = np.empty(arr.shape, dtype=object)
            for idx, did in np.ndenumerate(arr):
                devices[idx] = all_devices[int(did)]
        self._jax_mesh = Mesh(devices, axis_names=self._dim_names)

    # ---- reference-parity surface ----
    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return [int(x) for x in self._process_ids.flat]

    @property
    def mesh(self):
        return self._process_ids

    def get_dim_size(self, name):
        return self._shape[self._dim_names.index(name)]

    # ---- jax interop ----
    @property
    def jax_mesh(self) -> Mesh:
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and self._dim_names == other._dim_names
                and np.array_equal(self._process_ids, other._process_ids))

    def __hash__(self):
        return hash((self._dim_names, self._process_ids.tobytes()))

    def __repr__(self):
        return (f"ProcessMesh(shape={list(self._shape)}, "
                f"dim_names={list(self._dim_names)})")

    def __enter__(self):
        _MESH_STACK.append(self)
        return self

    def __exit__(self, *exc):
        _MESH_STACK.pop()


_MESH_STACK: list[ProcessMesh] = []


def get_mesh() -> ProcessMesh | None:
    """Innermost `with mesh:` scope, else the globally-set default."""
    if _MESH_STACK:
        return _MESH_STACK[-1]
    return _DEFAULT[0]


_DEFAULT: list = [None]


def set_mesh(mesh: ProcessMesh):
    _DEFAULT[0] = mesh


from contextlib import contextmanager


@contextmanager
def suspended():
    """Temporarily deactivate the scoped AND default mesh.

    Used by ragged-batch eager fallbacks (framework/train_step.py): a
    batch that does not divide the dp axis cannot satisfy the model's
    activation ``shard_constraint``s in ANY lane, but with the mesh
    scope lifted those constraints become no-ops while committed
    (sharded) parameters still compute the same values through GSPMD
    eager propagation."""
    saved_stack = _MESH_STACK[:]
    saved_default = _DEFAULT[0]
    del _MESH_STACK[:]
    _DEFAULT[0] = None
    try:
        yield
    finally:
        _MESH_STACK[:] = saved_stack
        _DEFAULT[0] = saved_default


def init_mesh(shape, dim_names, devices=None) -> ProcessMesh:
    """Build a mesh over the first prod(shape) available devices.

    On real hardware prefer `jax.experimental.mesh_utils` contiguity; here we
    keep device order (jax.devices() is already ICI-contiguous on TPU).
    """
    devices = devices if devices is not None else jax.devices()
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh shape {shape} needs {n} devices, "
                         f"have {len(devices)}")
    try:
        from jax.experimental import mesh_utils
        dev_arr = mesh_utils.create_device_mesh(
            tuple(shape), devices=devices[:n])
    except Exception:
        dev_arr = np.array(devices[:n], dtype=object).reshape(shape)
    mesh = ProcessMesh(np.array(dev_arr, dtype=object), dim_names)
    return mesh
