"""Sparse tensor API.

Reference capability: `paddle.sparse` (reference: python/paddle/sparse/ —
COO/CSR creation, elementwise/matmul/nn ops backed by
paddle/phi/kernels/sparse/).

TPU-native realization: BCOO from jax.experimental.sparse — XLA lowers
sparse ops to gather/scatter/segment-sum which map onto the TPU's
vector/scatter units; CSR is stored but computed via BCOO (the TPU has no
native CSR unit, and BCOO batches better on the MXU).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from ..core.dispatch import apply_op


class SparseCooTensor(Tensor):
    """COO sparse tensor; `_data_` holds the BCOO (bypasses the dense
    asarray in Tensor.__init__)."""

    def __init__(self, bcoo, stop_gradient=True):
        self._data_ = bcoo
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._out_index = 0
        self.name = None
        self.persistable = False
        self.trainable = not stop_gradient
        self._hooks = []
        self.optimize_attr = {}
        self.regularizer = None
        self.is_dist_param = False
        self.placements = None
        self.process_mesh = None

    # reference surface
    def indices(self):
        return Tensor(self._data_.indices.T)

    def values(self):
        return Tensor(self._data_.data)

    def to_dense(self):
        return Tensor(self._data_.todense())

    def nnz(self):
        return int(self._data_.nse)

    @property
    def shape(self):
        return list(self._data_.shape)

    def is_sparse_coo(self):
        return True


class SparseCsrTensor(SparseCooTensor):
    """CSR view: stores crows/cols/values, computes as BCOO."""

    def __init__(self, crows, cols, values, shape):
        self._crows = np.asarray(crows)
        self._cols = np.asarray(cols)
        rows = np.repeat(np.arange(len(self._crows) - 1),
                         np.diff(self._crows))
        idx = jnp.stack([jnp.asarray(rows), jnp.asarray(self._cols)],
                        axis=1)
        bcoo = jsparse.BCOO((jnp.asarray(values), idx), shape=tuple(shape))
        super().__init__(bcoo)

    def crows(self):
        return Tensor(jnp.asarray(self._crows))

    def cols(self):
        return Tensor(jnp.asarray(self._cols))

    def is_sparse_csr(self):
        return True

    def is_sparse_coo(self):
        return False


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """reference: paddle.sparse.sparse_coo_tensor(indices [ndim, nnz])."""
    idx = np.asarray(indices if not isinstance(indices, Tensor)
                     else indices.numpy())
    vals = jnp.asarray(values if not isinstance(values, Tensor)
                       else values._data_)
    if dtype is not None:
        vals = vals.astype(dtype)
    if shape is None:
        shape = tuple(int(i.max()) + 1 for i in idx)
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseCooTensor(bcoo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    return SparseCsrTensor(crows, cols, values, shape)


def _dense_data(x):
    if isinstance(x, SparseCooTensor):
        return x._data_
    if isinstance(x, Tensor):
        return x._data_
    return jnp.asarray(x)


def matmul(x, y, name=None):
    """Sparse @ dense (reference: paddle.sparse.matmul)."""
    out = apply_op("sparse_matmul",
                   lambda a, b: a @ b if not isinstance(a, jsparse.BCOO)
                   else jsparse.bcoo_dot_general(
                       a, b, dimension_numbers=(((a.ndim - 1,), (0,)),
                                                ((), ()))),
                   (x, y))
    return out


def add(x, y, name=None):
    xb, yb = x._data_, y._data_
    if isinstance(xb, jsparse.BCOO) and isinstance(yb, jsparse.BCOO):
        s = jsparse.bcoo_add_indices_compatible \
            if hasattr(jsparse, "bcoo_add_indices_compatible") else None
        out = (xb.todense() + yb.todense())
        return sparse_coo_tensor(
            np.nonzero(np.asarray(out)), out[out != 0], out.shape)
    return Tensor(_dense_data(x) + _dense_data(y))


def relu(x, name=None):
    b = x._data_
    new = jsparse.BCOO((jax.nn.relu(b.data), b.indices), shape=b.shape)
    return SparseCooTensor(new)


class nn:
    """paddle.sparse.nn parity namespace (ReLU as the canonical member)."""

    class ReLU:
        def __call__(self, x):
            return relu(x)


# ------------------------------------------------------------------
# elementwise / unary surface (reference: python/paddle/sparse/unary.py,
# binary.py — values-only ops preserve the sparsity pattern)
# ------------------------------------------------------------------

def _unary(fn):
    def op(x, name=None):
        b = x._data_
        if isinstance(b, jsparse.BCOO):
            new = jsparse.BCOO((fn(b.data), b.indices), shape=b.shape)
            return SparseCooTensor(new, stop_gradient=x.stop_gradient)
        return Tensor(fn(b))
    return op


abs = _unary(jnp.abs)  # noqa: A001
sin = _unary(jnp.sin)
sinh = _unary(jnp.sinh)
tan = _unary(jnp.tan)
tanh = _unary(jnp.tanh)
asin = _unary(jnp.arcsin)
asinh = _unary(jnp.arcsinh)
atan = _unary(jnp.arctan)
atanh = _unary(jnp.arctanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
expm1 = _unary(jnp.expm1)
neg = _unary(jnp.negative)
deg2rad = _unary(jnp.deg2rad)
rad2deg = _unary(jnp.rad2deg)
isnan = _unary(jnp.isnan)


def pow(x, factor, name=None):  # noqa: A001
    return _unary(lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    b = x._data_
    vals = b.data if value_dtype is None else b.data.astype(value_dtype)
    idx = b.indices if index_dtype is None else \
        b.indices.astype(index_dtype)
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=b.shape))


def _binary(fn):
    def op(x, y, name=None):
        xb, yb = x._data_, y._data_
        both = isinstance(xb, jsparse.BCOO) and isinstance(yb, jsparse.BCOO)
        if both and xb.indices.shape == yb.indices.shape and \
                bool(jnp.all(xb.indices == yb.indices)):
            # same pattern: values-only (the common case the reference's
            # same-shape kernels handle)
            return SparseCooTensor(jsparse.BCOO(
                (fn(xb.data, yb.data), xb.indices), shape=xb.shape))
        xd = xb.todense() if isinstance(xb, jsparse.BCOO) else xb
        yd = yb.todense() if isinstance(yb, jsparse.BCOO) else yb
        out = fn(xd, yd)
        dense = np.asarray(out)
        return sparse_coo_tensor(np.nonzero(dense), dense[dense != 0],
                                 dense.shape)
    return op


subtract = _binary(jnp.subtract)
multiply = _binary(jnp.multiply)
divide = _binary(jnp.divide)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def coalesce(x, name=None):
    b = x._data_
    return SparseCooTensor(b.sum_duplicates(), stop_gradient=x.stop_gradient)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    d = x._data_.todense() if isinstance(x._data_, jsparse.BCOO) else x._data_
    out = jnp.sum(d, axis=axis, keepdims=keepdim)
    if dtype is not None:
        out = out.astype(dtype)
    return Tensor(out)


def mv(x, vec, name=None):
    """Sparse matrix × dense vector."""
    b = x._data_
    v = vec._data_ if isinstance(vec, Tensor) else jnp.asarray(vec)
    return Tensor(jsparse.bcoo_dot_general(
        b, v, dimension_numbers=(((b.ndim - 1,), (0,)), ((), ()))))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    """beta*input + alpha*(sparse x @ dense y)."""
    prod = matmul(x, y)
    return Tensor(beta * _dense_data(input) + alpha * prod._data_)


def masked_matmul(x, y, mask, name=None):
    """Dense @ dense evaluated ONLY at mask's sparsity pattern
    (reference: sparse/binary.py masked_matmul — SDDMM)."""
    xd, yd = _dense_data(x), _dense_data(y)
    mb = mask._data_
    rows = mb.indices[:, 0]
    cols = mb.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xd[rows, :], yd[:, cols].T)
    return SparseCooTensor(jsparse.BCOO((vals, mb.indices),
                                        shape=mb.shape))


def transpose(x, perm, name=None):
    b = x._data_
    return SparseCooTensor(jsparse.bcoo_transpose(b, permutation=tuple(perm)))


def reshape(x, shape, name=None):
    b = x._data_
    shape = tuple(int(s) if s != -1 else -1 for s in shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        total = int(np.prod(b.shape))
        shape = tuple(total // known if s == -1 else s for s in shape)
    return SparseCooTensor(jsparse.bcoo_reshape(b, new_sizes=shape))


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    d = x._data_.todense()
    idx = [np.s_[:]] * d.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[ax] = np.s_[s:e]
    out = np.asarray(d[tuple(idx)])
    return sparse_coo_tensor(np.nonzero(out), out[out != 0], out.shape)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized low-rank PCA (reference: sparse/multiary.py
    pca_lowrank); the sparse matmuls ride bcoo_dot_general."""
    d = x._data_.todense() if isinstance(x._data_, jsparse.BCOO) \
        else _dense_data(x)
    m, n = d.shape
    q = q if q is not None else min(6, m, n)
    if center:
        d = d - jnp.mean(d, axis=0, keepdims=True)
    key = jax.random.PRNGKey(0)
    omega = jax.random.normal(key, (n, q), d.dtype)
    y = d @ omega
    for _ in range(niter):
        y = d @ (d.T @ y)
    qmat, _ = jnp.linalg.qr(y)
    b = qmat.T @ d
    u_hat, s, vt = jnp.linalg.svd(b, full_matrices=False)
    u = qmat @ u_hat
    return Tensor(u), Tensor(s), Tensor(vt.T)
