"""Drain-aware serving router: the fleet's front door.

Reference capability: the reference serves at pod scale through a fleet
layer pairing replicated predictors with membership, failure detection
and elastic relaunch (PAPER.md layers 5/9).  TPU-native realization:
`ServingRouter` spreads requests over N `Engine` replicas living in
separate processes (or threads, in tests), with

- **membership + gossip** over `distributed/store.py`: each replica
  heartbeats a TTL lease (`TCPElasticStore`) and gossips a
  `fleet.{name}` info record — rpc endpoint, lifecycle state
  (`warming|ready|draining`), join generation, and load (queue depth,
  active slots) — which the router polls to maintain its ring;
- **session-affine consistent hashing**: requests carrying the same
  `session_id` (or sharing a prompt prefix when none is given) hash to
  the same replica, so its warm prefix cache keeps serving them; a
  replica joining or leaving only remaps the sessions it owns;
- **load shedding with the engine's own admission semantics**: a
  replica at capacity raises `QueueFullError` through the rpc plane;
  the router spills to ring successors and, when EVERY ready replica
  sheds, fails fast with `QueueFullError(retry_after_s=...)` instead of
  queueing unboundedly.  Deadlines propagate end to end: the remaining
  budget rides along to the replica engine and bounds the rpc wait;
- **failure detection + transparent resubmission**: a dead replica is
  detected by its dropped rpc connection (SIGKILL closes the socket
  mid-call) or its expired heartbeat lease; in-flight requests are
  resubmitted to survivors under the SAME idempotent request id.  A
  request's Future resolves exactly once, so token delivery is
  at-most-once — never a duplicate, never a silently dropped stream.
  An rpc *timeout* against a replica that is still heartbeating is
  ambiguous (the call may be executing) and fails LOUDLY rather than
  hanging or blindly retrying;
- **drain awareness**: a replica entering `draining` (SIGTERM) stops
  receiving new routes within one poll interval; its queued requests
  bounce back as `EngineShutdownError` and are resubmitted to
  survivors, while its active slots finish inside the drain deadline.
  Fresh replicas register `warming`, flip to `ready`, and the watcher
  warms them into the ring (scale up).

Prefill/decode disaggregation (`RouterConfig.disaggregation`, ISSUE
14): replicas gossip a role, candidates order prefill > mixed >
decode, and every submit carries the least-loaded ready decode replica
as its KV-page migration target — the prefill replica streams the
finished prompt's pages there and the request resumes decoding with
its cache intact, bit-equal to never having moved.  Knob off: routing
is byte-identical to the symmetric fleet.

Anti-flap protocol (with `TCPElasticStore.reap`): a replica whose lease
expires is marked dead *sticky* under its join generation — resumed
heartbeats on the stale lease do NOT resurrect it.  The watcher reaps
the expired lease; the replica's own heartbeat loop notices the reap
and re-registers with a bumped generation, which the router accepts as
an explicit rejoin.  Membership events are edges, never oscillation.
"""
from __future__ import annotations

import bisect
import hashlib
import itertools
import json
import threading
import time
from collections import deque
from concurrent.futures import FIRST_COMPLETED, Future
from concurrent.futures import wait as _futures_wait
from dataclasses import dataclass

import numpy as np

from . import stats
from ..observability import tracing
from .api import (DeadlineExceededError, EngineShutdownError,
                  NoReplicaError, QueueFullError,
                  RequestCancelledError, RequestOutput,
                  SamplingParams, ServingError)

#: membership key prefixes on the fleet store (shared with fleet.py)
INFO_PREFIX = "fleet."


@dataclass
class RouterConfig:
    """Router knobs (docs/KNOBS.md "serving fleet" table).

    heartbeat_ttl_s      replica lease: heartbeats older than this mark
                         the replica dead (sticky until it re-registers)
    poll_interval_s      membership watcher cadence; also bounds how
                         long a draining replica keeps receiving routes
    rpc_timeout_s        per-attempt cap on one replica call (a request
                         deadline below this wins)
    max_resubmits        resubmission budget per request across replica
                         deaths before the router fails it loudly
    retry_after_s        backoff hint carried by shed requests'
                         QueueFullError (the 429 Retry-After analog)
    virtual_nodes        consistent-hash vnodes per replica: higher =
                         smoother spread, slower ring rebuild
    no_replica_patience_s how long submit-time dispatch waits for ANY
                         ready replica (fleet warming up / mid-failover)
                         before NoReplicaError
    request_timeout_s    sync generate()'s Future wait
    disaggregation       prefill/decode disaggregation: route new
                         requests to prefill-role replicas first
                         (prefill > mixed > decode preference, ring
                         order within a class — roles are preferences,
                         so a lone decode replica still serves direct
                         traffic) and assign each request the least-
                         loaded ready decode replica as its KV-page
                         migration target.  Off (default): roles are
                         ignored entirely — routing is byte-identical
                         to the symmetric fleet
    migrate_min_new_tokens  only requests decoding at least this many
                         tokens get a migration target — a short tail
                         is cheaper to decode where it prefilled than
                         to move (requests without an explicit
                         max_new_tokens always qualify)

    Gray-failure guardian (ISSUE 17, docs/RESILIENCE.md "Gray-failure
    guardian"; every knob defaults OFF — routing is then byte-identical
    to the guardian-less router):

    health_ejection      master switch for health-scored outlier
                         ejection: per-replica EWMA latency and error
                         rates are fed from EVERY dispatch; a replica
                         whose score exceeds a robust z-threshold vs
                         the fleet median is ejected from the candidate
                         order (reversible + generation-preserving,
                         unlike sticky-dead), canary-probed, and
                         readmitted on sustained recovery
    health_alpha         EWMA coefficient of the latency/error score
    eject_zscore         robust z (median/MAD) beyond which a replica
                         is an outlier
    eject_min_samples    dispatches a replica must have served before
                         it can be ejected (no ejection on noise)
    eject_max_fraction   never eject more than this fraction of the
                         ready fleet (and never the last replica)
    canary_interval_s    probe cadence for ejected replicas
    canary_timeout_s     rpc budget of one canary probe
    readmit_canaries     consecutive healthy canaries before
                         readmission (sustained recovery, not one
                         lucky probe)
    hedge_percentile     > 0 arms hedged dispatch: a primary attempt
                         still unanswered past this percentile of
                         recent route latencies fires ONE hedge to the
                         next candidate under the SAME idempotent rid
                         (the replica dedup cache makes the pair
                         at-most-once); first answer wins, the loser
                         is cancelled (`Engine.cancel`).  0 = off
    hedge_min_samples    recent-latency samples required before the
                         percentile is trusted (no hedging cold)
    breaker_failures     > 0 arms per-replica circuit breakers: this
                         many transport failures within
                         breaker_window_s opens the breaker (replica
                         skipped without paying an rpc), one trial
                         call after breaker_cooldown_s half-opens it,
                         and a trial success recloses.  0 = off
    breaker_window_s     sliding failure-count window
    breaker_cooldown_s   open -> half-open delay
    retry_budget_per_s   > 0 arms the fleet-wide token-bucket retry
                         budget: resubmissions (failover, drain
                         bounce, dead-timeout) spend a token; an empty
                         bucket fails the request instead of letting a
                         resubmission storm amplify an outage.  0 =
                         unlimited (the pre-guardian behavior)
    retry_budget_burst   bucket capacity (burst tolerance)
    """

    heartbeat_ttl_s: float = 3.0
    poll_interval_s: float = 0.2
    rpc_timeout_s: float = 120.0
    max_resubmits: int = 3
    retry_after_s: float = 1.0
    virtual_nodes: int = 64
    no_replica_patience_s: float = 30.0
    request_timeout_s: float = 120.0
    disaggregation: bool = False
    migrate_min_new_tokens: int = 2
    health_ejection: bool = False
    health_alpha: float = 0.3
    eject_zscore: float = 4.0
    eject_min_samples: int = 8
    eject_max_fraction: float = 0.5
    canary_interval_s: float = 0.5
    canary_timeout_s: float = 5.0
    readmit_canaries: int = 3
    hedge_percentile: float = 0.0
    hedge_min_samples: int = 16
    breaker_failures: int = 0
    breaker_window_s: float = 10.0
    breaker_cooldown_s: float = 2.0
    retry_budget_per_s: float = 0.0
    retry_budget_burst: int = 10

    def validate(self):
        if self.heartbeat_ttl_s <= 0:
            raise ValueError(f"heartbeat_ttl_s must be > 0, got "
                             f"{self.heartbeat_ttl_s}")
        if self.poll_interval_s <= 0:
            raise ValueError(f"poll_interval_s must be > 0, got "
                             f"{self.poll_interval_s}")
        if self.virtual_nodes < 1:
            raise ValueError(f"virtual_nodes must be >= 1, got "
                             f"{self.virtual_nodes}")
        if self.max_resubmits < 0:
            raise ValueError(f"max_resubmits must be >= 0, got "
                             f"{self.max_resubmits}")
        if not (0.0 < self.health_alpha <= 1.0):
            raise ValueError(f"health_alpha must be in (0, 1], got "
                             f"{self.health_alpha}")
        if self.eject_zscore <= 0:
            raise ValueError(f"eject_zscore must be > 0, got "
                             f"{self.eject_zscore}")
        if self.eject_min_samples < 1:
            raise ValueError(f"eject_min_samples must be >= 1, got "
                             f"{self.eject_min_samples}")
        if not (0.0 <= self.eject_max_fraction <= 1.0):
            raise ValueError(f"eject_max_fraction must be in [0, 1], "
                             f"got {self.eject_max_fraction}")
        if self.canary_interval_s <= 0 or self.canary_timeout_s <= 0:
            raise ValueError("canary_interval_s and canary_timeout_s "
                             "must be > 0")
        if self.readmit_canaries < 1:
            raise ValueError(f"readmit_canaries must be >= 1, got "
                             f"{self.readmit_canaries}")
        if not (0.0 <= self.hedge_percentile < 100.0):
            raise ValueError(f"hedge_percentile must be in [0, 100), "
                             f"got {self.hedge_percentile}")
        if self.hedge_min_samples < 1:
            raise ValueError(f"hedge_min_samples must be >= 1, got "
                             f"{self.hedge_min_samples}")
        if self.breaker_failures < 0 or self.breaker_window_s <= 0 \
                or self.breaker_cooldown_s <= 0:
            raise ValueError("breaker_failures must be >= 0 and "
                             "breaker_window_s/breaker_cooldown_s > 0")
        if self.retry_budget_per_s < 0 or self.retry_budget_burst < 1:
            raise ValueError("retry_budget_per_s must be >= 0 and "
                             "retry_budget_burst >= 1")
        return self


def _as_transport_error(exc):
    """A candidate list is a snapshot: a dispatch thread can race a
    concurrent `_mark_dead` + `rpc.forget_worker` and dial a replica
    the registry no longer knows.  That 'unknown worker' ValueError IS
    a dead-replica signal — coerce it to the ConnectionError failover
    path instead of failing the request with an app-level error."""
    if isinstance(exc, ValueError) and "unknown worker" in str(exc):
        return ConnectionError(str(exc))
    return exc


def _hash64(data):
    if isinstance(data, str):
        data = data.encode()
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(),
                          "big")


class HashRing:
    """Consistent-hash ring with virtual nodes.  `lookup(key)` returns
    the owner; `successors(key)` yields every member once, owner first,
    in ring order — the router's spill/failover candidate order."""

    def __init__(self, virtual_nodes=64):
        self.vnodes = virtual_nodes
        self._points: list[tuple[int, str]] = []
        self._members: set[str] = set()

    def rebuild(self, members):
        members = set(members)
        if members == self._members:
            return False
        pts = []
        for name in members:
            for v in range(self.vnodes):
                pts.append((_hash64(f"{name}#{v}"), name))
        pts.sort()
        self._points = pts
        self._members = members
        return True

    @property
    def members(self):
        return set(self._members)

    def lookup(self, key):
        nxt = next(self.successors(key), None)
        return nxt

    def successors(self, key):
        """Distinct members starting at the key's owner, ring order."""
        if not self._points:
            return
        h = _hash64(key)
        idx = bisect.bisect_left(self._points, (h, ""))
        seen = set()
        n = len(self._points)
        for i in range(n):
            _, name = self._points[(idx + i) % n]
            if name not in seen:
                seen.add(name)
                yield name


class _ReplicaView:
    __slots__ = ("name", "ip", "port", "state", "gen", "load",
                 "load_ts", "tp", "role", "adapters")

    def __init__(self, info):
        self.name = info["name"]
        self.ip = info.get("ip", "127.0.0.1")
        self.port = int(info.get("port", 0))
        self.state = info.get("state", "warming")
        self.gen = int(info.get("gen", 0))
        self.load = info.get("load") or {}
        self.load_ts = float(info.get("load_ts", 0.0))
        self.tp = int(info.get("tp", 1))
        self.role = info.get("role", "mixed")
        self.adapters = frozenset(info.get("adapters") or ())


class _RoutedRequest:
    __slots__ = ("rid", "prompt", "max_new_tokens", "sampling",
                 "eos_token_id", "deadline", "session_key", "future",
                 "submit_t", "attempts", "resubmits", "adapter_id",
                 "trace")

    def __init__(self, rid, prompt, max_new_tokens, sampling,
                 eos_token_id, deadline, session_key, adapter_id=None):
        self.rid = rid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.sampling = sampling
        self.eos_token_id = eos_token_id
        self.deadline = deadline            # absolute monotonic or None
        self.session_key = session_key
        self.adapter_id = adapter_id        # multi-tenant LoRA affinity
        self.future = Future()
        self.submit_t = time.monotonic()
        self.attempts = 0                   # dispatch rounds
        self.resubmits = 0                  # re-sends after the first
        self.trace = None                   # root Span (tracing armed)


class _ReplicaHealth:
    """EWMA latency + error-rate score of one replica, fed from every
    dispatch.  `score()` is the health scalar the guardian compares
    across the fleet: EWMA route latency (ms) inflated by the EWMA
    transport-error rate — a replica that is slow OR flaky scores high.
    Backpressure (`QueueFullError`) and lifecycle bounces are neutral:
    a full queue is load, not sickness."""

    __slots__ = ("ewma_ms", "err_ewma", "samples")

    def __init__(self):
        self.ewma_ms = None
        self.err_ewma = 0.0
        self.samples = 0

    def observe(self, alpha, latency_ms, error):
        self.samples += 1
        if self.ewma_ms is None:
            self.ewma_ms = float(latency_ms)
        else:
            self.ewma_ms += alpha * (float(latency_ms) - self.ewma_ms)
        self.err_ewma += alpha * ((1.0 if error else 0.0)
                                  - self.err_ewma)

    def score(self):
        if self.ewma_ms is None:
            return None
        return self.ewma_ms * (1.0 + 4.0 * self.err_ewma)


class _Breaker:
    """Per-replica circuit breaker: closed -> open -> half-open.
    `breaker_failures` transport failures inside `breaker_window_s`
    open it (calls skipped without paying an rpc); after
    `breaker_cooldown_s` ONE trial call is admitted (half-open); a
    trial success recloses, a trial failure re-opens."""

    __slots__ = ("state", "fail_times", "open_until")

    def __init__(self):
        self.state = "closed"
        self.fail_times: list[float] = []
        self.open_until = 0.0

    def allow(self, now, cooldown_s):
        if self.state == "closed":
            return True
        if self.state == "open" and now >= self.open_until:
            self.state = "half"          # admit exactly one trial
            return True
        return False                     # open (cooling) or half (trial
        #                                  already in flight)

    def on_success(self):
        self.state = "closed"
        self.fail_times.clear()

    def on_failure(self, now, threshold, window_s, cooldown_s):
        """Record one transport failure; returns True on a transition
        into `open` (the caller counts those)."""
        if self.state == "half":
            self.state = "open"
            self.open_until = now + cooldown_s
            return True
        self.fail_times.append(now)
        self.fail_times = [t for t in self.fail_times
                           if now - t <= window_s]
        if self.state == "closed" and len(self.fail_times) >= threshold:
            self.state = "open"
            self.open_until = now + cooldown_s
            return True
        return False


class _RetryBudget:
    """Fleet-wide token bucket spent by resubmissions.  A replica
    outage that triggers mass failover drains the bucket; once empty,
    further resubmissions fail loudly instead of amplifying the outage
    with a retry storm (the classic metastable-failure feedback
    loop)."""

    __slots__ = ("rate", "burst", "tokens", "stamp", "_lock")

    def __init__(self, rate, burst):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.stamp = time.monotonic()
        self._lock = threading.Lock()

    def take(self):
        with self._lock:
            now = time.monotonic()
            self.tokens = min(self.burst,
                              self.tokens + (now - self.stamp)
                              * self.rate)
            self.stamp = now
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                return True
            return False


class ServingRouter:
    """`ServingRouter(store).start()`; then `submit()` / `generate()`
    exactly like a local `Engine` — the fleet is one logical engine.
    `close()` stops the watcher and fails outstanding futures."""

    def __init__(self, store, config: RouterConfig | None = None,
                 name="router"):
        from ..distributed.store import TCPElasticStore
        self.store = store
        self.cfg = (config or RouterConfig()).validate()
        self.name = name
        self.membership = TCPElasticStore(store,
                                          ttl=self.cfg.heartbeat_ttl_s)
        self.ring = HashRing(self.cfg.virtual_nodes)
        self._replicas: dict[str, _ReplicaView] = {}
        self._dead_gen: dict[str, int] = {}   # sticky-dead by generation
        self._lock = threading.RLock()
        self._inflight: dict[str, _RoutedRequest] = {}
        self._running = False
        self._watcher = None
        self._rid_prefix = f"{name}-{_hash64(repr(time.time())) % 10**6}"
        self._ids = itertools.count()
        # ---- gray-failure guardian state (all knobs default off) ----
        cfg = self.cfg
        self._guardian = bool(cfg.health_ejection
                              or cfg.hedge_percentile > 0
                              or cfg.breaker_failures > 0)
        self._health: dict[str, _ReplicaHealth] = {}
        self._ejected: dict[str, dict] = {}   # name -> canary state
        self._breakers: dict[str, _Breaker] = {}
        self._lat_ring: deque[float] = deque(maxlen=512)
        self._shed_times: deque[float] = deque(maxlen=256)
        self._retry_budget = (_RetryBudget(cfg.retry_budget_per_s,
                                           cfg.retry_budget_burst)
                              if cfg.retry_budget_per_s > 0 else None)

    # ---------------- lifecycle ----------------
    def start(self):
        with self._lock:
            if self._running:
                return self
            stats.reset_router_stats()
            stats.declare_trace_stats()
            if tracing.enabled():
                tracing.set_process_name(self.name, default=True)
            self._running = True
        self._poll_membership()               # synchronous first view
        self._watcher = threading.Thread(
            target=self._watch_loop, name="paddle-tpu-serving-router",
            daemon=True)
        self._watcher.start()
        return self

    def close(self):
        with self._lock:
            if not self._running:
                return
            self._running = False
            pending = list(self._inflight.values())
            self._inflight.clear()
        for req in pending:
            if not req.future.done():
                try:
                    req.future.set_exception(EngineShutdownError(
                        "serving router closed"))
                except Exception:
                    pass
            if req.trace is not None:
                req.trace.end(status="shutdown")
                tracing.decide(
                    req.trace.ctx.trace_id, status="shutdown",
                    latency_ms=(time.monotonic() - req.submit_t) * 1e3)
        if tracing.enabled():
            tracing.spool_now()
        w = self._watcher
        if w is not None:
            w.join(5.0)
            self._watcher = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # ---------------- membership ----------------
    def _watch_loop(self):
        while self._running:
            try:
                self._poll_membership()
            except Exception:
                # a flaky store read must not kill routing; the next
                # poll retries and the sticky-dead set is unchanged
                pass
            try:
                self._guardian_tick()
            except Exception:
                # guardian bookkeeping must never kill membership
                # polling either
                pass
            time.sleep(self.cfg.poll_interval_s)

    def _poll_membership(self):
        alive, expired = self.membership._scan()
        alive, expired = set(alive), set(expired)
        infos = {}
        for key, val in self.store.list_prefix(INFO_PREFIX).items():
            try:
                view = _ReplicaView(json.loads(val.decode()))
            except (ValueError, KeyError):
                continue
            infos[view.name] = view
        with self._lock:
            ready = set()
            for name, view in infos.items():
                dead_gen = self._dead_gen.get(name)
                if dead_gen is not None and view.gen <= dead_gen:
                    continue                      # sticky dead, no rejoin
                if dead_gen is not None and view.gen > dead_gen:
                    del self._dead_gen[name]      # explicit rejoin
                if name in expired or (name not in alive
                                       and name not in infos):
                    self._mark_dead_locked(name, view.gen)
                    continue
                if name not in alive:
                    # info published but no lease yet (registering) —
                    # not ready, not dead
                    continue
                if view.state == "ready":
                    ready.add(name)
            self._replicas = infos
            was = self.ring.members
            self.ring.rebuild(ready)
            for name in ready - was:
                from ..distributed import rpc
                rpc.connect_worker(name, infos[name].ip,
                                   infos[name].port)
            stats.set_value("router.replicas_alive", len(ready))
        # reap expired leases so a paused-then-resumed heartbeater must
        # explicitly re-register (anti-flap; see module docstring)
        if expired:
            self.membership.reap()

    def _mark_dead_locked(self, name, gen):
        if self._dead_gen.get(name, -1) < gen:
            self._dead_gen[name] = gen
        if name in self.ring.members:
            self.ring.rebuild(self.ring.members - {name})
            stats.incr("router.replicas_lost")
        # a dead replica's guardian state dies with it: its eventual
        # rejoin (bumped generation) starts with a clean slate
        self._ejected.pop(name, None)
        self._health.pop(name, None)
        self._breakers.pop(name, None)
        from ..distributed import rpc
        rpc.forget_worker(name)

    def _mark_dead(self, name):
        with self._lock:
            view = self._replicas.get(name)
            self._mark_dead_locked(name, view.gen if view else 0)
            stats.set_value("router.replicas_alive",
                            len(self.ring.members))

    def replicas(self):
        """Current membership snapshot: {name: state} (ready members are
        routable; draining/warming/dead ones are not)."""
        with self._lock:
            out = {}
            for name, view in self._replicas.items():
                if name in self._dead_gen and \
                        view.gen <= self._dead_gen[name]:
                    out[name] = "dead"
                else:
                    out[name] = view.state
            return out

    # ---------------- client API ----------------
    def submit(self, prompt_ids, max_new_tokens=None, sampling=None,
               eos_token_id=None, deadline_s=None, session_id=None,
               adapter_id=None):
        """Route one request; returns a `Future[RequestOutput]`.  The
        Future resolves exactly once — with the output, or with the
        loudest-applicable error (`QueueFullError` when the fleet sheds,
        `DeadlineExceededError`, `NoReplicaError`, ...)."""
        if not self._running:
            raise EngineShutdownError("router is not running")
        prompt = np.asarray(
            prompt_ids._data_ if hasattr(prompt_ids, "_data_")
            else prompt_ids).astype(np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        sampling = (sampling or SamplingParams()).validate()
        deadline = (time.monotonic() + deadline_s) \
            if deadline_s is not None else None
        key = str(session_id) if session_id is not None \
            else prompt[:16].tobytes()
        rid = f"{self._rid_prefix}-{next(self._ids)}"
        req = _RoutedRequest(
            rid, prompt, max_new_tokens, sampling, eos_token_id,
            deadline, key,
            adapter_id=str(adapter_id) if adapter_id is not None
            else None)
        if tracing.enabled():
            # the router owns the ROOT span of a routed trace: it ends
            # it in _complete/_fail and makes the one tail-sampling
            # decision for the whole request (engine-side spans of a
            # routed request are always children, never roots)
            req.trace = tracing.start_span(
                "router.request", rid=rid,
                prompt_tokens=int(prompt.size))
        with self._lock:
            self._inflight[rid] = req
        threading.Thread(target=self._dispatch, args=(req,),
                         name=f"route-{rid}", daemon=True).start()
        return req.future

    def generate(self, prompt_ids, max_new_tokens=None, sampling=None,
                 eos_token_id=None, deadline_s=None, session_id=None,
                 timeout=None, adapter_id=None):
        fut = self.submit(prompt_ids, max_new_tokens=max_new_tokens,
                          sampling=sampling, eos_token_id=eos_token_id,
                          deadline_s=deadline_s, session_id=session_id,
                          adapter_id=adapter_id)
        return fut.result(timeout or self.cfg.request_timeout_s)

    def stats(self):
        return stats.serving_stats()

    # ---------------- dispatch ----------------
    def _remaining(self, req):
        if req.deadline is None:
            return None
        return req.deadline - time.monotonic()

    def _candidates(self, req):
        """Ready replicas in affinity order, cheap-shed filtered: a
        replica whose fresh gossip already says its queue is full is
        skipped without paying an rpc.  Disaggregation reorders the
        candidates by role preference (prefill > mixed > decode, ring
        order within a class) — new prompts land on prefill replicas,
        but a decode replica still serves as the last resort, so a
        fleet mid-role-flip never strands a request.

        Adapter affinity is the OUTERMOST (final, stable) sort: a
        request carrying an `adapter_id` prefers replicas whose gossip
        advertises that adapter as hot-loaded, so a warm pool slot is
        reused instead of paying a hot-load; a cold replica is still a
        valid fallback (it hot-loads on admission), so no adapter ever
        strands a request."""
        with self._lock:
            order = list(self.ring.successors(req.session_key))
            views = dict(self._replicas)
            blocked = set()
            if self._guardian:
                if self.cfg.health_ejection and self._ejected:
                    blocked |= set(self._ejected)
                if self.cfg.breaker_failures > 0 and self._breakers:
                    mono = time.monotonic()
                    for n in order:
                        br = self._breakers.get(n)
                        if br is not None and n not in blocked and \
                                not br.allow(
                                    mono, self.cfg.breaker_cooldown_s):
                            blocked.add(n)
        now = time.time()
        out, skipped_full = [], 0
        for name in order:
            view = views.get(name)
            if view is None:
                continue
            if name in blocked:
                # ejected by the health guardian or breaker-open:
                # reversible, generation-preserving skip — the replica
                # stays in the ring and rejoins the order on
                # readmission / breaker reclose
                continue
            load = view.load
            fresh = (now - view.load_ts) <= \
                max(2 * self.cfg.heartbeat_ttl_s, 1.0)
            if fresh and load and \
                    load.get("queue_depth", 0) >= load.get(
                        "max_queue", float("inf")):
                skipped_full += 1
                continue
            out.append(name)
        if self.cfg.disaggregation:
            rank = {"prefill": 0, "mixed": 1, "decode": 2}
            out.sort(key=lambda n: rank.get(
                getattr(views.get(n), "role", "mixed"), 1))
        if req.adapter_id is not None:
            out.sort(key=lambda n: 0 if req.adapter_id in getattr(
                views.get(n), "adapters", ()) else 1)
        return out, skipped_full, sorted(blocked)

    def _fail(self, req, exc):
        with self._lock:
            self._inflight.pop(req.rid, None)
        if not req.future.done():
            try:
                req.future.set_exception(exc)
            except Exception:
                pass
        if req.trace is not None:
            status = type(exc).__name__
            req.trace.end(status=status, error=str(exc)[:200])
            tracing.decide(
                req.trace.ctx.trace_id, status=status,
                latency_ms=(time.monotonic() - req.submit_t) * 1e3)

    def _complete(self, req, payload, replica):
        """Deliver one payload to the request future.  Returns True iff
        THIS call won the exactly-once delivery (the caller marks its
        attempt span as the trace's single winner on True)."""
        out = RequestOutput(
            request_id=req.rid, prompt_ids=req.prompt,
            output_ids=np.asarray(payload["output_ids"], np.int32),
            finish_reason=payload["finish_reason"],
            ttft_ms=payload.get("ttft_ms"),
            latency_ms=(time.monotonic() - req.submit_t) * 1e3,
            decoded_by=payload.get("decoded_by") or replica)
        with self._lock:
            self._inflight.pop(req.rid, None)
            view = self._replicas.get(replica)
        if req.future.done():            # at-most-once delivery
            return False
        try:
            req.future.set_result(out)
        except Exception:
            return False
        stats.route_observe(replica, view.role if view else "mixed")
        stats.observe("router.route_latency_ms", out.latency_ms)
        if req.resubmits:
            stats.incr("router.requests_recovered")
        if req.trace is not None:
            req.trace.end(status="ok",
                          finish_reason=out.finish_reason,
                          replica=replica,
                          decoded_by=out.decoded_by,
                          resubmits=req.resubmits)
            tracing.decide(req.trace.ctx.trace_id, status="ok",
                           latency_ms=out.latency_ms)
        return True

    def _dispatch(self, req):
        cfg = self.cfg
        patience = time.monotonic() + cfg.no_replica_patience_s
        while True:
            if req.future.done():
                return
            if not self._running:
                self._fail(req, EngineShutdownError(
                    "serving router closed"))
                return
            remaining = self._remaining(req)
            if remaining is not None and remaining <= 0:
                self._fail(req, DeadlineExceededError(
                    f"request {req.rid} expired after "
                    f"{time.monotonic() - req.submit_t:.3f}s at the "
                    "router"))
                return
            candidates, skipped_full, blocked = self._candidates(req)
            if req.trace is not None:
                req.trace.event("candidates", order=list(candidates),
                                skipped_full=skipped_full,
                                blocked=blocked)
            if not candidates:
                if skipped_full:
                    if req.trace is not None:
                        req.trace.event("shed",
                                        skipped_full=skipped_full)
                    self._shed(req)
                    return
                # no ready replica AT ALL: wait for the fleet (warming
                # up or mid-failover) within the patience window
                if time.monotonic() >= patience:
                    self._fail(req, NoReplicaError(
                        f"no ready replica for request {req.rid} "
                        f"within {cfg.no_replica_patience_s:.1f}s "
                        f"(membership: {self.replicas()})"))
                    return
                time.sleep(cfg.poll_interval_s)
                continue
            all_full = True
            for i, name in enumerate(candidates):
                remaining = self._remaining(req)
                if remaining is not None and remaining <= 0:
                    self._fail(req, DeadlineExceededError(
                        f"request {req.rid} expired mid-dispatch"))
                    return
                budget = cfg.rpc_timeout_s if remaining is None \
                    else min(cfg.rpc_timeout_s, remaining)
                # hedging applies to the PRIMARY attempt only (first
                # candidate, first round) — hedging a spill chain would
                # amplify load exactly when the fleet is struggling
                hedge_peer = (candidates[i + 1]
                              if cfg.hedge_percentile > 0 and i == 0
                              and req.attempts == 0
                              and len(candidates) > 1 else None)
                err = self._try_replica(req, name, budget,
                                        hedge_peer=hedge_peer)
                if err is None:
                    return                       # delivered
                if isinstance(err, QueueFullError):
                    if req.trace is not None:
                        req.trace.event("spill", replica=name)
                    continue                     # spill to successor
                if isinstance(err, EngineShutdownError):
                    # draining/stopped: resubmit elsewhere — counted
                    # against the same budget as death-failovers so a
                    # replica stuck bouncing every submit can never pin
                    # a request in the dispatch loop forever
                    if not self._retry_allowed(req, err):
                        return
                    stats.incr("router.resubmissions")
                    req.resubmits += 1
                    req.attempts += 1
                    if req.trace is not None:
                        req.trace.event("resubmit", replica=name,
                                        reason="drain_bounce")
                    all_full = False
                    if req.attempts > cfg.max_resubmits:
                        self._fail(req, ServingError(
                            f"request {req.rid}: exhausted "
                            f"{cfg.max_resubmits} resubmits (last: "
                            f"replica {name} refused: {err})"))
                        return
                    continue
                if isinstance(err, (ConnectionError, OSError)):
                    self._mark_dead(name)
                    stats.incr("router.failovers")
                    if req.trace is not None:
                        req.trace.event("failover", replica=name,
                                        reason="transport")
                    if not self._retry_allowed(req, err):
                        return
                    stats.incr("router.resubmissions")
                    req.resubmits += 1
                    req.attempts += 1
                    all_full = False
                    if req.attempts > cfg.max_resubmits:
                        self._fail(req, ServingError(
                            f"request {req.rid}: exhausted "
                            f"{cfg.max_resubmits} resubmits across "
                            f"replica failures (last: {err})"))
                        return
                    continue
                if isinstance(err, TimeoutError):
                    # ambiguous: the replica may still be computing.
                    # Dead (lease expired) -> safe to resubmit under the
                    # idempotent rid; alive -> fail LOUDLY, never hang.
                    if name in self.membership.alive_nodes():
                        self._fail(req, DeadlineExceededError(
                            f"request {req.rid}: rpc to live replica "
                            f"{name} timed out after {budget:.1f}s; "
                            "not retrying a possibly-executing call "
                            "on a healthy replica"))
                        return
                    self._mark_dead(name)
                    stats.incr("router.failovers")
                    if req.trace is not None:
                        req.trace.event("failover", replica=name,
                                        reason="timeout_dead")
                    if not self._retry_allowed(req, err):
                        return
                    stats.incr("router.resubmissions")
                    req.resubmits += 1
                    req.attempts += 1
                    all_full = False
                    if req.attempts > cfg.max_resubmits:
                        self._fail(req, ServingError(
                            f"request {req.rid}: exhausted "
                            f"{cfg.max_resubmits} resubmits (last: "
                            f"rpc timeout on dead replica {name})"))
                        return
                    continue
                self._fail(req, err)             # app-level error
                return
            if all_full:
                if req.trace is not None:
                    req.trace.event("shed", all_full=True)
                self._shed(req)
                return
            # unsuccessful round that wasn't a shed: give the watcher
            # one poll to settle the ring before re-reading membership
            time.sleep(cfg.poll_interval_s)

    def _shed(self, req):
        stats.incr("router.requests_shed")
        hint = self._retry_after_hint()
        self._fail(req, QueueFullError(
            f"request {req.rid}: every ready replica is at capacity; "
            f"retry after {hint:.1f}s",
            retry_after_s=hint))

    def _retry_after_hint(self):
        """The Retry-After hint, scaled by current shed pressure: the
        busier the last 5 s of sheds, the longer clients are told to
        back off — fleet-side pushback that spreads the retry wave
        instead of inviting it back all at once.  The FIRST shed in a
        quiet window returns exactly `retry_after_s`."""
        now = time.monotonic()
        with self._lock:
            self._shed_times.append(now)
            recent = sum(1 for t in self._shed_times
                         if now - t <= 5.0)
        return self.cfg.retry_after_s * min(
            8.0, 1.0 + 0.25 * (recent - 1))

    def _pick_decode_target(self, exclude):
        """The migration target for a request about to land on
        `exclude`: the least-loaded ready decode-role replica, or None
        when the fleet has none (the prefill replica then decodes
        locally — disaggregation degrades to mixed, never to a
        failure)."""
        with self._lock:
            ready = self.ring.members
            views = [v for n, v in self._replicas.items()
                     if n in ready and n != exclude
                     and v.role == "decode"]
        if not views:
            return None
        v = min(views, key=lambda v: (
            v.load.get("queue_depth", 0) + v.load.get("active_slots", 0),
            v.name))
        return {"name": v.name, "ip": v.ip, "port": v.port}

    def _submit_args(self, req, name):
        """The `_remote_submit` args tuple for one attempt against
        `name` (the handoff target is picked per target replica, so a
        hedge recomputes it)."""
        remaining = self._remaining(req)
        sampling = {"temperature": req.sampling.temperature,
                    "top_k": req.sampling.top_k,
                    "top_p": req.sampling.top_p,
                    "repetition_penalty":
                        req.sampling.repetition_penalty,
                    "seed": req.sampling.seed}
        migratable = req.max_new_tokens is None or \
            req.max_new_tokens >= self.cfg.migrate_min_new_tokens
        handoff = self._pick_decode_target(name) \
            if self.cfg.disaggregation and migratable else None
        return (name, req.rid, req.prompt, req.max_new_tokens,
                sampling, req.eos_token_id, remaining, handoff,
                req.adapter_id)

    def _try_replica(self, req, name, budget, hedge_peer=None):
        """One delivery attempt.  Returns None on success (future
        completed) or the exception describing why this replica did not
        serve it.  With hedging armed and warmed up, the attempt runs
        through `_try_replica_hedged` instead."""
        from ..distributed import rpc
        from .fleet import _remote_submit
        if hedge_peer is not None:
            threshold_s = self._hedge_threshold_s()
            if threshold_s is not None and threshold_s < budget:
                return self._try_replica_hedged(
                    req, name, hedge_peer, budget, threshold_s)
        span = None
        if req.trace is not None:
            span = tracing.start_span(
                "router.attempt", parent=req.trace,
                replica=name, attempt=req.attempts)
        t0 = time.monotonic()
        try:
            # bind the attempt span so rpc_sync attaches its wire form
            # to the call envelope — the replica's engine spans parent
            # under THIS attempt, not the root
            with tracing.bind(span):
                payload = rpc.rpc_sync(
                    name, _remote_submit,
                    args=self._submit_args(req, name),
                    timeout=budget + 1.0)
        except Exception as e:               # noqa: BLE001
            e = _as_transport_error(e)
            self._observe_attempt(name, time.monotonic() - t0, e)
            if span is not None:
                span.end(status=type(e).__name__)
            return e
        self._observe_attempt(name, time.monotonic() - t0, None)
        won = self._complete(req, payload, name)
        if span is not None:
            span.end(status="ok", winner=won)
        return None

    # ---------------- gray-failure guardian ----------------
    def _observe_attempt(self, name, dt_s, exc):
        """Health/breaker bookkeeping for one finished attempt.  Fed
        from EVERY dispatch (successes included), which is what lets
        the guardian see a replica that is slow-but-alive.  Transport
        failures (connection loss, timeout) count as errors;
        backpressure and lifecycle errors (`QueueFullError`,
        `EngineShutdownError`) are neutral — a shedding replica is
        busy, not sick.  A hedged loser's `RequestCancelledError` is a
        LATENCY observation, not an error: the attempt was at least
        `dt_s` slow before the hedge beat it and we gave up — without
        this, hedging would mask exactly the slow replica that
        health-scored ejection exists to catch (every slow primary
        gets hedged away and cancelled, so it never reports a slow
        success)."""
        if not self._guardian:
            return
        transport = exc is not None and isinstance(
            exc, (OSError, TimeoutError))
        cancelled = isinstance(exc, RequestCancelledError)
        success = exc is None
        with self._lock:
            if self.cfg.breaker_failures > 0:
                br = self._breakers.setdefault(name, _Breaker())
                if transport:
                    if br.on_failure(time.monotonic(),
                                     self.cfg.breaker_failures,
                                     self.cfg.breaker_window_s,
                                     self.cfg.breaker_cooldown_s):
                        stats.incr("router.breaker_open")
                elif success:
                    br.on_success()
            if success or transport or cancelled:
                h = self._health.setdefault(name, _ReplicaHealth())
                h.observe(self.cfg.health_alpha, dt_s * 1e3,
                          error=transport)
            if success:
                self._lat_ring.append(dt_s * 1e3)

    def _attempt_observer(self, name, t0):
        """`add_done_callback` adapter for async (hedged) attempts."""
        def _cb(fut):
            try:
                exc = fut.exception()
            except Exception as e:           # noqa: BLE001
                exc = e
            self._observe_attempt(name, time.monotonic() - t0, exc)
        return _cb

    def _hedge_threshold_s(self):
        """p{hedge_percentile} of recent route latencies, or None until
        `hedge_min_samples` successes have been seen (no hedging on a
        cold or idle fleet — a made-up threshold would hedge every
        request)."""
        if self.cfg.hedge_percentile <= 0:
            return None
        with self._lock:
            if len(self._lat_ring) < self.cfg.hedge_min_samples:
                return None
            arr = np.fromiter(self._lat_ring, dtype=np.float64)
        return float(np.percentile(arr,
                                   self.cfg.hedge_percentile)) / 1e3

    def _try_replica_hedged(self, req, name, peer, budget,
                            threshold_s):
        """Hedged primary attempt: fire `name`, wait the latency
        percentile, and if still unanswered fire ONE hedge to `peer`
        under the SAME rid.  The replica-side dedup cache makes the
        pair at-most-once on any single replica, and `_complete`'s
        done-check makes delivery exactly-once across both.  First
        answer wins; the loser is cancelled (`Engine.cancel` via
        `_remote_cancel`) so its slot/pages/adapter rows come back
        instead of decoding a stream nobody will read."""
        from ..distributed import rpc
        from .fleet import _remote_cancel, _remote_submit
        spans = {}                           # future -> attempt Span
        span1 = None
        if req.trace is not None:
            span1 = tracing.start_span(
                "router.attempt", parent=req.trace,
                replica=name, attempt=req.attempts, hedged="primary")
        t0 = time.monotonic()
        # rpc_async captures the caller's thread-bound context at CALL
        # time, so each attempt's wire context is its own span — both
        # hedge arms stay under the SAME trace, each as its own child
        with tracing.bind(span1):
            fut1 = rpc.rpc_async(name, _remote_submit,
                                 args=self._submit_args(req, name),
                                 timeout=budget + 1.0)
        fut1.add_done_callback(self._attempt_observer(name, t0))
        spans[fut1] = span1
        done, _ = _futures_wait([fut1], timeout=threshold_s)
        futs = {fut1: name}
        hedge_fut = None
        if not done:
            left = budget - (time.monotonic() - t0)
            if left > 0:
                stats.incr("router.hedges")
                hedge_span = None
                if req.trace is not None:
                    req.trace.event("hedge", primary=name, peer=peer,
                                    threshold_ms=round(
                                        threshold_s * 1e3, 3))
                    hedge_span = tracing.start_span(
                        "router.attempt", parent=req.trace,
                        replica=peer, attempt=req.attempts,
                        hedged="hedge")
                t1 = time.monotonic()
                with tracing.bind(hedge_span):
                    hedge_fut = rpc.rpc_async(
                        peer, _remote_submit,
                        args=self._submit_args(req, peer),
                        timeout=left + 1.0)
                hedge_fut.add_done_callback(
                    self._attempt_observer(peer, t1))
                futs[hedge_fut] = peer
                spans[hedge_fut] = hedge_span
        pending = set(futs)
        primary_err = None
        other_err = None
        while pending:
            # each attempt carries its own rpc timeout, so this wait
            # always terminates; the outer timeout is a backstop
            done, pending = _futures_wait(
                pending, timeout=budget + 5.0,
                return_when=FIRST_COMPLETED)
            if not done:
                break
            for fut in done:
                who = futs[fut]
                try:
                    exc = fut.exception()
                except Exception as e:       # noqa: BLE001
                    exc = e
                exc = _as_transport_error(exc) if exc is not None \
                    else None
                if exc is None:
                    won = self._complete(req, fut.result(), who)
                    if spans.get(fut) is not None:
                        spans[fut].end(status="ok", winner=won)
                    if fut is hedge_fut:
                        stats.incr("router.hedge_wins")
                    for loser, loser_name in futs.items():
                        if loser is not fut and not loser.done():
                            try:             # fire-and-forget cancel
                                rpc.rpc_async(
                                    loser_name, _remote_cancel,
                                    args=(loser_name, req.rid),
                                    timeout=self.cfg.rpc_timeout_s)
                            except Exception:
                                pass
                            if spans.get(loser) is not None:
                                # the explicitly-cancelled loser: one
                                # winning span + this, never two wins
                                spans[loser].end(status="cancelled",
                                                 cancelled=True)
                    for f2, sp2 in spans.items():
                        # a loser that FINISHED before the winner was
                        # processed (same done batch): not cancelled,
                        # just beaten — end() is idempotent, so spans
                        # already closed above keep their status
                        if sp2 is not None and f2 is not fut:
                            sp2.end(status="superseded")
                    return None
                if spans.get(fut) is not None:
                    spans[fut].end(status=type(exc).__name__)
                if fut is fut1:
                    primary_err = exc
                else:
                    other_err = exc
        # both attempts failed (or the primary failed before a hedge
        # fired): report the primary's error so the dispatch loop's
        # spill/failover semantics match the unhedged path
        for sp in spans.values():
            if sp is not None:               # idempotent for ended ones
                sp.end(status="unresolved")
        if primary_err is not None:
            return primary_err
        if other_err is not None:
            return other_err
        return TimeoutError(
            f"hedged attempt pair for {req.rid} did not resolve "
            f"within {budget:.1f}s")

    def _retry_allowed(self, req, err):
        """Spend one fleet-wide retry-budget token for a resubmission;
        an empty bucket fails the request loudly (no retry storm).
        Unlimited when the budget knob is off."""
        if self._retry_budget is None or self._retry_budget.take():
            return True
        stats.incr("router.retry_budget_exhausted")
        self._fail(req, ServingError(
            f"request {req.rid}: fleet retry budget exhausted "
            f"({self.cfg.retry_budget_per_s:.1f}/s, burst "
            f"{self.cfg.retry_budget_burst}); not amplifying the "
            f"outage (last error: {err})"))
        return False

    def _healthy_median_locked(self, exclude=None):
        """Median health score of ready, non-ejected replicas (the
        canary's yardstick), or None when nothing has a score yet."""
        vals = []
        for n in self.ring.members:
            if n == exclude or n in self._ejected:
                continue
            h = self._health.get(n)
            s = h.score() if h is not None else None
            if s is not None:
                vals.append(s)
        return float(np.median(vals)) if vals else None

    def _guardian_tick(self):
        """One watcher-cadence pass of the health guardian: publish
        per-replica scores, eject robust-z outliers, and canary-probe
        ejected replicas toward readmission."""
        cfg = self.cfg
        if not cfg.health_ejection:
            return
        now = time.monotonic()
        probes = []
        with self._lock:
            ready = self.ring.members
            # scores -> gauge (ejected replicas keep publishing so the
            # recovery is visible on the dashboard)
            scored = {}
            for n in ready | set(self._ejected):
                h = self._health.get(n)
                s = h.score() if h is not None else None
                if s is not None:
                    scored[n] = s
                    stats.health_observe(n, s)
            # robust-z outlier ejection over warmed-up, still-in
            # candidates
            eligible = {
                n: s for n, s in scored.items()
                if n in ready and n not in self._ejected
                and self._health[n].samples >= cfg.eject_min_samples}
            # never eject past the fraction cap, and never the last
            # standing replica
            allowed = min(max(0, len(ready) - 1),
                          int(cfg.eject_max_fraction * len(ready)))
            if len(eligible) >= 2 and len(self._ejected) < allowed:
                vals = sorted(eligible.values())
                med = float(np.median(vals))
                mad = float(np.median([abs(v - med) for v in vals]))
                # MAD floor: an all-identical fleet (MAD 0) must not
                # turn noise into ejections
                scale = max(1.4826 * mad, 0.05 * med, 1.0)
                for n, s in sorted(eligible.items(),
                                   key=lambda kv: -kv[1]):
                    if len(self._ejected) >= allowed:
                        break
                    if (s - med) / scale > cfg.eject_zscore:
                        self._ejected[n] = {
                            "since": now, "ok": 0,
                            "last_probe": 0.0, "probing": False}
                        stats.incr("router.ejections")
            # due canaries (fired outside the lock)
            for n, st in self._ejected.items():
                if st["probing"]:
                    continue
                if now - st["last_probe"] < cfg.canary_interval_s:
                    continue
                st["probing"] = True
                st["last_probe"] = now
                probes.append(n)
        for n in probes:
            threading.Thread(target=self._canary_probe, args=(n,),
                             name=f"canary-{n}", daemon=True).start()

    def _canary_probe(self, name):
        """One canary against an ejected replica: a real 1-token
        generate through the full engine path (a connect-level ping
        would pass right through an `engine_slow` gray failure).
        Healthy = completed within the canary budget AND at a latency
        comparable to the healthy fleet; `readmit_canaries` consecutive
        healthy probes readmit the replica with a fresh health slate."""
        from ..distributed import rpc
        from .fleet import _remote_canary
        cfg = self.cfg
        t0 = time.monotonic()
        ok, lat_ms = False, None
        try:
            res = rpc.rpc_sync(name, _remote_canary, args=(name,),
                               timeout=cfg.canary_timeout_s)
            lat_ms = float(res.get(
                "latency_ms", (time.monotonic() - t0) * 1e3))
            ok = True
        except Exception:                    # noqa: BLE001
            ok = False
        with self._lock:
            st = self._ejected.get(name)
            if st is None:
                return
            st["probing"] = False
            if ok:
                med = self._healthy_median_locked(exclude=name)
                # a 1-token canary is cheaper than a typical request,
                # so "comparable" is generous: 3x the healthy median
                # score (floor 100 ms); with no yardstick, finishing
                # inside the canary budget counts
                limit = max(3.0 * med, 100.0) if med is not None \
                    else cfg.canary_timeout_s * 1e3
                ok = lat_ms <= limit
            if not ok:
                st["ok"] = 0
                return
            st["ok"] += 1
            if st["ok"] >= cfg.readmit_canaries:
                del self._ejected[name]
                self._health[name] = _ReplicaHealth()
                stats.incr("router.readmissions")
