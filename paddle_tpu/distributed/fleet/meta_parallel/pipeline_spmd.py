"""Single-program SPMD pipeline schedule: collective-permute pipelining.

Reference capability: the 1F1B schedule (reference:
fleet/meta_parallel/pipeline_parallel.py:397-603) and the interleaved
virtual pipeline (`PipelineParallelWithInterleave`, :832) with batched p2p
activation exchange (pp_utils/p2p_communication.py:302).

TPU-native realization: instead of a host-driven issue order over per-stage
programs, the WHOLE schedule is one compiled XLA program — `shard_map` over
the `pp` mesh axis, `lax.scan` over schedule ticks, one cyclic
`lax.ppermute` per tick for the stage-boundary activation hand-off (the
compiled p2p).  Every pp rank executes the same instruction stream on its
own stage's weights, so stage compute for different micro-batches overlaps
by construction — the property the reference's 1F1B issue order exists to
create.

Schedule (circular wavefront): with S stages, C chunks per stage (virtual
pipeline), micro-batch m = g*S + mig (group g, offset mig < S) is processed
by rank r with chunk c at tick

    t = r + c*S + g*S*C + mig

This is a valid schedule: each (tick, rank) pair decodes to at most one
(micro, chunk) via u = t - r; the producer of every activation ran at tick
t-1 one rank earlier (cyclically — the S-1 → 0 wrap is exactly the chunk
c → c+1 hand-off), so ONE cyclic ppermute per tick moves every in-flight
activation where it needs to be.  C=1 degenerates to the classic GPipe
wavefront (T = M + S - 1 ticks); C>1 shrinks the pipeline bubble by 1/C at
the cost of one extra ring pass — the same trade as Megatron's interleaved
1F1B (reference pipeline_parallel.py:832).

Backward is `jax.vjp` through the scan: XLA transposes the ppermute into
the reverse hand-off, giving the backward pipeline for free.  Per-tick
rematerialisation (`jax.checkpoint` around the stage body) keeps live
activation memory at O(carry) per tick instead of O(full residuals) — the
memory property 1F1B exists to create.
"""
from __future__ import annotations

import numpy as np

from ....core import state as _state
from ....core.state import no_grad
from ....core.tensor import Tensor
from ....nn.layer import Layer
from ...placement import Replicate, Shard


class NotHomogeneous(ValueError):
    """Stage parts cannot be stacked (heterogeneous structure)."""


def _part_items(part):
    return [(item, fwd) for item, fwd, _shared in part]


def _item_params(item):
    return list(item.parameters()) if isinstance(item, Layer) else []


def _items_params(items):
    out = []
    for item, _fwd in items:
        out.extend(_item_params(item))
    return out


def _sig(items):
    """Stackability signature: per-item structural identity (layer class /
    callable name, forward-func name) plus per-param (shape, dtype).
    Structure matters, not just parameters — stages with identical params
    but different param-free ops (ReLU vs Tanh) must NOT stack, because
    every stacked part executes the template part's ops."""
    out = []
    for item, fwd in items:
        if isinstance(item, Layer):
            ident = type(item).__name__
        else:
            ident = getattr(item, "__qualname__", type(item).__name__)
        fident = (getattr(fwd, "__qualname__", repr(fwd))
                  if fwd is not None else None)
        psig = tuple((tuple(p._data_.shape), str(p._data_.dtype))
                     for p in _item_params(item))
        out.append((ident, fident, psig))
    return tuple(out)


def homogenize(parts):
    """Split execution-ordered parts into (pre_items, body_parts,
    post_items): strip leading items of the first part / trailing items of
    the last part until every part has the same param signature.  Raises
    NotHomogeneous when no such split exists (e.g. unequal blocks per
    stage)."""
    parts = [_part_items(p) for p in parts]
    if len(parts) < 2:
        raise NotHomogeneous("pipelining needs >= 2 parts")
    mid = [_sig(p) for p in parts[1:-1]]
    if mid and any(s != mid[0] for s in mid):
        raise NotHomogeneous(f"middle stage parts differ: {set(mid)}")
    target = mid[0] if mid else None

    first, last = list(parts[0]), list(parts[-1])
    pre, post = [], []
    if target is None:
        # two parts: strip first down until its sig matches last's remainder
        for cut in range(len(first) + 1):
            for rcut in range(len(last) + 1):
                body_f = first[cut:]
                body_l = last[:len(last) - rcut]
                if _sig(body_f) == _sig(body_l) and _sig(body_f):
                    return (first[:cut],
                            [body_f] + [body_l],
                            last[len(last) - rcut:])
        raise NotHomogeneous("no common stage structure between the 2 parts")
    while first and _sig(first) != target:
        pre.append(first.pop(0))
    while last and _sig(last) != target:
        post.insert(0, last.pop())
    if _sig(first) != target or _sig(last) != target or not target:
        raise NotHomogeneous(
            f"first/last stage parts irreducible to middle signature "
            f"(first={_sig(first)}, mid={target}, last={_sig(last)})")
    return pre, [first] + parts[1:-1] + [last], post


def _run_items(items, x):
    for item, fwd in items:
        x = fwd(item, x) if fwd is not None else item(x)
    return x


class SPMDPipeline:
    """Compiled pipeline runner for a homogeneous-body PipelineLayer.

    Owns the STACKED body parameters ([S, C, *shape], axis 0 sharded over
    pp) — these are the authoritative, optimizer-visible tensors; the
    original per-part layer params become a template through which the
    stage body is traced.  `write_back()` unstacks into the per-part params
    (for state_dict/checkpoint parity with the host-scheduled path).
    """

    def __init__(self, pipeline_layer, n_micro, remat=True):
        import jax

        self._pl = pipeline_layer
        self._mesh = pipeline_layer._mesh
        self._S = pipeline_layer._num_stages
        self._C = pipeline_layer._num_chunks
        self._n_micro = n_micro
        self._remat = remat
        self._loss_fn = pipeline_layer._loss_fn
        if self._mesh is None or "pp" not in self._mesh.dim_names \
                or self._mesh.get_dim_size("pp") != self._S:
            raise NotHomogeneous("mesh pp axis does not match num_stages")

        self._jitted = None
        self.pre, body_parts, self.post = homogenize(pipeline_layer._parts)
        # schedule depth: last micro's exit tick + 1.  The whole point:
        # M+S-1 wavefront ticks (C=1) instead of M*S serialized stage
        # applications — each tick runs ONE stage application on EVERY
        # pp rank concurrently.
        M, S, C = n_micro, self._S, self._C
        self.num_ticks = ((M - 1) // S) * S * C + (M - 1) % S + S * C
        # template = the first body part's layer objects; all stacked
        # chunks are traced through it
        self._template = body_parts[0]
        self._body_params = _items_params(self._template)
        if not self._body_params:
            raise NotHomogeneous("stage body has no parameters")
        self._body_parts = body_parts

        # unique pre/post params, re-committed onto the FULL mesh
        # (replicated over pp; TP placements kept) so the single compiled
        # program sees one device assignment
        seen, self._edge_params = set(), []
        for p in _items_params(self.pre) + _items_params(self.post):
            if id(p) not in seen:
                seen.add(id(p))
                self._edge_params.append(p)
        from ...placement import commit_param
        for p in self._edge_params:
            placements = [Replicate() for _ in self._mesh.dim_names]
            ann = getattr(p, "mp_placement", None)
            if ann is not None and ann[0] in self._mesh.dim_names:
                placements[self._mesh.dim_names.index(ann[0])] = ann[1]
            commit_param(p, self._mesh, placements)

        self._stack_params()

    # ---------------- stacked parameter management ----------------
    def _stacked_sharding(self, param):
        """NamedSharding for a stacked [S, C, *shape] param: axis 0 over
        pp, original TP placement shifted by the two leading axes."""
        from jax.sharding import NamedSharding, PartitionSpec
        entries = [None] * (param._data_.ndim + 2)
        entries[0] = "pp"
        ann = getattr(param, "mp_placement", None)
        if ann is not None and ann[0] in self._mesh.dim_names \
                and isinstance(ann[1], Shard):
            entries[2 + ann[1].dim] = ann[0]
        while entries and entries[-1] is None:
            entries.pop()
        return NamedSharding(self._mesh.jax_mesh, PartitionSpec(*entries))

    def _stack_params(self):
        """Build (or refresh) stacked Tensors from the per-part params
        (S*C parts, execution order part p = c*S + s → stacked[s, c]).
        Refreshing updates the EXISTING Tensor objects in place — an
        optimizer holds references to them, so replacing the objects
        would silently orphan its parameter list (checkpoint resume)."""
        import jax
        import jax.numpy as jnp

        S, C = self._S, self._C
        per_part = [_items_params(p) for p in self._body_parts]
        n = len(self._body_params)
        if any(len(pp) != n for pp in per_part):
            raise NotHomogeneous("inconsistent param counts across parts")
        fresh = not getattr(self, "stacked", None)
        if fresh:
            self.stacked = []
        for j in range(n):
            # [S, C, *shape]
            arr = jnp.stack([
                jnp.stack([np.asarray(per_part[c * S + s][j]._data_)
                           for c in range(C)])
                for s in range(S)])
            arr = jax.device_put(arr,
                                 self._stacked_sharding(self._body_params[j]))
            if fresh:
                t = Tensor(arr, stop_gradient=False)
                t.name = f"pipeline_stacked_{j}_" \
                         f"{getattr(self._body_params[j], 'name', j)}"
                self.stacked.append(t)
            else:
                self.stacked[j]._data_ = arr
        self._dirty = False

    def write_back(self):
        """Unstack the authoritative stacked params into the per-part layer
        params (state_dict/checkpoint path).  No-op while clean — run()
        marks the runner dirty, so eval loops don't re-unstack per batch."""
        import jax
        if not getattr(self, "_dirty", True):
            return
        S = self._S
        per_part = [_items_params(p) for p in self._body_parts]
        for j, t in enumerate(self.stacked):
            for p_idx, part in enumerate(self._body_parts):
                s, c = p_idx % S, p_idx // S
                target = per_part[p_idx][j]
                sl = t._data_[s, c]
                if getattr(target, "process_mesh", None) is not None:
                    from ...placement import named_sharding
                    sl = jax.device_put(sl, named_sharding(
                        target.process_mesh,
                        target.placements or
                        [Replicate()
                         for _ in target.process_mesh.dim_names],
                        sl.ndim))
                target._data_ = sl
        self._dirty = False

    def read_from_layers(self):
        """Re-stack from the per-part params (set_state_dict path)."""
        self._stack_params()

    def parameters(self):
        return list(self.stacked) + list(self._edge_params)

    # ---------------- the compiled schedule ----------------
    def _stage_apply(self, chunk_arrays, x_arr, rng_key):
        """One stage body application, traced through the template part."""
        saved = [(p, p._data_) for p in self._body_params]
        saved_rng = _state.STATE.rng_key, _state.STATE.rng_counter
        _state.STATE.rng_key = rng_key
        _state.STATE.rng_counter = 0
        try:
            for p, a in zip(self._body_params, chunk_arrays):
                p._data_ = a
            t = Tensor(x_arr, stop_gradient=True)
            out = _run_items(self._template, t)
            return out._data_
        finally:
            for p, a in saved:
                p._data_ = a
            _state.STATE.rng_key, _state.STATE.rng_counter = saved_rng

    def _pipeline_fn(self, x_arr, y_arr, base_key, edge_arrays,
                     stacked_arrays):
        """Pure: (micro-batched inputs, labels, params) → mean loss.

        Always executed under jax.jit (see run()): the partial-manual
        shard_map inside must go through the abstract tracing path — its
        eager impl re-shards concrete operands with internal specs that
        refer to auto axes and rejects them."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        S, C, M = self._S, self._C, self._n_micro
        SC = S * C
        T = self.num_ticks

        with no_grad():
            # ---- pre (embedding etc.) on the full mesh ----
            saved = [(p, p._data_) for p in self._edge_params]
            try:
                for p, a in zip(self._edge_params, edge_arrays):
                    p._data_ = a
                h = _run_items(self.pre, Tensor(x_arr, stop_gradient=True))
                h = h._data_
                mb = h.shape[0] // M
                micros = h.reshape((M, mb) + h.shape[1:])

                stage = self._stage_apply
                if self._remat:
                    stage = jax.checkpoint(stage)

                def tick_loop(stacked_local, micros_rep):
                    # stacked_local leaves: [1, C, *shape] → [C, *shape]
                    local = [a[0] for a in stacked_local]
                    r = lax.axis_index("pp")
                    zero = jnp.zeros(micros_rep.shape[1:],
                                     micros_rep.dtype)

                    def body(carry, t):
                        recv = carry
                        u = t - r
                        g = jnp.maximum(u, 0) // SC
                        span = jnp.maximum(u, 0) % SC
                        c = span // S
                        mig = span % S
                        m = g * S + mig
                        valid = (u >= 0) & (m < M)
                        inject = valid & (r == 0) & (c == 0)
                        m_c = jnp.clip(m, 0, M - 1)
                        x_in = jnp.where(
                            inject,
                            lax.dynamic_index_in_dim(micros_rep, m_c, 0,
                                                     keepdims=False),
                            recv)
                        if C == 1:
                            chunk = [a[0] for a in local]
                        else:
                            c_c = jnp.clip(c, 0, C - 1)
                            chunk = [lax.dynamic_index_in_dim(
                                a, c_c, 0, keepdims=False) for a in local]
                        key = jax.random.fold_in(base_key, t)
                        y = stage(chunk, x_in, key)
                        y = jnp.where(valid, y, zero)
                        emit = valid & (r == S - 1) & (c == C - 1)
                        out = jnp.where(emit, y, zero)
                        send = lax.ppermute(
                            y, "pp", [(i, (i + 1) % S) for i in range(S)])
                        return send, out

                    _, ys = lax.scan(body, zero, jnp.arange(T))
                    return ys[None]  # [1, T, mb, ...]

                if hasattr(jax, "shard_map"):       # jax >= 0.5 surface
                    _shard_map = jax.shard_map
                    sm_kwargs = dict(axis_names={"pp"}, check_vma=False)
                else:                               # 0.4.x: experimental
                    # full-manual over the mesh (0.4.x partial-auto
                    # cannot host committed specs naming manual axes;
                    # ZeRO-stacked pp × sep/mp combinations need the
                    # jax >= 0.5 axis_names surface)
                    from jax.experimental.shard_map import shard_map \
                        as _shard_map
                    sm_kwargs = dict(check_rep=False)
                pipelined = _shard_map(
                    tick_loop,
                    mesh=self._mesh.jax_mesh,
                    in_specs=([P("pp")] * len(stacked_arrays), P()),
                    out_specs=P("pp"),
                    **sm_kwargs)
                ys = pipelined(list(stacked_arrays), micros)  # [S, T, ...]

                # collect each micro's exit tick from the last rank
                t_end = np.array([(m // S) * SC + m % S + SC - 1
                                  for m in range(M)])
                body_out = jnp.take(ys[S - 1], jnp.asarray(t_end), axis=0)
                h_out = body_out.reshape((M * mb,) + body_out.shape[2:])

                # ---- post (final norm / head) + loss on the full batch ----
                out = _run_items(self.post,
                                 Tensor(h_out, stop_gradient=True))
                if self._loss_fn is not None and y_arr is not None:
                    loss = self._loss_fn(out,
                                         Tensor(y_arr, stop_gradient=True))
                else:
                    loss = out
                return loss._data_ if isinstance(loss, Tensor) else loss
            finally:
                for p, a in saved:
                    p._data_ = a

    def run(self, inputs, labels):
        """One pipelined forward+loss with gradients to all params via the
        framework tape (backward() then accumulates into .grad)."""
        from ....core.dispatch import apply_op
        from ....core.state import next_rng_key

        x = inputs if isinstance(inputs, Tensor) else Tensor(inputs)
        y = labels if isinstance(labels, Tensor) or labels is None \
            else Tensor(labels)
        if x.shape[0] % self._n_micro:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by accumulate_steps "
                f"{self._n_micro}")
        base_key = next_rng_key()
        n_edge = len(self._edge_params)
        if self._jitted is None:
            import jax
            self._jitted = jax.jit(self._pipeline_fn)

        def fn(x_arr, y_arr, *param_arrays):
            return self._jitted(x_arr, y_arr, base_key,
                                list(param_arrays[:n_edge]),
                                list(param_arrays[n_edge:]))

        args = (x, y, *self._edge_params, *self.stacked)
        return apply_op("pipeline_spmd", fn, args)
