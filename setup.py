"""Thin setup shim — configuration lives in pyproject.toml.

Kept so `python setup.py --version` and legacy tooling work (reference:
/root/reference/setup.py is the monolithic build driver; here the native
runtime pieces are JIT-built via paddle_tpu/utils/cpp_extension.py)."""
from setuptools import setup

setup()
