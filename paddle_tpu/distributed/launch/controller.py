"""Collective controller: spawn, watch, restart local worker processes.

Reference capability: launch controllers (reference:
launch/controllers/collective.py — builds pod of N procs with the env
contract; controllers/watcher.py monitors; master.py KV rendezvous) and the
relaunch-on-failure loop (fleet/elastic ELASTIC_EXIT_CODE protocol).

TPU-native notes: one process per host is the JAX multi-controller model
(all local chips belong to that process), so nproc_per_node>1 is for CPU
testing; rendezvous is jax.distributed.initialize against the coordinator
address instead of a bespoke TCPStore.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

from .context import Context, free_port

ELASTIC_EXIT_CODE = 101  # reference: fleet/elastic/manager.py:32


class CollectiveController:
    def __init__(self, ctx: Context):
        self.ctx = ctx
        self.procs = []
        master = ctx.args.master
        if master is None:
            master = f"127.0.0.1:{free_port()}"
        self.master = master

    def _spawn_one(self, local_rank):
        args = self.ctx.args
        env = self.ctx.proc_env(local_rank, self.master)
        cmd = [sys.executable, args.training_script,
               *args.training_script_args]
        stdout = stderr = None
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
            rank = self.ctx.global_rank(local_rank)
            log = open(os.path.join(args.log_dir,
                                    f"worker.{rank}.log"), "ab")
            stdout = stderr = log
        return subprocess.Popen(cmd, env=env, stdout=stdout, stderr=stderr)

    def run(self):
        args = self.ctx.args
        restarts = 0
        while True:
            self.procs = [self._spawn_one(i)
                          for i in range(args.nproc_per_node)]
            codes = self._watch()
            if all(c == 0 for c in codes):
                return 0
            if any(c == ELASTIC_EXIT_CODE for c in codes) \
                    and restarts < args.max_restart:
                restarts += 1
                continue
            return max(codes)

    def _watch(self):
        """Wait for all procs; if one fails, terminate the rest (the
        watcher/pod-failure policy of controllers/watcher.py)."""
        codes = [None] * len(self.procs)
        try:
            while any(c is None for c in codes):
                for i, p in enumerate(self.procs):
                    if codes[i] is None:
                        c = p.poll()
                        if c is not None:
                            codes[i] = c
                            if c != 0:
                                self._terminate(exclude=i)
                                for j, q in enumerate(self.procs):
                                    if codes[j] is None:
                                        codes[j] = q.wait()
                                return codes
                time.sleep(0.2)
        except KeyboardInterrupt:
            self._terminate()
            raise
        return codes

    def _terminate(self, exclude=None):
        for i, p in enumerate(self.procs):
            if i != exclude and p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass


def launch(argv=None):
    ctx = Context(argv=argv)
    return CollectiveController(ctx).run()
