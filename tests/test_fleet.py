"""Multi-host serving fleet (paddle_tpu/serving/{router,fleet}.py):
consistent-hash routing, membership + anti-flap reap, load shedding,
failover with idempotent resubmission, drain awareness, and the rpc /
store / engine hardening underneath it.  Thread-mode replicas (several
`ReplicaServer`s in one process, each with its own rpc listener) keep
these fast; the process-mode chaos drill lives in
benchmarks/serving_fleet_bench.py and the CI fleet lane."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import rpc
from paddle_tpu.distributed.store import (FileKVStore, TCPElasticStore,
                                          TCPStore)
from paddle_tpu.models import GPTForCausalLM, gpt_config
from paddle_tpu.serving import (Engine, EngineShutdownError, HashRing,
                                QueueFullError, ReplicaConfig,
                                ReplicaServer, RouterConfig,
                                SamplingParams, ServingConfig,
                                ServingRouter, serving_stats)
from paddle_tpu.utils.flags import set_flags


def _np(t):
    return np.asarray(t._data_)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_config(
        "gpt2-124m", num_layers=2, hidden_size=64, num_heads=4,
        vocab_size=256, max_seq_len=64))
    m.eval()
    return m


def _prompts(lens, seed=0, vocab=256):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (n,)).astype("int32") for n in lens]


def _ref_greedy(model, prompt, max_new):
    ids = model.generate(paddle.to_tensor(prompt[None, :]),
                         max_new_tokens=max_new, temperature=0.0)
    return _np(ids)[0, prompt.size:]


_FAST = dict(heartbeat_interval_s=0.15, heartbeat_ttl_s=1.2)


class _Fleet:
    """Thread-mode harness: N ReplicaServers + router on one TCPStore."""

    def __init__(self, model, n=2, serving_config=None, replica_config=None,
                 router_config=None):
        self.master = TCPStore(is_master=True)
        scfg = serving_config or ServingConfig(num_slots=2, max_queue=16)
        rcfg = (replica_config or ReplicaConfig(**_FAST)).validate()
        self.reps = {}
        for i in range(n):
            name = f"rep-{i}"
            self.reps[name] = ReplicaServer(
                name, model, TCPStore("127.0.0.1", self.master.port),
                scfg, rcfg)
        self.router = ServingRouter(
            TCPStore("127.0.0.1", self.master.port),
            router_config or RouterConfig(
                heartbeat_ttl_s=rcfg.heartbeat_ttl_s,
                poll_interval_s=0.1)).start()
        deadline = time.monotonic() + 30
        while len(self.router.ring.members) < n:
            assert time.monotonic() < deadline, \
                f"ring never filled: {self.router.replicas()}"
            time.sleep(0.05)

    def kill(self, name):
        """SIGKILL analog for a threaded replica: rpc listener gone,
        heartbeats stop, engine dead — NO deregistration."""
        rep = self.reps[name]
        rep._stop.set()
        rep._beat.join(5.0)
        rep.rpc_server.close()
        rep.engine.shutdown()

    def close(self):
        self.router.close()
        for rep in self.reps.values():
            rep.close()
        self.master.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------- ring
def test_hash_ring_distinct_successors_and_minimal_remap():
    ring = HashRing(virtual_nodes=32)
    ring.rebuild({"a", "b", "c"})
    keys = [f"key-{i}" for i in range(200)]
    for k in keys:
        succ = list(ring.successors(k))
        assert sorted(succ) == ["a", "b", "c"]      # each member once
        assert succ[0] == ring.lookup(k)
    owners = {k: ring.lookup(k) for k in keys}
    # removing one member must not remap keys owned by survivors
    ring.rebuild({"a", "b"})
    for k in keys:
        if owners[k] != "c":
            assert ring.lookup(k) == owners[k]
    # adding it back restores the original ownership exactly
    ring.rebuild({"a", "b", "c"})
    assert {k: ring.lookup(k) for k in keys} == owners


def test_config_validation():
    with pytest.raises(ValueError, match="heartbeat_ttl_s"):
        RouterConfig(heartbeat_ttl_s=0).validate()
    with pytest.raises(ValueError, match="virtual_nodes"):
        RouterConfig(virtual_nodes=0).validate()
    with pytest.raises(ValueError, match="must exceed"):
        ReplicaConfig(heartbeat_interval_s=2.0,
                      heartbeat_ttl_s=1.0).validate()
    with pytest.raises(ValueError, match="tensor_parallel_degree"):
        ReplicaConfig(tensor_parallel_degree=0).validate()


# ------------------------------------------------------------- routing
def test_fleet_greedy_bit_equal_and_affinity(model):
    """Outputs routed through a 2-replica fleet are bit-equal to the
    single-model greedy reference, and same-session requests stick to
    the ring owner."""
    prompts = _prompts([5, 7, 3, 9, 6])
    with _Fleet(model, n=2) as f:
        futs = [f.router.submit(p, max_new_tokens=5, session_id=f"s{i}")
                for i, p in enumerate(prompts)]
        outs = [fut.result(timeout=120) for fut in futs]
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(o.output_ids,
                                          _ref_greedy(model, p, 5))
            assert o.finish_reason == "length"
        # affinity: the ring owner of a session serves every repeat
        owner = f.router.ring.lookup("sticky")
        with f.reps[owner]._dedup_lock:
            before = len(f.reps[owner]._dedup)
        futs = [f.router.submit(prompts[0], max_new_tokens=2,
                                session_id="sticky") for _ in range(3)]
        [fut.result(timeout=120) for fut in futs]
        with f.reps[owner]._dedup_lock:
            assert len(f.reps[owner]._dedup) == before + 3
        snap = serving_stats()
        assert snap["router_requests_routed"] == 8
        assert snap["router_replicas_alive"] == 2
        assert snap["router_route_latency_ms_avg"] > 0


def test_router_load_shedding_fails_fast(model):
    """At >capacity offered load every ready replica sheds; the router
    fails fast with QueueFullError carrying retry_after_s instead of
    queueing unboundedly, and counts the sheds."""
    scfg = ServingConfig(num_slots=1, max_queue=1)
    with _Fleet(model, n=2, serving_config=scfg,
                router_config=RouterConfig(
                    heartbeat_ttl_s=1.2, poll_interval_s=0.1,
                    retry_after_s=0.7)) as f:
        shed_before = serving_stats()["router_requests_shed"]
        prompts = _prompts([6] * 10, seed=3)
        futs = [f.router.submit(p, max_new_tokens=40, session_id=i)
                for i, p in enumerate(prompts)]
        done, shed = 0, 0
        for fut in futs:
            try:
                out = fut.result(timeout=180)
                assert out.finish_reason in ("length", "eos")
                done += 1
            except QueueFullError as e:
                # the hint starts at the knob and scales (up to 8x)
                # with the router's recent shed pressure
                assert 0.7 <= e.retry_after_s <= 0.7 * 8
                shed += 1
        assert done + shed == 10
        assert shed >= 1, "10 requests into 2x(1 slot + 1 queue) must shed"
        assert serving_stats()["router_requests_shed"] - shed_before \
            == shed


def test_failover_replica_death_recovers_request(model):
    """A request routed to a replica that dies mid-fleet is resubmitted
    to a survivor under the same id: the client sees one complete,
    correct stream — never a duplicate, never a hang."""
    with _Fleet(model, n=2) as f:
        owner = f.router.ring.lookup("victim-session")
        f.kill(owner)
        p = _prompts([6], seed=5)[0]
        out = f.router.submit(p, max_new_tokens=5,
                              session_id="victim-session").result(timeout=120)
        np.testing.assert_array_equal(out.output_ids,
                                      _ref_greedy(model, p, 5))
        snap = serving_stats()
        assert snap["router_failovers"] >= 1
        assert snap["router_requests_recovered"] >= 1
        # the dead replica is sticky-dead, not flapping
        deadline = time.monotonic() + 10
        while f.router.replicas().get(owner) != "dead":
            assert time.monotonic() < deadline
            time.sleep(0.05)
        assert f.router.ring.members == {n for n in f.reps if n != owner}


def test_rpc_drop_injection_drills_failover(model):
    """The rpc_drop fault point makes the failover path deterministic:
    no SIGKILL needed — connects to the victim fail, the router marks it
    dead and reroutes."""
    with _Fleet(model, n=2) as f:
        owner = f.router.ring.lookup("drilled")
        try:
            set_flags({"FLAGS_fault_inject": f"rpc_drop:to={owner}"})
            p = _prompts([5], seed=7)[0]
            out = f.router.submit(
                p, max_new_tokens=4,
                session_id="drilled").result(timeout=120)
            np.testing.assert_array_equal(out.output_ids,
                                          _ref_greedy(model, p, 4))
            assert serving_stats()["router_failovers"] >= 1
            assert f.router.replicas()[owner] == "dead"
        finally:
            set_flags({"FLAGS_fault_inject": ""})


def test_rpc_delay_injection_sleeps_connects():
    from paddle_tpu.utils import fault_injection as fi
    try:
        set_flags({"FLAGS_fault_inject":
                   "rpc_delay:to=slowpoke,delay_s=0.2,count=1"})
        t0 = time.monotonic()
        assert fi.check_rpc("rpc_delay", "slowpoke-0") is False
        assert time.monotonic() - t0 >= 0.2
        t0 = time.monotonic()                 # count=1 exhausted
        fi.check_rpc("rpc_delay", "slowpoke-0")
        assert time.monotonic() - t0 < 0.1
        assert fi.check_rpc("rpc_drop", "slowpoke-0") is False
    finally:
        set_flags({"FLAGS_fault_inject": ""})


def test_drain_aware_routing(model):
    """A draining replica leaves the ring within a poll interval and its
    queued requests are resubmitted to survivors — zero lost."""
    with _Fleet(model, n=2) as f:
        owner = f.router.ring.lookup("drainee")
        survivor = next(n for n in f.reps if n != owner)
        # long decodes occupy the owner, then drain it mid-flight
        prompts = _prompts([6] * 4, seed=9)
        futs = [f.router.submit(p, max_new_tokens=30,
                                session_id="drainee") for p in prompts]
        time.sleep(0.3)
        drainer = threading.Thread(
            target=f.reps[owner].drain, kwargs={"deadline_s": 30.0})
        drainer.start()
        outs = [fut.result(timeout=180) for fut in futs]
        drainer.join(60)
        assert not drainer.is_alive()
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(o.output_ids,
                                          _ref_greedy(model, p, 30))
        # the drained replica left the ring; the survivor serves on
        deadline = time.monotonic() + 10
        while f.router.ring.members != {survivor}:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        out = f.router.submit(prompts[0], max_new_tokens=2,
                              session_id="drainee").result(timeout=60)
        assert len(out.output_ids) == 2


def test_replica_reap_and_generation_rejoin(model):
    """Anti-flap end to end: a replica that misses heartbeats goes
    sticky-dead and its lease is reaped; resumed heartbeats re-register
    under a BUMPED generation, which the router accepts as an explicit
    rejoin — membership sees two edges, not an oscillation."""
    with _Fleet(model, n=2) as f:
        victim = sorted(f.reps)[0]
        rep = f.reps[victim]
        gen0 = rep.gen
        rep._stop.set()                      # pause heartbeats
        rep._beat.join(5.0)
        deadline = time.monotonic() + 15
        while f.router.replicas().get(victim) != "dead":
            assert time.monotonic() < deadline, "never marked dead"
            time.sleep(0.05)
        # the router reaped the expired lease (anti-flap)
        deadline = time.monotonic() + 10
        while rep.membership.is_registered(victim):
            assert time.monotonic() < deadline, "lease never reaped"
            time.sleep(0.05)
        # resume heartbeats: the loop notices the reap and re-registers
        # with a bumped generation
        rep._stop = threading.Event()
        rep._beat = threading.Thread(target=rep._beat_loop, daemon=True)
        rep._beat.start()
        deadline = time.monotonic() + 15
        while victim not in f.router.ring.members:
            assert time.monotonic() < deadline, "never rejoined"
            time.sleep(0.05)
        assert rep.gen > gen0


# ------------------------------------------------- store / rpc hardening
@pytest.mark.parametrize("kind", ["tcp", "file"])
def test_elastic_store_expiry_reap_reregister(kind, tmp_path):
    master = None
    if kind == "tcp":
        master = TCPStore(is_master=True)
        store = TCPStore("127.0.0.1", master.port)
    else:
        store = FileKVStore(str(tmp_path))   # no stamp/server_now: falls
        #                                      back to writer wall clock
    try:
        es = TCPElasticStore(store, ttl=0.4)
        es.register("n1")
        es.register("n2")
        assert es.alive_nodes() == ["n1", "n2"]
        assert es.expired_nodes() == []
        time.sleep(0.6)
        es.heartbeat("n2")                   # n1 flaps, n2 stays fresh
        assert es.alive_nodes() == ["n2"]
        assert es.expired_nodes() == ["n1"]
        assert es.is_registered("n1")        # key lingers until reaped
        assert es.reap() == ["n1"]
        assert es.is_registered("n1") is False
        assert es.expired_nodes() == []
        es.register("n1")                    # explicit rejoin
        assert es.alive_nodes() == ["n1", "n2"]
    finally:
        if master is not None:
            store.close()
            master.close()


def test_rpc_shutdown_idempotent_and_connect_retry():
    rpc.shutdown()                           # never initialized: no-op
    rpc.shutdown()
    # connect to a port nothing listens on: retried, then a loud
    # ConnectionError naming the worker — never a hang
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    rpc.connect_worker("ghost", "127.0.0.1", port)
    try:
        t0 = time.monotonic()
        with pytest.raises(ConnectionError, match="ghost"):
            rpc.rpc_sync("ghost", sorted, args=([3, 1],))
        assert time.monotonic() - t0 < 10
    finally:
        rpc.forget_worker(name="ghost")
    with pytest.raises(ValueError, match="unknown worker"):
        rpc.rpc_sync("ghost", sorted, args=([],))


def test_rpc_server_close_releases_port():
    """close() must wake the accept loop so the kernel releases the
    socket — a dangling accept would keep 'serving' a dead replica."""
    srv = rpc.RpcServer("porttest")
    port = srv.info.port
    srv.close()
    srv.close()                              # idempotent
    import socket
    deadline = time.monotonic() + 5
    while True:
        try:
            s = socket.socket()
            s.bind(("127.0.0.1", port))
            s.close()
            break
        except OSError:
            assert time.monotonic() < deadline, "port never released"
            time.sleep(0.1)


# -------------------------------------------------- engine under drain
def test_submit_drain_race_never_strands_a_future(model):
    """Hammer submit() from several threads while drain() runs: every
    future resolves (result or EngineShutdownError) and every late
    submit raises — no client ever hangs."""
    eng = Engine(model, ServingConfig(num_slots=2, max_queue=64)).start()
    prompts = _prompts([5], seed=11)
    futures, rejected = [], []
    flock = threading.Lock()
    stop = threading.Event()

    def _hammer():
        while not stop.is_set():
            try:
                fut = eng.submit(prompts[0], max_new_tokens=3)
                with flock:
                    futures.append(fut)
            except (EngineShutdownError, QueueFullError) as e:
                with flock:
                    rejected.append(e)
                if isinstance(e, EngineShutdownError):
                    return
            time.sleep(0.002)

    threads = [threading.Thread(target=_hammer) for _ in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.4)
    eng.drain(deadline_s=60.0)
    stop.set()
    for t in threads:
        t.join(30)
        assert not t.is_alive()
    assert futures, "hammer never got a request in"
    assert any(isinstance(e, EngineShutdownError) for e in rejected), \
        "drain must reject late submits loudly"
    resolved = 0
    for fut in futures:
        try:
            out = fut.result(timeout=30)     # must already be done
            assert out.finish_reason in ("length", "eos")
            resolved += 1
        except (EngineShutdownError, Exception):
            assert fut.done()
    assert eng._pending == {}, "audit registry must drain"
    assert resolved >= 1


def test_replica_handle_submit_idempotent(model):
    """A resubmitted request id re-awaits the SAME engine future: the
    engine decodes once, both calls return identical payloads."""
    master = TCPStore(is_master=True)
    rep = ReplicaServer("solo", model,
                        TCPStore("127.0.0.1", master.port),
                        ServingConfig(num_slots=2, max_queue=8),
                        ReplicaConfig(**_FAST))
    try:
        p = _prompts([6], seed=13)[0]
        sampling = {"temperature": 0.0}
        a = rep.handle_submit("rid-1", p, 4, sampling, None, None)
        before = serving_stats()["requests_submitted"]
        b = rep.handle_submit("rid-1", p, 4, sampling, None, None)
        assert serving_stats()["requests_submitted"] == before, \
            "resubmit must not re-decode"
        np.testing.assert_array_equal(a["output_ids"], b["output_ids"])
        assert a["finish_reason"] == b["finish_reason"]
        # sampled requests stay idempotent too (same future, same draw)
        c = rep.handle_submit("rid-2", p, 4,
                              {"temperature": 0.8, "top_k": 8}, None,
                              None)
        d = rep.handle_submit("rid-2", p, 4,
                              {"temperature": 0.8, "top_k": 8}, None,
                              None)
        np.testing.assert_array_equal(c["output_ids"], d["output_ids"])
    finally:
        rep.close()
        master.close()


def test_router_submit_validation(model):
    with _Fleet(model, n=1) as f:
        with pytest.raises(ValueError, match="empty prompt"):
            f.router.submit(np.zeros((0,), np.int32))
        with pytest.raises(ValueError):
            f.router.submit(_prompts([4])[0],
                            sampling=SamplingParams(temperature=-1))
    with pytest.raises(EngineShutdownError):
        f.router.submit(_prompts([4])[0])
