"""Linear algebra ops (reference: python/paddle/tensor/linalg.py —
matmul at :144 dispatching to the PHI cuBLAS path).  TPU-native realization:
`jnp.matmul`/`lax.dot_general` lower straight onto the MXU; bf16 inputs use
native mixed-precision accumulation (preferred_element_type=f32)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import defop


@defop("matmul")
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    # accumulate in f32 on the MXU even for bf16 operands
    if x.dtype in (jnp.bfloat16, jnp.float16):
        return jnp.matmul(x, y, preferred_element_type=jnp.float32).astype(x.dtype)
    return jnp.matmul(x, y)


@defop("transpose")
def transpose(x, perm=None, name=None):
    return jnp.transpose(x, axes=tuple(perm) if perm is not None else None)


@defop("t")
def t(x, name=None):
    if x.ndim > 2:
        raise ValueError("paddle.t only supports ndim<=2")
    return x.T


@defop("dot")
def dot(x, y, name=None):
    return jnp.sum(x * y, axis=-1)


@defop("mv")
def mv(x, vec, name=None):
    return jnp.matmul(x, vec)


@defop("bmm")
def bmm(x, y, name=None):
    return jnp.matmul(x, y)


@defop("norm")
def norm(x, p="fro", axis=None, keepdim=False, name=None):
    if p == "fro" or p is None:
        if axis is None:
            return jnp.sqrt(jnp.sum(x * x))
        return jnp.sqrt(jnp.sum(x * x, axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis,
                                keepdims=keepdim))
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)


@defop("dist")
def dist(x, y, p=2.0, name=None):
    d = x - y
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == 0:
        return jnp.sum((d != 0).astype(x.dtype))
    return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)


@defop("cross")
def cross(x, y, axis=9, name=None):
    if axis == 9:
        axis = next(i for i, s in enumerate(x.shape) if s == 3)
    return jnp.cross(x, y, axis=axis)


@defop("einsum")
def einsum_op(equation, *operands):
    return jnp.einsum(equation, *operands)


def einsum(equation, *operands):
    from ..core.dispatch import apply_op

    def fn(*ops):
        return jnp.einsum(equation, *ops)
    return apply_op("einsum", fn, operands)


@defop("matrix_power")
def matrix_power(x, n, name=None):
    return jnp.linalg.matrix_power(x, n)


@defop("inverse")
def inverse(x, name=None):
    return jnp.linalg.inv(x)


inv = inverse


@defop("pinv")
def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@defop("det")
def det(x, name=None):
    return jnp.linalg.det(x)


@defop("slogdet")
def slogdet(x, name=None):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


@defop("cholesky")
def cholesky(x, upper=False, name=None):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


@defop("cholesky_solve")
def cholesky_solve(x, y, upper=False, name=None):
    c = jnp.swapaxes(y, -1, -2) if upper else y
    return jax.scipy.linalg.cho_solve((c, True), x)


@defop("triangular_solve")
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@defop("solve")
def solve(x, y, name=None):
    return jnp.linalg.solve(x, y)


@defop("lstsq", nondiff=True)
def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@defop("qr")
def qr(x, mode="reduced", name=None):
    q, r = jnp.linalg.qr(x, mode=mode)
    return q, r


@defop("svd")
def svd(x, full_matrices=False, name=None):
    """reference: paddle.linalg.svd returns (U, S, VH) where VH is the
    conjugate transpose of V (tensor/linalg.py svd docstring) — same
    contract as numpy; x == u @ diag(s) @ vh."""
    return jnp.linalg.svd(x, full_matrices=full_matrices)


@defop("eig", nondiff=True)
def eig(x, name=None):
    return jnp.linalg.eig(x)


@defop("eigh")
def eigh(x, UPLO="L", name=None):
    return jnp.linalg.eigh(x, UPLO=UPLO)


@defop("eigvals", nondiff=True)
def eigvals(x, name=None):
    return jnp.linalg.eigvals(x)


@defop("eigvalsh")
def eigvalsh(x, UPLO="L", name=None):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@defop("matrix_rank", nondiff=True)
def matrix_rank(x, tol=None, hermitian=False, name=None):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@defop("cond")
def cond(x, p=None, name=None):
    return jnp.linalg.cond(x, p=p)


@defop("lu", nondiff=True)
def lu(x, pivot=True, get_infos=False, name=None):
    lu_, piv = jax.scipy.linalg.lu_factor(x)
    # reference (LAPACK getrf) pivots are 1-based; jax returns 0-based
    piv = piv.astype(jnp.int32) + 1
    if get_infos:
        return lu_, piv, jnp.zeros((), jnp.int32)
    return lu_, piv


@defop("kron")
def kron(x, y, name=None):
    return jnp.kron(x, y)


@defop("multi_dot")
def multi_dot(xs, name=None):
    from ..core.tensor import Tensor
    arrs = [a._data if isinstance(a, Tensor) else a for a in xs]
    return jnp.linalg.multi_dot(arrs)


@defop("householder_product")
def householder_product(x, tau, name=None):
    m, n = x.shape[-2], x.shape[-1]
    eye = jnp.eye(m, dtype=x.dtype)
    q = jnp.broadcast_to(eye, x.shape[:-2] + (m, m)).copy() if x.ndim > 2 else eye

    def body(i, q):
        v = jnp.where(jnp.arange(m) < i, 0.0, x[..., i])
        v = v.at[i].set(1.0) if x.ndim == 2 else v
        h = jnp.eye(m, dtype=x.dtype) - tau[..., i] * jnp.outer(v, v)
        return q @ h
    for i in range(n):
        q = body(i, q)
    return q[..., :, :n]
