"""paddle.jit.dy2static convert-operator surface (reference:
python/paddle/jit/dy2static/convert_operators.py — the functions the
AST/SOT transform rewrites python control flow into).

TPU-native realization: tensor-valued conditions route to the
control-flow ops in tensor_ops/control.py (one lax.while_loop/lax.cond
program when gradients are off; tape-recorded guarded python otherwise),
python-valued conditions run natively — the same dispatch the
reference's _run_paddle_*/_run_py_* pairs perform."""
from __future__ import annotations

from ..core.tensor import Tensor
from ..tensor_ops import control as _control

__all__ = [
    "convert_while_loop", "convert_ifelse", "convert_logical_and",
    "convert_logical_or", "convert_logical_not", "convert_len",
    "convert_shape", "convert_range", "convert_enumerate", "convert_zip",
    "convert_attr", "indexable", "unpack_by_structure",
]


def _is_tensor(x):
    return isinstance(x, Tensor)


def convert_while_loop(cond, body, getter, setter, return_name_ids=None,
                       push_pop_names=None):
    """reference: convert_operators.py convert_while_loop — loop state
    flows through getter/setter closures."""
    # the reference's protocol: getter() returns the loop-var tuple,
    # setter(values) writes them back; cond/body are nullary
    vars_ = getter()
    single = not isinstance(vars_, (tuple, list))
    if single:
        vars_ = (vars_,)
    if all(_is_tensor(v) for v in vars_) and vars_:
        def c(*vs):
            setter(vs[0] if single else tuple(vs))
            return cond()

        def b(*vs):
            setter(vs[0] if single else tuple(vs))
            body()
            out = getter()
            return (out,) if single else tuple(out)

        res = _control.while_loop(c, b, list(vars_))
        setter(res[0] if single else tuple(res))
        return getter()
    # python state: plain while
    while cond():
        body()
    return getter()


def convert_ifelse(pred, true_fn, false_fn, get_args, set_args,
                   return_name_ids=None, push_pop_names=None):
    """reference: convert_operators.py convert_ifelse."""
    if _is_tensor(pred):
        def t():
            set_args(get_args())
            return true_fn()

        def f():
            set_args(get_args())
            return false_fn()
        return _control.cond(pred, t, f)
    return true_fn() if pred else false_fn()


def convert_logical_and(x_fn, y_fn):
    """Short-circuit only when x is a python bool (reference:
    _run_py_logical_and vs _run_paddle_logical_and)."""
    x = x_fn()
    if not _is_tensor(x):
        return x and y_fn()
    y = y_fn()
    if not _is_tensor(y):
        return y and x
    from ..tensor_ops.logic import logical_and
    return logical_and(x, y)


def convert_logical_or(x_fn, y_fn):
    x = x_fn()
    if not _is_tensor(x):
        return x or y_fn()
    y = y_fn()
    if not _is_tensor(y):
        return y or x
    from ..tensor_ops.logic import logical_or
    return logical_or(x, y)


def convert_logical_not(x):
    if not _is_tensor(x):
        return not x
    from ..tensor_ops.logic import logical_not
    return logical_not(x)


def convert_len(x):
    if _is_tensor(x):
        return x.shape[0]
    return len(x)


def convert_shape(x):
    if _is_tensor(x):
        return tuple(x.shape)
    return x.shape


def convert_range(*args):
    args = [int(a.numpy()) if _is_tensor(a) else a for a in args]
    return range(*args)


def convert_enumerate(*args):
    items = args[0]
    start = args[1] if len(args) > 1 else 0
    if _is_tensor(items):
        items = [items[i] for i in range(items.shape[0])]
    return enumerate(items, start)


def convert_zip(*args):
    seqs = []
    for a in args:
        if _is_tensor(a):
            seqs.append([a[i] for i in range(a.shape[0])])
        else:
            seqs.append(a)
    return zip(*seqs)


def convert_attr(x, attr):
    if _is_tensor(x) and attr == "size":
        return x.size
    return getattr(x, attr)


def indexable(x, code=None):
    if _is_tensor(x):
        return [x[i] for i in range(x.shape[0])]
    if hasattr(x, "__len__") and hasattr(x, "__getitem__"):
        return x
    return list(x)


def unpack_by_structure(target, structure):
    """reference: convert_operators.py unpack_by_structure."""
    if structure == 1:
        return target
    return [unpack_by_structure(t, s)
            for t, s in zip(target, structure)] \
        if isinstance(structure, (list, tuple)) else target


# ------------------------------------------------------------------
# AST transform (reference: jit/dy2static/transformers/ — rewrite python
# control flow into convert_* calls).  Scope: `while`/`if` statements
# without break/continue/return in their bodies, and bool ops.  Anything
# outside that scope is left as native python, which still executes
# correctly (eager, or guard-specialized under to_static).
# ------------------------------------------------------------------

import ast as _ast
import functools as _functools
import inspect as _inspect
import textwrap as _textwrap


def _assigned_names(nodes):
    out = []
    for n in nodes:
        for sub in _ast.walk(n):
            if isinstance(sub, _ast.Name) and isinstance(sub.ctx,
                                                         _ast.Store):
                if sub.id not in out:
                    out.append(sub.id)
            elif isinstance(sub, (_ast.FunctionDef,
                                  _ast.AsyncFunctionDef)):
                break
    return out


def _has_escape(nodes):
    for n in nodes:
        for sub in _ast.walk(n):
            if isinstance(sub, (_ast.Break, _ast.Continue, _ast.Return)):
                return True
    return False


class _ControlFlowTransformer(_ast.NodeTransformer):
    """Rewrites
        while <test>: <body>
    into the convert_while_loop getter/setter protocol (and `if` into
    convert_ifelse) so tensor conditions compile through the lax
    lowering instead of per-iteration host reads."""

    def __init__(self):
        self._n = 0

    def _fresh(self, base):
        self._n += 1
        return f"__d2s_{base}_{self._n}"

    def _state_fns(self, names, tag):
        get_name, set_name = self._fresh(f"get{tag}"), \
            self._fresh(f"set{tag}")
        get_def = _ast.parse(
            f"def {get_name}():\n"
            f"    return ({', '.join(names)}{',' if names else ''})\n"
        ).body[0]
        set_src = f"def {set_name}(__vals):\n"
        if names:
            set_src += f"    nonlocal {', '.join(names)}\n"
            set_src += f"    ({', '.join(names)}{',' if names else ''}) " \
                       f"= __vals\n"
        else:
            set_src += "    pass\n"
        set_def = _ast.parse(set_src).body[0]
        return get_name, set_name, [get_def, set_def]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has_escape(node.body):
            return node
        names = [n for n in _assigned_names(node.body)
                 if not n.startswith("__d2s_")]
        cond_name = self._fresh("cond")
        body_name = self._fresh("body")
        get_name, set_name, state_defs = self._state_fns(names, "w")
        cond_def = _ast.FunctionDef(
            name=cond_name,
            args=_ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                kw_defaults=[], defaults=[]),
            body=([_ast.Nonlocal(names=list(names))] if names else [])
            + [_ast.Return(value=node.test)],
            decorator_list=[])
        body_def = _ast.FunctionDef(
            name=body_name,
            args=_ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                kw_defaults=[], defaults=[]),
            body=([_ast.Nonlocal(names=list(names))] if names else [])
            + list(node.body),
            decorator_list=[])
        call = _ast.Expr(value=_ast.Call(
            func=_ast.Attribute(value=_ast.Name(id="__d2s__",
                                                ctx=_ast.Load()),
                                attr="convert_while_loop",
                                ctx=_ast.Load()),
            args=[_ast.Name(id=cond_name, ctx=_ast.Load()),
                  _ast.Name(id=body_name, ctx=_ast.Load()),
                  _ast.Name(id=get_name, ctx=_ast.Load()),
                  _ast.Name(id=set_name, ctx=_ast.Load())],
            keywords=[]))
        return [_ast.fix_missing_locations(_ast.copy_location(s, node))
                for s in state_defs + [cond_def, body_def, call]]

    def visit_If(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or _has_escape(node.orelse):
            return node
        names = [n for n in _assigned_names(node.body + node.orelse)
                 if not n.startswith("__d2s_")]
        true_name = self._fresh("true")
        false_name = self._fresh("false")
        get_name, set_name, state_defs = self._state_fns(names, "i")

        def branch(name, stmts):
            return _ast.FunctionDef(
                name=name,
                args=_ast.arguments(posonlyargs=[], args=[],
                                    kwonlyargs=[], kw_defaults=[],
                                    defaults=[]),
                body=([_ast.Nonlocal(names=list(names))] if names else [])
                + (list(stmts) if stmts else [_ast.Pass()]),
                decorator_list=[])
        call = _ast.Expr(value=_ast.Call(
            func=_ast.Attribute(value=_ast.Name(id="__d2s__",
                                                ctx=_ast.Load()),
                                attr="convert_ifelse", ctx=_ast.Load()),
            args=[node.test,
                  _ast.Name(id=true_name, ctx=_ast.Load()),
                  _ast.Name(id=false_name, ctx=_ast.Load()),
                  _ast.Name(id=get_name, ctx=_ast.Load()),
                  _ast.Name(id=set_name, ctx=_ast.Load())],
            keywords=[]))
        return [_ast.fix_missing_locations(_ast.copy_location(s, node))
                for s in state_defs
                + [branch(true_name, node.body),
                   branch(false_name, node.orelse), call]]


def ast_transform(fn):
    """Rewrite `fn`'s python control flow into convert_* calls
    (reference: the dy2static program translator).  Tensor `while`/`if`
    then compile through the lax lowering; functions whose source is
    unavailable are returned unchanged."""
    try:
        src = _textwrap.dedent(_inspect.getsource(fn))
    except (OSError, TypeError):
        return fn
    if fn.__closure__:
        # free variables can't be rebuilt by exec — fall back untransformed
        return fn
    tree = _ast.parse(src)
    fdef = tree.body[0]
    fdef.decorator_list = []   # don't re-apply to_static/ast_transform
    new_tree = _ControlFlowTransformer().visit(tree)
    _ast.fix_missing_locations(new_tree)
    try:
        code = compile(new_tree, filename=f"<dy2static {fn.__name__}>",
                       mode="exec")
    except SyntaxError:
        # e.g. a branch-local first binding can't be nonlocal'd — run the
        # original (eager / guard-specialized) semantics instead
        return fn
    import sys
    glb = dict(fn.__globals__)
    glb["__d2s__"] = sys.modules[__name__]
    loc = {}
    exec(code, glb, loc)
    return _functools.wraps(fn)(loc[fdef.name])
