"""Throughput / step-time benchmarking + MFU.

Reference capability: profiler/timer.py (`benchmark()` hub with
reader/batch cost and ips) and fleet's step timers
(fleet/utils/timer_helper.py:48); the MFU calculator is the TPU-side
"north star" metric (SURVEY §6).
"""
from __future__ import annotations

import time


class _Event:
    def __init__(self):
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        if self._t0 is not None:
            self.total += time.perf_counter() - self._t0
            self.count += 1
            self._t0 = None

    @property
    def avg(self):
        return self.total / max(self.count, 1)


class TimerHub:
    """reference: timer_helper.py get_timers() pattern."""

    def __init__(self):
        self._timers = {}

    def __call__(self, name):
        if name not in self._timers:
            self._timers[name] = _Event()
        return self._timers[name]

    def log(self, names=None, normalizer=1.0, reset=True):
        names = names or list(self._timers)
        parts = []
        for n in names:
            t = self._timers.get(n)
            if t is None:
                continue
            parts.append(f"{n}: {t.total * 1000 / normalizer:.2f}ms")
            if reset:
                t.reset()
        return " | ".join(parts)


class Benchmark:
    """reference: profiler/timer.py benchmark() — reader/batch cost + ips."""

    def __init__(self):
        self.reader = _Event()
        self.batch = _Event()
        self._samples = 0
        self._t_start = None

    def begin(self):
        self._t_start = time.perf_counter()
        self.reader.reset()
        self.batch.reset()
        self._samples = 0

    def before_reader(self):
        self.reader.start()

    def after_reader(self):
        self.reader.stop()
        self.batch.start()

    def after_step(self, num_samples=1):
        self.batch.stop()
        self._samples += num_samples

    def step_info(self, unit="samples"):
        ips = self._samples / max(self.batch.total, 1e-12)
        return (f"reader_cost: {self.reader.avg * 1000:.3f} ms "
                f"batch_cost: {self.batch.avg * 1000:.3f} ms "
                f"ips: {ips:.2f} {unit}/s")

    @property
    def ips(self):
        return self._samples / max(self.batch.total, 1e-12)


_BENCH = Benchmark()


def benchmark():
    return _BENCH


# peak bf16 FLOP/s per chip by TPU generation (public spec sheet numbers)
_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "cpu": 1e12,      # nominal, keeps MFU finite in CI
}


def device_peak_flops(device=None):
    import jax
    d = device or jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    for key, val in _PEAK_FLOPS.items():
        if key in kind:
            return val
    return _PEAK_FLOPS["v5e" if d.platform in ("tpu", "axon") else "cpu"]


def mfu(model_flops_per_step, step_time_s, n_devices=1, device=None):
    """Model FLOPs utilization: achieved / peak."""
    peak = device_peak_flops(device) * n_devices
    return model_flops_per_step / max(step_time_s, 1e-12) / peak
