"""Control-flow ops (reference: python/paddle/static/nn/control_flow.py —
cond/while_loop as program ops).

TPU-native realization, two regimes:

- **Gradients disabled** (inference, decode loops, convergence loops):
  `while_loop` lowers to ONE `jax.lax.while_loop` and `cond` to ONE
  `jax.lax.cond` — a tensor-dependent trip count executes as a single
  compiled program under `to_static` (no per-trip-count respecialization,
  no host round-trip per iteration).  This is the analog of the
  reference's while/conditional_block program ops executed by
  InterpreterCore (reference: python/paddle/static/nn/control_flow.py:218
  While, :1069 cond).

- **Gradients enabled**: the taken path must be materialized on the tape
  for reverse mode (JAX has no vjp through `lax.while_loop` either), so
  the loop runs as a python loop whose iterations are tape-recorded; the
  predicate read goes through Tensor.__bool__, which the two-phase tracer
  records as an in-graph GUARD — each taken branch compiles to its own
  entry and re-dispatches on the branch bit (the SOT analog).  The guard
  cache is bounded (see jit/tracer.py rediscovery cap).
"""
from __future__ import annotations

import jax

from ..core.tensor import Tensor
from ..core import state as _state

_UNMATCHED = object()


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    if (isinstance(pred, Tensor) and true_fn is not None
            and false_fn is not None and not _state.STATE.grad_enabled):
        out = _lax_cond(pred, true_fn, false_fn)
        if out is not _UNMATCHED:
            return out
    if bool(pred):
        return true_fn() if true_fn is not None else None
    return false_fn() if false_fn is not None else None


def _arm(fn, box):
    """Wrap a branch thunk as arrays->arrays for lax.cond; the output
    pytree structure is recorded in `box` (identical across arms when the
    lowering succeeds — lax.cond enforces matching avals)."""
    def f(_):
        with _state.no_grad():
            out = fn()
        leaves, tree = jax.tree.flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        if not leaves or not all(isinstance(x, Tensor) for x in leaves):
            raise TypeError("cond arms must return Tensor pytrees")
        box["tree"] = tree
        return tuple(x._data for x in leaves)
    return f


def _lax_cond(pred, true_fn, false_fn):
    """Lower to one lax.cond program; _UNMATCHED falls back to the python
    branch (mismatched arm structures, non-tensor outputs, arms that
    mutate outside state in ways tracing rejects)."""
    box = {}
    try:
        arrays = jax.lax.cond(
            pred._data.reshape(()).astype(jax.numpy.bool_),
            _arm(true_fn, box), _arm(false_fn, box), 0)
    except Exception:
        return _UNMATCHED
    leaves = [Tensor(a) for a in arrays]
    return jax.tree.unflatten(box["tree"], leaves)


def while_loop(cond_fn, body, loop_vars, is_test=False, name=None):
    vars_ = list(loop_vars)
    if (vars_ and all(isinstance(v, Tensor) for v in vars_)
            and not _state.STATE.grad_enabled):
        out = _lax_while(cond_fn, body, vars_)
        if out is not _UNMATCHED:
            return out
    # tape-recorded python loop (reverse mode needs the unrolled tape)
    while bool(cond_fn(*vars_)):
        out = body(*vars_)
        vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
    return vars_


def _lax_while(cond_fn, body, vars_):
    """Lower to one lax.while_loop program: a tensor trip count runs as a
    single compiled program (under to_static it composes into the step
    program with NO guard outputs — one entry regardless of trip count)."""
    def c(arrays):
        with _state.no_grad():
            r = cond_fn(*[Tensor(a) for a in arrays])
        r = r._data if isinstance(r, Tensor) else jax.numpy.asarray(r)
        return r.reshape(()).astype(jax.numpy.bool_)

    def b(arrays):
        with _state.no_grad():
            out = body(*[Tensor(a) for a in arrays])
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        if len(out) != len(arrays) or not all(
                isinstance(x, Tensor) for x in out):
            raise TypeError("body must return the loop_vars structure")
        return tuple(x._data.astype(a.dtype).reshape(a.shape)
                     for x, a in zip(out, arrays))

    try:
        res = jax.lax.while_loop(c, b, tuple(v._data for v in vars_))
    except Exception:
        return _UNMATCHED
    return [Tensor(a) for a in res]
