"""Multi-tenant LoRA (ISSUE 16): the training lane — wrap, freeze,
merge/unmerge, adapter-only save/load, compiled-train-step parity —
and the serving lane — batched multi-adapter decode through one
engine with per-slot bit-equality vs dedicated single-adapter
engines, LRU hot-load/eviction under pool pressure, compiled-tick
zero-fallback guarantees, prefix-tree adapter isolation, typed
registry errors, telemetry, and router adapter affinity."""
import os
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import Model, nn
from paddle_tpu.framework.checkpoint_manager import (read_manifest,
                                                     verify_checkpoint)
from paddle_tpu.models import GPTForCausalLM, gpt_config
from paddle_tpu.nn.lora import LoRALinear
from paddle_tpu.serving import (AdapterConfigError, Engine,
                                ReplicaConfig, ReplicaServer,
                                RouterConfig, SamplingParams,
                                ServingConfig, ServingRouter,
                                TickFallbackWarning,
                                UnknownAdapterError, serving_stats)
from paddle_tpu.serving.paged_kv import PrefixTree
from paddle_tpu.utils import flags as _flags


# ------------------------------------------------------------------
# training lane
# ------------------------------------------------------------------

class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc_in = nn.Linear(8, 16)
        self.act = nn.ReLU()
        self.fc_out = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc_out(self.act(self.fc_in(x)))


def _mlp(seed=0):
    paddle.seed(seed)
    return _MLP()


def _batches(steps=6, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.standard_normal((4, 8)).astype("float32"),
             rng.standard_normal((4, 4)).astype("float32"))
            for _ in range(steps)]


def test_attach_and_grad_mask():
    """attach_lora wraps the named projections; after
    mark_only_lora_trainable a training run moves ONLY the A/B
    factors — base weight and bias stay bitwise untouched."""
    net = _mlp()
    names = nn.attach_lora(net, rank=4)
    assert names == ["fc_in", "fc_out"]
    assert isinstance(net.fc_in, LoRALinear)
    nn.mark_only_lora_trainable(net)
    trainable = sorted(n for n, p in net.named_parameters()
                       if p.trainable)
    assert trainable == ["fc_in.lora_A", "fc_in.lora_B",
                         "fc_out.lora_A", "fc_out.lora_B"]
    frozen = {n: p.numpy().copy() for n, p in net.named_parameters()
              if not p.trainable}
    before = {n: p.numpy().copy()
              for n, p in net.named_parameters() if p.trainable}
    opt = paddle.optimizer.AdamW(
        0.05, parameters=[p for p in net.parameters() if p.trainable])
    for x, y in _batches():
        loss = ((net(paddle.to_tensor(x)) - paddle.to_tensor(y))
                ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    for n, p in net.named_parameters():
        if p.trainable:
            assert not np.array_equal(p.numpy(), before[n]), \
                f"{n} never trained"
        else:
            np.testing.assert_array_equal(p.numpy(), frozen[n],
                                          err_msg=n)


def test_merge_unmerge_bitwise():
    """merge() bakes W + A@B*scale into the base weight with the SAME
    expression the unmerged forward computes, so outputs are bitwise
    identical; unmerge() restores the exact pre-merge weight."""
    net = _mlp()
    nn.attach_lora(net, rank=4, alpha=8)
    rng = np.random.default_rng(1)
    for l in nn.lora_layers(net).values():
        l.lora_B.set_value(rng.standard_normal(
            l.lora_B.shape).astype(np.float32) * 0.1)
    x = paddle.to_tensor(
        rng.standard_normal((3, 8)).astype("float32"))
    y0 = net(x).numpy()
    w0 = net.fc_in.weight.numpy().copy()
    for l in nn.lora_layers(net).values():
        l.merge()
        assert l.merged
    np.testing.assert_array_equal(net(x).numpy(), y0)
    assert not np.array_equal(net.fc_in.weight.numpy(), w0)
    for l in nn.lora_layers(net).values():
        l.unmerge()
    np.testing.assert_array_equal(net.fc_in.weight.numpy(), w0)
    np.testing.assert_array_equal(net(x).numpy(), y0)


def test_save_load_adapter_roundtrip(tmp_path):
    """save_adapter writes ONLY the A/B factors (crc-manifested like
    CheckpointManager); load_adapter restores them byte-equal into a
    freshly wrapped model."""
    net = _mlp()
    nn.attach_lora(net, rank=4, alpha=16)
    rng = np.random.default_rng(2)
    for l in nn.lora_layers(net).values():
        l.lora_A.set_value(rng.standard_normal(
            l.lora_A.shape).astype(np.float32))
        l.lora_B.set_value(rng.standard_normal(
            l.lora_B.shape).astype(np.float32))
    d = str(tmp_path / "adapter")
    os.makedirs(d)
    nn.save_adapter(net, d)
    assert verify_checkpoint(d)
    meta = read_manifest(d)["meta"]
    assert meta["format"] == "lora_adapter"
    assert meta["layers"]["fc_in"]["rank"] == 4

    other = _mlp(seed=7)                      # different base weights
    nn.attach_lora(other, rank=4)
    nn.load_adapter(other, d)
    for name, l in nn.lora_layers(net).items():
        l2 = nn.lora_layers(other)[name]
        np.testing.assert_array_equal(l.lora_A.numpy(),
                                      l2.lora_A.numpy())
        np.testing.assert_array_equal(l.lora_B.numpy(),
                                      l2.lora_B.numpy())
        assert l2.alpha == 16 and l2.scaling == l.scaling

    # rank mismatch at load is a typed construction-time error
    third = _mlp()
    nn.attach_lora(third, rank=2)
    with pytest.raises(ValueError, match="rank"):
        nn.load_adapter(third, d)


def test_lora_construction_errors():
    with pytest.raises(TypeError, match="Linear"):
        LoRALinear(nn.LayerNorm(8))
    with pytest.raises(ValueError, match="rank"):
        LoRALinear(nn.Linear(4, 4), rank=0)
    with pytest.raises(ValueError, match="no Linear sublayers"):
        nn.attach_lora(_mlp(), targets=("does_not_exist",))
    with pytest.raises(ValueError, match="no LoRA"):
        nn.mark_only_lora_trainable(_mlp())


def _fit_lora(compiled, steps=6):
    paddle.set_flags({"FLAGS_compiled_train_step": compiled})
    net = _mlp()
    nn.attach_lora(net, rank=4)
    nn.mark_only_lora_trainable(net)
    opt = paddle.optimizer.AdamW(
        0.05, parameters=[p for p in net.parameters() if p.trainable])
    model = Model(net)
    model.prepare(optimizer=opt,
                  loss=lambda o, y: ((o - y) ** 2).mean())
    losses = []
    for x, y in _batches(steps):
        losses.append(np.float32(model.train_batch(
            paddle.to_tensor(x), paddle.to_tensor(y))[0]))
    base = {n: p.numpy().copy() for n, p in net.named_parameters()
            if not p.trainable}
    lora = {n: p.numpy().copy() for n, p in net.named_parameters()
            if p.trainable}
    return losses, base, lora, model


def test_compiled_train_step_frozen_base_matches_eager():
    """A LoRA-wrapped model rides the compiled train step unchanged:
    loss trajectory ulp-close to eager, the frozen base identical on
    both lanes, and only the adapters move."""
    saved = paddle.get_flags("FLAGS_compiled_train_step")
    try:
        le, base_e, lora_e, _ = _fit_lora(False)
        lc, base_c, lora_c, mc = _fit_lora(True)
    finally:
        paddle.set_flags(saved)
    cs = mc._compiled_step
    assert cs and cs is not False and cs.compiled, \
        cs and cs.fallback_reason
    for a, b in zip(le, lc):
        assert abs(a - b) <= 2e-6 * max(abs(a), 1e-12), (a, b)
    for n in base_e:
        np.testing.assert_array_equal(base_e[n], base_c[n], err_msg=n)
    ref = {n: p.numpy() for n, p in _mlp().named_parameters()}
    for n in base_e:                      # base never moved at all
        np.testing.assert_array_equal(base_e[n], ref[n], err_msg=n)
    for n in lora_e:
        np.testing.assert_allclose(lora_e[n], lora_c[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)


# ------------------------------------------------------------------
# serving lane
# ------------------------------------------------------------------

@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_config(
        "gpt2-124m", num_layers=2, hidden_size=64, num_heads=2,
        vocab_size=256, max_seq_len=64))
    m.eval()
    return m


@pytest.fixture(scope="module")
def specs(model):
    """Four heterogeneous adapter state dicts (different seeds) built
    on a throwaway wrapped copy that shares the served model's
    qualified projection names."""
    paddle.seed(0)
    tmp = GPTForCausalLM(gpt_config(
        "gpt2-124m", num_layers=2, hidden_size=64, num_heads=2,
        vocab_size=256, max_seq_len=64))
    tmp.eval()
    nn.attach_lora(tmp, rank=4)
    out = {}
    for i in range(4):
        rng = np.random.default_rng(100 + i)
        for l in nn.lora_layers(tmp).values():
            l.lora_A.set_value(rng.standard_normal(
                l.lora_A.shape).astype(np.float32) * 0.5)
            l.lora_B.set_value(rng.standard_normal(
                l.lora_B.shape).astype(np.float32) * 0.5)
        out[f"t{i}"] = nn.adapter_spec(tmp)
    return out


def _prompts(lens, seed=0, vocab=256):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (n,)).astype("int32") for n in lens]


def test_multi_adapter_bit_equal_vs_single_adapter_engines(model,
                                                           specs):
    """Heterogeneous adapters decoding in the SAME batched step: each
    per-slot output is bitwise identical to a dedicated single-adapter
    engine serving that adapter alone, and a base request riding the
    same program stays pure base — with zero compiled-tick fallbacks
    and no fallback warning."""
    prompts = _prompts([6, 9, 5], seed=3)
    ids = ["t0", "t1", "t2"]

    refs = {}
    for aid, p in zip(ids, prompts):
        eng = Engine(model, ServingConfig(
            num_slots=2, max_queue=4, max_adapters=1,
            adapter_rank_pool=4, adapters={aid: specs[aid]})).start()
        try:
            refs[aid] = eng.submit(
                p, max_new_tokens=5,
                adapter_id=aid).result(timeout=300).output_ids
        finally:
            eng.shutdown()
    base_eng = Engine(model, ServingConfig(
        num_slots=2, max_queue=4)).start()
    try:
        base_ref = base_eng.submit(
            prompts[0], max_new_tokens=5).result(timeout=300).output_ids
    finally:
        base_eng.shutdown()

    eng = Engine(model, ServingConfig(
        num_slots=2, max_queue=8, max_adapters=3, adapter_rank_pool=4,
        adapters=specs)).start()
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("error", TickFallbackWarning)
            futs = [eng.submit(p, max_new_tokens=5, adapter_id=aid)
                    for aid, p in zip(ids, prompts)]
            futs.append(eng.submit(prompts[0], max_new_tokens=5))
            outs = [f.result(timeout=300) for f in futs]
        snap = eng.stats()
    finally:
        eng.shutdown()
    for aid, o in zip(ids, outs):
        np.testing.assert_array_equal(o.output_ids, refs[aid],
                                      err_msg=aid)
    np.testing.assert_array_equal(outs[3].output_ids, base_ref)
    assert snap["tick_fallbacks"] == 0
    assert snap["tick_compiled_hits"] > 0
    assert snap["requests_routed_adapter"] == 3


def test_lru_evict_reload_zero_drops(model, specs):
    """Four tenants through a TWO-slot adapter pool: hot-loads and LRU
    evictions happen mid-run, eviction never touches an in-flight
    request, and every future completes (zero drops).  Re-submitting
    an evicted tenant reloads it bit-identically."""
    prompts = _prompts([5, 7, 6, 8], seed=4)
    eng = Engine(model, ServingConfig(
        num_slots=2, max_queue=16, max_adapters=2, adapter_rank_pool=4,
        adapters=specs)).start()
    try:
        futs = [eng.submit(p, max_new_tokens=4, adapter_id=f"t{i}")
                for i, p in enumerate(prompts)]
        outs = [f.result(timeout=300) for f in futs]
        first = [o.output_ids for o in outs]
        snap = eng.stats()
        assert snap["adapter_evictions"] >= 1
        assert snap["adapters_loaded"] >= 4
        # evicted tenants reload bit-identically
        futs = [eng.submit(p, max_new_tokens=4, adapter_id=f"t{i}")
                for i, p in enumerate(prompts)]
        again = [f.result(timeout=300).output_ids for f in futs]
        snap2 = eng.stats()
    finally:
        eng.shutdown()
    for a, b in zip(first, again):
        np.testing.assert_array_equal(a, b)
    assert all(o.finish_reason == "length" for o in outs)
    assert snap2["requests_completed"] == 8      # zero drops


def test_uncompiled_lane_matches_tick(model, specs):
    """FLAGS_compiled_tick off: the per-call scheduler applies the
    same per-slot delta — outputs bit-equal to the compiled lane."""
    prompts = _prompts([6, 8], seed=5)
    saved = _flags._FLAGS["FLAGS_compiled_tick"]

    def _run():
        eng = Engine(model, ServingConfig(
            num_slots=2, max_queue=4, max_adapters=2,
            adapter_rank_pool=4,
            adapters={k: specs[k] for k in ("t0", "t1")})).start()
        try:
            futs = [eng.submit(p, max_new_tokens=4,
                               adapter_id=aid)
                    for aid, p in zip(("t0", "t1"), prompts)]
            return [f.result(timeout=300).output_ids for f in futs]
        finally:
            eng.shutdown()

    try:
        _flags._FLAGS["FLAGS_compiled_tick"] = True
        compiled = _run()
        _flags._FLAGS["FLAGS_compiled_tick"] = False
        eager = _run()
    finally:
        _flags._FLAGS["FLAGS_compiled_tick"] = saved
    for a, b in zip(compiled, eager):
        np.testing.assert_array_equal(a, b)


def test_prefix_tree_adapter_isolation(model, specs):
    """The SAME prompt under two different adapters must never share
    KV through the prefix tree: scope-keyed entries keep each tenant's
    cache private, and outputs equal each adapter's no-cache
    reference."""
    prompt = _prompts([12], seed=6)[0]
    refs = {}
    for aid in ("t0", "t1"):
        eng = Engine(model, ServingConfig(
            num_slots=2, max_queue=4, max_adapters=1,
            adapter_rank_pool=4, page_size=4,
            enable_prefix_cache=False,
            adapters={aid: specs[aid]})).start()
        try:
            refs[aid] = eng.submit(
                prompt, max_new_tokens=4,
                adapter_id=aid).result(timeout=300).output_ids
        finally:
            eng.shutdown()
    eng = Engine(model, ServingConfig(
        num_slots=2, max_queue=8, max_adapters=2, adapter_rank_pool=4,
        page_size=4, enable_prefix_cache=True,
        adapters={k: specs[k] for k in ("t0", "t1")})).start()
    try:
        # serve t0 twice so its prefix is cached and REUSED, then t1
        # with the identical prompt: a cross-tenant hit would replay
        # t0's adapter KV into t1's decode
        eng.submit(prompt, max_new_tokens=4,
                   adapter_id="t0").result(timeout=300)
        hits0 = eng.stats()["prefix_cache_hits"]
        o0 = eng.submit(prompt, max_new_tokens=4,
                        adapter_id="t0").result(timeout=300)
        assert eng.stats()["prefix_cache_hits"] > hits0
        hits1 = eng.stats()["prefix_cache_hits"]
        o1 = eng.submit(prompt, max_new_tokens=4,
                        adapter_id="t1").result(timeout=300)
        assert eng.stats()["prefix_cache_hits"] == hits1
    finally:
        eng.shutdown()
    np.testing.assert_array_equal(o0.output_ids, refs["t0"])
    np.testing.assert_array_equal(o1.output_ids, refs["t1"])


def test_prefix_tree_scope_api():
    class _FakeCache:
        def make_shared(self, slot, i):
            return 100 + i

    tree = PrefixTree(page_size=4)
    prompt = np.arange(9).astype(np.int32)
    held = []
    assert tree.insert(prompt, _FakeCache(), 0, held, scope="a") == 2
    nodes_a, pages_a = tree.match(prompt, scope="a")
    nodes_b, pages_b = tree.match(prompt, scope="b")
    nodes_0, pages_0 = tree.match(prompt)
    assert pages_a == [100, 101]
    assert not pages_b and not pages_0
    tree.release(nodes_a)
    tree.release(held)


def test_unknown_adapter_fails_future_not_engine(model, specs):
    eng = Engine(model, ServingConfig(
        num_slots=2, max_queue=4, max_adapters=1, adapter_rank_pool=4,
        adapters={"t0": specs["t0"]})).start()
    try:
        p = _prompts([5], seed=8)[0]
        fut = eng.submit(p, max_new_tokens=3, adapter_id="nope")
        with pytest.raises(UnknownAdapterError, match="t0"):
            fut.result(timeout=30)
        # the scheduler survived: both a base and a known-adapter
        # request still complete
        o = eng.submit(p, max_new_tokens=3).result(timeout=300)
        assert o.output_ids.size == 3
        o = eng.submit(p, max_new_tokens=3,
                       adapter_id="t0").result(timeout=300)
        assert o.output_ids.size == 3
    finally:
        eng.shutdown()


def test_adapter_config_errors(model, specs):
    # rank above the preallocated pool rank
    with pytest.raises(AdapterConfigError, match="rank"):
        Engine(model, ServingConfig(
            num_slots=2, max_adapters=1, adapter_rank_pool=2,
            adapters={"t0": specs["t0"]}))
    # width mismatch vs the wrapped projection
    bad = {k: dict(v) for k, v in specs["t0"].items()}
    name = next(iter(bad))
    bad[name] = dict(bad[name], A=np.zeros((3, 4), np.float32))
    with pytest.raises(AdapterConfigError, match=name):
        Engine(model, ServingConfig(
            num_slots=2, max_adapters=1, adapter_rank_pool=4,
            adapters={"t0": bad}))
    # unknown projection name
    with pytest.raises(AdapterConfigError, match="does not have"):
        Engine(model, ServingConfig(
            num_slots=2, max_adapters=1, adapter_rank_pool=4,
            adapters={"t0": {"not.a.layer": specs["t0"][name]}}))
    # ServingConfig-level validation
    with pytest.raises(ValueError, match="max_adapters"):
        ServingConfig(num_slots=2, max_adapters=-1).validate()
    with pytest.raises(ValueError, match="adapters"):
        ServingConfig(num_slots=2,
                      adapters={"t0": specs["t0"]}).validate()
    with pytest.raises(ValueError, match="paged"):
        ServingConfig(num_slots=2, kv_layout="slots",
                      max_adapters=1).validate()


def test_adapter_telemetry_keys_and_exposition(model, specs):
    from tools.check_telemetry import (check_lora_exposition,
                                       parse_prometheus)
    eng = Engine(model, ServingConfig(
        num_slots=2, max_queue=4, max_adapters=1, adapter_rank_pool=4,
        adapters={k: specs[k] for k in ("t0", "t1")})).start()
    try:
        p = _prompts([5], seed=9)[0]
        for aid in ("t0", "t1"):
            eng.submit(p, max_new_tokens=3,
                       adapter_id=aid).result(timeout=300)
        snap = eng.stats()
    finally:
        eng.shutdown()
    assert snap["adapters_loaded"] >= 2
    assert snap["adapter_evictions"] >= 1
    assert snap["requests_routed_adapter"] == 2
    assert snap["adapter_load_ms_avg"] >= 0
    from paddle_tpu import observability as obs
    series, typed, errors = parse_prometheus(obs.render_prometheus())
    assert not errors
    assert check_lora_exposition(series, typed) == []
    assert ('adapter', 't0') in [
        (k, v) for labels, _ in
        series["serving_adapter_requests_routed_adapter"]
        for k, v in labels.items()]


def test_pallas_lora_delta_interpret_matches_xla():
    """The FLAGS_pallas_lora fused gather-matmul lane, run through the
    Pallas interpreter, is bitwise identical to the default XLA gather
    path; pool slot 0 is an exact identity."""
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.serving import adapters as ad
    rng = np.random.default_rng(0)
    ns, d_in, d_out, P, r = 4, 32, 48, 3, 8
    x = Tensor(rng.standard_normal((ns, 1, d_in)).astype(np.float32))
    y = Tensor(rng.standard_normal((ns, 1, d_out)).astype(np.float32))
    a = Tensor(rng.standard_normal((P, d_in, r)).astype(np.float32))
    b = Tensor(rng.standard_normal((P, r, d_out)).astype(np.float32))
    s = Tensor(np.array([0.0, 1.0, 0.5], np.float32))
    idx = Tensor(np.array([0, 1, 2, 1], np.int32))
    saved = _flags._FLAGS.get("FLAGS_pallas_lora", False)
    os.environ["PADDLE_TPU_PALLAS_INTERPRET"] = "1"
    try:
        _flags._FLAGS["FLAGS_pallas_lora"] = False
        ref = ad.lora_delta(y, x, a, b, s, idx).numpy()
        _flags._FLAGS["FLAGS_pallas_lora"] = True
        assert ad._use_pallas()
        out = ad.lora_delta(y, x, a, b, s, idx).numpy()
        zero = ad.lora_delta(y, x, a, b, s, Tensor(
            np.zeros(ns, np.int32))).numpy()
    finally:
        _flags._FLAGS["FLAGS_pallas_lora"] = saved
        del os.environ["PADDLE_TPU_PALLAS_INTERPRET"]
    np.testing.assert_array_equal(out, ref)
    np.testing.assert_array_equal(zero, y.numpy())


def test_router_adapter_affinity(model, specs):
    """Fleet lane: replicas gossip their hot-adapter set; once a
    tenant is hot on one replica, requests for it stick there even
    when session keys would scatter them across the ring."""
    from paddle_tpu.distributed.store import TCPStore
    scfg = ServingConfig(num_slots=2, max_queue=8, max_adapters=2,
                         adapter_rank_pool=4,
                         adapters={k: specs[k] for k in ("t0", "t1")})
    master = TCPStore(is_master=True)
    rcfg = ReplicaConfig(heartbeat_interval_s=0.15,
                         heartbeat_ttl_s=1.2).validate()
    reps, router = {}, None
    try:
        for name in ("rep-a", "rep-b"):
            reps[name] = ReplicaServer(
                name, model, TCPStore("127.0.0.1", master.port),
                scfg, rcfg)
        router = ServingRouter(
            TCPStore("127.0.0.1", master.port),
            RouterConfig(heartbeat_ttl_s=1.2,
                         poll_interval_s=0.1)).start()
        deadline = time.monotonic() + 30
        while len(router.ring.members) < 2:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        p = _prompts([6], seed=10)[0]
        first = router.submit(p, max_new_tokens=3, adapter_id="t0",
                              session_id="s0").result(timeout=300)
        hot = first.decoded_by
        # wait for the hot replica's gossip to advertise the adapter
        deadline = time.monotonic() + 10
        while True:
            with router._lock:
                view = router._replicas.get(hot)
            if view is not None and "t0" in view.adapters:
                break
            assert time.monotonic() < deadline, "gossip never updated"
            time.sleep(0.1)
        for i in range(3):                 # scattered session keys
            out = router.submit(
                p, max_new_tokens=3, adapter_id="t0",
                session_id=f"scatter-{i}").result(timeout=300)
            assert out.decoded_by == hot
    finally:
        if router is not None:
            router.close()
        for rep in reps.values():
            rep.close()
        master.close()
