#!/usr/bin/env python
"""Eager op-dispatch overhead microbench: tier-1 op cache on vs off.

Measures ops/sec over a representative eager op loop — a 3-layer MLP
forward chain (matmul, add, relu, ... , sum) over grad-tracked tensors,
plus the full fwd+bwd train-style step — with the tier-1 executable
cache (core/op_cache.py, FLAGS_eager_op_cache) enabled and disabled in
the same process.  The uncached mode pays JAX eager dispatch plus a
fresh jax.vjp trace per op; the cached mode replays one jitted
executable per op signature.

Prints ONE JSON line and (unless --no-write) records the full result at
benchmarks/EAGER_OVERHEAD.json next to the other bench artifacts.
`--smoke` shrinks the iteration counts for CI (tools/run_ci.sh), which
then validates the JSON schema via tools/check_bench_result.py.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

# ops per fwd() call: 3 x (matmul, add, relu) + sum
_OPS_PER_FWD = 10


def _build(paddle):
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((32, 64)).astype(np.float32),
                         stop_gradient=False)
    ws = [paddle.to_tensor(
        (rng.standard_normal((64, 64)) * 0.05).astype(np.float32),
        stop_gradient=False) for _ in range(3)]
    bs = [paddle.to_tensor(np.zeros(64, np.float32), stop_gradient=False)
          for _ in range(3)]
    F = paddle.nn.functional

    def fwd():
        h = x
        for w, b in zip(ws, bs):
            h = F.relu(paddle.add(paddle.matmul(h, w), b))
        return h.sum()

    def step():
        loss = fwd()
        loss.backward()
        for p in ws + bs + [x]:
            p.clear_grad()
        return loss

    return fwd, step


def _sentinel_overhead(paddle, jax, iters):
    """Eager-lane sentinel cost (ISSUE 10 satellite): a guarded train
    step (unit-scale GradScaler, found-inf skip armed — what the
    sentinel installs for non-AMP runs) vs the same step with the
    sentinel's detection feeds (fused grad-health dispatch + window
    bookkeeping + cadence fetch), INTERLEAVED so box drift cancels.
    The model is deliberately non-micro: the contract is about real
    train steps, where one fused health dispatch amortizes."""
    import time
    import numpy as np
    from paddle_tpu import nn
    from paddle_tpu.amp import GradScaler
    from paddle_tpu.framework.sentinel import TrainingSentinel

    def build(sentinel):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(256, 256), nn.Tanh(),
                            nn.Linear(256, 256), nn.Tanh(),
                            nn.Linear(256, 1))
        m = paddle.Model(net)
        m.prepare(optimizer=paddle.optimizer.AdamW(
            1e-3, parameters=net.parameters()), loss=nn.MSELoss())
        m._scaler = GradScaler(init_loss_scaling=1.0,
                               use_dynamic_loss_scaling=False,
                               always_check_found_inf=True)
        if sentinel:
            m._sentinel = TrainingSentinel(m)
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(64, 256))
                             .astype(np.float32))
        y = paddle.to_tensor(rng.normal(size=(64, 1))
                             .astype(np.float32))
        return m, x, y

    guarded, xg, yg = build(False)
    sent, xs, ys = build(True)
    for _ in range(3):
        guarded._train_step(xg, yg)
        sent._fi_step = 0
        sent._train_step(xs, ys)
        sent._sentinel.after_step(0, 0, 0, None, update=False)
    tg, ts = [], []
    for i in range(iters):
        t0 = time.perf_counter()
        loss, _ = guarded._train_step(xg, yg)
        jax.block_until_ready(loss._data_)
        tg.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        sent._fi_step = i
        loss, _ = sent._train_step(xs, ys)
        sent._sentinel.after_step(i, 0, i, loss, update=True)
        jax.block_until_ready(loss._data_)
        ts.append(time.perf_counter() - t0)
    g_p50 = float(np.median(tg) * 1e3)
    s_p50 = float(np.median(ts) * 1e3)
    return {
        "guarded_step_p50_ms": round(g_p50, 3),
        "sentinel_step_p50_ms": round(s_p50, 3),
        "overhead_vs_guarded": round(s_p50 / g_p50, 4),
        "anomalies": len(sent._sentinel.report()["anomalies"]),
    }


def _time_loop(fn, iters, jax):
    fn()                       # warm (compiles on the cached pass)
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out._data_)
    return time.perf_counter() - t0, float(out)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny iteration counts for CI")
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "EAGER_OVERHEAD.json"))
    ap.add_argument("--no-write", action="store_true")
    ap.add_argument("--baseline", default=None, metavar="JSON",
                    help="drift gate: fail when the measured cached/"
                         "uncached speedups fall below --drift-floor of "
                         "the recorded ones (speedup RATIOS are compared "
                         "— host-speed independent, unlike raw ops/sec)")
    ap.add_argument("--drift-floor", type=float, default=0.6)
    args = ap.parse_args()

    import jax
    import paddle_tpu as paddle
    from paddle_tpu.core import op_cache
    from paddle_tpu.utils import cache_stats

    iters = args.iters or (40 if args.smoke else 200)
    paddle.seed(0)
    fwd, step = _build(paddle)

    results = {}
    losses = {}
    stats = None
    for mode, label in ((True, "cached"), (False, "uncached")):
        op_cache.clear()
        paddle.set_flags({"FLAGS_eager_op_cache": mode})
        dt_fwd, _ = _time_loop(fwd, iters, jax)
        dt_step, loss = _time_loop(step, max(iters // 4, 5), jax)
        results[label] = {
            "fwd_ops_per_sec": round(iters * _OPS_PER_FWD / dt_fwd, 1),
            "step_ops_per_sec": round(
                max(iters // 4, 5) * _OPS_PER_FWD / dt_step, 1),
        }
        losses[label] = loss
        if mode:
            stats = cache_stats()   # snapshot before clear() wipes tier 1
    paddle.set_flags({"FLAGS_eager_op_cache": True})

    if not np.allclose(losses["cached"], losses["uncached"],
                       rtol=1e-5, atol=1e-6):
        print(f"PARITY FAILURE: cached loss {losses['cached']} != "
              f"uncached {losses['uncached']}", file=sys.stderr)
        return 1

    speedup_fwd = (results["cached"]["fwd_ops_per_sec"]
                   / results["uncached"]["fwd_ops_per_sec"])
    speedup_step = (results["cached"]["step_ops_per_sec"]
                    / results["uncached"]["step_ops_per_sec"])
    rec = {
        "metric": "eager_op_dispatch_ops_per_sec",
        "value": results["cached"]["fwd_ops_per_sec"],
        "unit": "ops/sec",
        "speedup_vs_uncached": round(speedup_fwd, 3),
        "step_speedup_vs_uncached": round(speedup_step, 3),
        "cached": results["cached"],
        "uncached": results["uncached"],
        "loss": round(losses["cached"], 6),
        "iters": iters,
        "ops_per_fwd": _OPS_PER_FWD,
        "smoke": bool(args.smoke),
        "platform": jax.devices()[0].platform,
        "tier1": {k: stats["tier1"][k]
                  for k in ("hits", "misses", "evictions", "bypasses",
                            "entries", "bytes")},
        "sentinel": _sentinel_overhead(paddle, jax,
                                       max(iters // 2, 16)),
    }
    if not args.no_write:
        try:
            existing = {}
            if os.path.exists(args.out):
                with open(args.out) as f:
                    existing = json.load(f)
            if args.smoke and existing and not existing.get("smoke"):
                # never clobber a recorded full-mode baseline with a CI
                # smoke run: refresh only its smoke_ref section
                existing["smoke_ref"] = {
                    "speedup_vs_uncached": rec["speedup_vs_uncached"],
                    "step_speedup_vs_uncached":
                        rec["step_speedup_vs_uncached"],
                }
                rec_out = existing
            else:
                if existing.get("smoke_ref"):
                    rec["smoke_ref"] = existing["smoke_ref"]
                rec_out = rec
            with open(args.out, "w") as f:
                json.dump(rec_out, f, indent=1)
        except (OSError, ValueError) as e:
            print(f"[eager_overhead] could not write {args.out}: {e}",
                  file=sys.stderr)
    print(json.dumps({k: rec[k] for k in
                      ("metric", "value", "unit", "speedup_vs_uncached",
                       "step_speedup_vs_uncached", "smoke")}))

    if args.baseline:
        # drift gate (ISSUE 8 satellite): the op-dispatch hot path drifted
        # ~0.9x across PRs 2-5 without any gate noticing.  Raw ops/sec
        # depends on the host, so the gate compares the cached/uncached
        # SPEEDUP ratios, which cancel machine speed: a real hot-path
        # regression (instrumentation on the per-op path) shrinks the
        # cached advantage no matter how fast the box is.
        try:
            base = json.load(open(args.baseline))
        except (OSError, ValueError) as e:
            print(f"DRIFT GATE ERROR: cannot read {args.baseline}: {e}",
                  file=sys.stderr)
            return 1
        # iteration counts shape the ratios (short smoke loops amortize
        # warmup differently), so a smoke run gates against the recorded
        # smoke_ref section, a full run against the top-level numbers
        if bool(base.get("smoke")) != rec["smoke"]:
            base = base.get("smoke_ref") or {}
            if not base:
                print("[eager_overhead] drift gate SKIPPED: baseline has "
                      "no smoke_ref section for this mode",
                      file=sys.stderr)
                return 0
        failures = []
        for key in ("speedup_vs_uncached", "step_speedup_vs_uncached"):
            recorded = float(base.get(key, 0) or 0)
            measured = float(rec[key])
            if recorded > 1.0 and measured < args.drift_floor * recorded:
                failures.append(
                    f"  {key}: measured {measured:.2f}x < "
                    f"{args.drift_floor:.2f} x recorded {recorded:.2f}x")
        if failures:
            print("EAGER-OVERHEAD DRIFT GATE FAILED (vs "
                  f"{args.baseline}):", file=sys.stderr)
            print("\n".join(failures), file=sys.stderr)
            print("the eager per-op hot path regressed — profile "
                  "core/dispatch.apply_op + op_cache.tier1_execute for "
                  "new per-op work before re-recording the baseline",
                  file=sys.stderr)
            return 1
        print(f"[eager_overhead] drift gate OK vs {args.baseline} "
              f"(floor {args.drift_floor})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
