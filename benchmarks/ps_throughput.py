"""Measure the PS transport: sparse pull/push rows/sec over real
processes.

docs/PARITY.md calls the multiprocessing.connection transport "a
throughput ceiling, not a capability gap" — this records the ceiling
(VERDICT r03 weak #8).  The server runs in its own process, so every
request crosses a real authenticated TCP connection like a deployment
would; nothing is measured in-process.

Writes benchmarks/PS_THROUGHPUT.json and prints one JSON line.
Reference analog: brpc_ps_client throughput (ps/service/brpc_ps_client).
"""
from __future__ import annotations

import json
import multiprocessing as mp
import os
import time

import numpy as np


DIM = 64
BATCH = 4096
LOOPS = 20


def _server_main(q):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    from paddle_tpu.distributed.ps import PSServer
    srv = PSServer()
    srv.add_sparse_table(0, DIM, lr=0.1)
    srv.start()
    q.put(srv.address)
    srv.run()


def bench_tables():
    """Storage-tier capacity benchmark (VERDICT r04 item 7): RAM
    SparseTable vs SSDSparseTable (4096-row hot cache + WAL + record log)
    at working sets far beyond the cache — rows/sec for pull and push,
    plus the on-disk footprint.  Reference analog: memory_sparse_table
    vs ssd_sparse_table capacity trade (ps/table/ssd_sparse_table.h)."""
    import tempfile
    from paddle_tpu.distributed.ps import SparseTable, SSDSparseTable

    rng = np.random.default_rng(1)
    out = {}
    for n_rows in (50_000, 200_000):
        for kind in ("ram", "ssd"):
            if kind == "ram":
                t = SparseTable(DIM, lr=0.1)
            else:
                d = tempfile.mkdtemp(prefix="ps_tier_bench_")
                t = SSDSparseTable(DIM, lr=0.1, cache_rows=4096,
                                   path=os.path.join(d, "t.bin"))
            # populate the working set (off the clock)
            for lo in range(0, n_rows, BATCH):
                t.pull(list(range(lo, min(lo + BATCH, n_rows))))
            loops = 6
            batches = [rng.integers(0, n_rows, BATCH).tolist()
                       for _ in range(loops)]
            grads = rng.standard_normal((BATCH, DIM)).astype(np.float32)
            t0 = time.perf_counter()
            for ids in batches:
                t.pull(ids)
            pull_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            for ids in batches:
                t.push(ids, grads)
            push_s = time.perf_counter() - t0
            rec = {
                "pull_rows_per_sec": round(BATCH * loops / pull_s),
                "push_rows_per_sec": round(BATCH * loops / push_s),
            }
            if kind == "ssd":
                t.flush()
                rec["log_bytes"] = os.path.getsize(t.path)
                rec["cache_rows"] = t.cache_rows
                rec["cold_rows"] = t.num_cold_rows
                t.close()
            out[f"{kind}_{n_rows}"] = rec
    return out


def main():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    from paddle_tpu.distributed.ps import PSClient

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    proc = ctx.Process(target=_server_main, args=(q,), daemon=True)
    proc.start()
    addr = q.get(timeout=60)
    client = PSClient(addr)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 1_000_000, BATCH).tolist()
    grads = rng.standard_normal((BATCH, DIM)).astype(np.float32)

    client.pull_sparse(0, ids)          # warm: row creation off the clock
    t0 = time.perf_counter()
    for _ in range(LOOPS):
        client.pull_sparse(0, ids)
    pull_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(LOOPS):
        client.push_sparse(0, ids, grads)
    push_s = time.perf_counter() - t0

    client.stop_server()
    client.close()
    proc.join(timeout=10)

    rec = {
        "transport": "multiprocessing.connection (authenticated TCP)",
        "dim": DIM, "batch": BATCH, "loops": LOOPS,
        "pull_rows_per_sec": round(BATCH * LOOPS / pull_s),
        "push_rows_per_sec": round(BATCH * LOOPS / push_s),
        "pull_MBps": round(BATCH * LOOPS * DIM * 4 / pull_s / 1e6, 1),
        "push_MBps": round(BATCH * LOOPS * DIM * 4 / push_s / 1e6, 1),
        "tiers": bench_tables(),
    }
    out = os.path.join(os.path.dirname(__file__), "PS_THROUGHPUT.json")
    with open(out, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
