"""Compiled train step: the whole optimizer step as ONE XLA program.

Reference capability: the reference's static-graph train executor runs a
whole step (forward, backward, gradient communication, optimizer update)
as one `InterpreterCore` program (reference:
python/paddle/distributed/passes/auto_parallel_gradient_merge.py +
new_executor/interpretercore.cc), which is how it reaches its published
MFU numbers; op-by-op eager dispatch cannot overlap collectives or fuse
the update.

TPU-native realization (docs/TRAIN_STEP.md): :class:`CompiledTrainStep`
extracts the parameter / optimizer-state / gradient pytrees from a live
eager model, lowers the step body — forward via the op-dispatch funnel,
tape backward, AMP unscale + in-program found-inf reduction, global-norm
clip, the optimizer's ``_fused_update`` — as a pure function of those
pytrees, and compiles it with ``jax.jit`` donating the parameter,
gradient and optimizer-state buffers so XLA updates them in place.  When
a PURE data-parallel mesh spans more than one local device the body runs
under ``shard_map`` over the ``NamedSharding`` mesh
(``distributed/mesh.py``): the batch is sharded over ``dp`` and gradient
reduction happens as an in-program ``psum``/``pmean`` that XLA can
overlap with the rest of the backward, instead of the eager path's
post-hoc per-tensor host collectives (``hapi.Model._sync_grads``).

Hybrid dp×mp meshes (ISSUE 12) compile as ONE GSPMD program instead:
``jax.jit`` over per-leaf ``NamedSharding`` trees derived from each
parameter's declared partition (the ``mp_placement`` annotations the TP
layers carry, committed by ``fleet.distributed_model``), gradients and
optimizer moments mirroring their parameter's sharding, and the batch
sharded over ``dp``.  The model's own ``shard_constraint`` calls then
direct XLA to insert the exact mp collectives (row-parallel partial-sum
all-reduce, vocab-parallel softmax reductions), while the dp gradient
all-reduce falls out of differentiating the global-batch loss — all
inside one program, so XLA's scheduler overlaps the dp grad reduction
with mp compute instead of serializing them at a host boundary.  Mesh
axes the one-program step cannot host (``pp`` — the 1F1B schedule is a
python micro-batch loop; ``sharding`` — ZeRO accumulators rebind per
step; ``sep``) fall back to eager with a :class:`MeshFallbackWarning`
naming the axis.

Lifecycle (two-phase, mirroring ``jit/tracer.py``):

1. **Call 1 — eager + discovery.**  The step runs through the caller's
   byte-identical eager path (a REAL step, so lazily-initialized
   optimizer state and gradients exist), then one no-grad forward under
   a discovery tracer records every pre-existing tensor the forward
   reads (parameters, buffers, masks); its side effects (RNG counter,
   buffer writes) are rolled back.
2. **Call 2 — bind + compile.**  A pure wrapper installs JAX tracers
   into the captured tensors' data slots, replays the step body, and
   collects loss + every mutated value as program outputs; ``jax.jit``
   compiles it with ``donate_argnums`` over params/grads/state.  All
   later calls execute the one cached executable per input signature.

Eager stays the fallback and is byte-for-byte today's path: flag off
(``FLAGS_compiled_train_step``), layer/tensor hooks installed, active
tracers or ``saved_tensors_hooks``, data-dependent host reads in the
forward, optimizers without a fused update (LBFGS), ZeRO-sharded
accumulators, or a launched multi-process world whose backend cannot
run cross-process XLA programs.  A trace failure at any point warns
once and permanently falls back — training never dies on the compiler.
"""
from __future__ import annotations

import warnings

import numpy as np
import jax
import jax.numpy as jnp

from ..core import state as _state
from ..core.tensor import Tensor
from ..utils.flags import flag as _flag
from .capture import BindTracer, Installed, TraceEscape, run_discovery


_DONATED_FAILURE_MSG = (
    "compiled train step failed after buffer donation; parameters/"
    "optimizer state backing this step are invalid — reload them from a "
    "checkpoint, or set FLAGS_jit_donate_buffers=False to trade memory "
    "for failure recovery")


class MeshFallbackWarning(UserWarning):
    """Warned once when the active ``ProcessMesh`` carries an axis the
    one-program train step cannot host (pipeline, ZeRO sharding,
    context parallel); the message names the axis that forced the
    eager fallback."""


class _MeshEscape(TraceEscape):
    """A mesh axis forced the eager fallback — warn with the typed
    :class:`MeshFallbackWarning` so callers can filter on it."""

    category = MeshFallbackWarning


# the two-phase capture/replay machinery lived here through PR 12; it is
# shared with the serving scheduler's compiled tick now (ISSUE 13) and
# moved to framework/capture.py — these aliases keep the historical
# import surface intact
_StepBindTracer = BindTracer
_Installed = Installed


def _resolve_mesh(mesh=None):
    """``(mesh, blocked_axis)`` — the mesh this step compiles over, or
    the axis name that forces the eager fallback.

    Precedence: explicit argument > the framework's active/default
    ``ProcessMesh`` (``distributed.mesh``) > the ``PADDLE_COMPILED_DP``
    env var (dp over the first N local devices).  There is deliberately
    NO implicit all-local-devices default: silently resharding the
    batch would change trajectories whenever CI forces a multi-device
    host platform.

    A pure-dp mesh runs under ``shard_map`` (bit-identical to the PR 8
    lane); a mesh with an ``mp`` axis > 1 runs as one GSPMD program
    over NamedSharding trees.  Any other axis of size > 1 (``pp``: the
    1F1B schedule is a python micro-batch loop, not one program;
    ``sharding``: ZeRO accumulators rebind per step; ``sep``) blocks
    compilation — ``blocked_axis`` names it for the typed warning."""
    import os
    from ..distributed import mesh as _mesh_mod
    if mesh is None:
        mesh = _mesh_mod.get_mesh()
    if mesh is None:
        n = int(os.environ.get("PADDLE_COMPILED_DP", "0") or 0)
        if n > 1:
            mesh = _mesh_mod.init_mesh([n], ["dp"])
    if mesh is None:
        return None, None
    for name in mesh.dim_names:
        if name not in ("dp", "mp") and mesh.get_dim_size(name) != 1:
            return None, name
    dp = mesh.get_dim_size("dp") if "dp" in mesh.dim_names else 1
    mp = mesh.get_dim_size("mp") if "mp" in mesh.dim_names else 1
    if dp <= 1 and mp <= 1:
        return None, None
    if mp > 1 and not _flag("FLAGS_compiled_mp_step", True):
        return None, "mp"
    return mesh, None


class CompiledTrainStep:
    """One donated-buffer XLA program per (input signature, phase).

    ``forward_fn(x, y) -> loss Tensor`` is the only user code replayed
    inside the program (wrap autocast inside it); everything after the
    loss — backward, loss scaling, found-inf, dp reduction, clip, the
    fused optimizer update — is the framework-owned step tail.

    ``eager_step(x, y, update) -> loss Tensor`` supplies the exact eager
    semantics used for the warmup call and every fallback
    (``update=False`` marks a gradient-accumulation micro-step: backward
    only, no optimizer update / clear).  hapi passes its historical
    ``Model._train_step`` so fallbacks stay byte-identical; standalone
    callers get a default with the same structure.
    """

    def __init__(self, forward_fn, optimizer, *, scaler=None, network=None,
                 accumulate_grad_batches=1, mesh=None, eager_step=None,
                 sentinel=False):
        self._forward = forward_fn
        self._opt = optimizer
        self._scaler = scaler
        self._network = network
        self._accum = max(int(accumulate_grad_batches or 1), 1)
        self._mesh_arg = mesh
        self._eager = eager_step or self._default_eager_step
        # training-sentinel mode (framework/sentinel.py): the full-step
        # program additionally emits a [grad_norm_sq, skipped] health
        # vector as a device output — detection signals ride the
        # program, the hot path gains NO host syncs.  Off: the program
        # is bit-identical to a sentinel-less build.
        self._sentinel = bool(sentinel)
        self._health_every = max(
            int(_flag("FLAGS_sentinel_check_every", 8) or 1), 1)
        self.last_health = None
        self._micro = 0               # position within the accum window
        self._calls = 0
        self._fallback_reason = None
        self._warned = False
        # build products (populated by discovery / first bind)
        self._built = False
        self._mesh = None
        self._dp = 1
        self._mp = 1
        self._shard_map = False     # pure-dp shard_map lane (PR 8)
        self._gspmd = False         # hybrid dp×mp GSPMD lane (ISSUE 12)
        self._psh = None            # per-param NamedSharding tree
        self._csh = None            # per-capture NamedSharding tree
        self._rep = None            # replicated NamedSharding on the mesh
        self._caps = []               # non-param captured tensors
        self._params = []             # params receiving grads (update set)
        self._idxs = []               # their positions in the optimizer list
        self._lr_scales = ()
        self._wd_mask = ()
        self._state_names = ()
        self._mut_caps = []           # forward-mutated captures (buffers)
        self._jit_full = None
        self._jit_micro = None
        self._donating = None
        self._scaler_vec = None       # device [scale, good, bad] fp32
        self.check_static_eligibility()

    # ------------------------------------------------------------------
    # public surface
    # ------------------------------------------------------------------

    @property
    def compiled(self):
        return self._built and self._fallback_reason is None

    @property
    def fallback_reason(self):
        return self._fallback_reason

    def __call__(self, x, y=None, update=None):
        if update is None:
            # standalone callers: position within the accumulation window
            update = (self._micro + 1) >= self._accum
        self._calls += 1
        from ..utils import monitor as _monitor
        if self._fallback_reason is not None or not self._eligible_now():
            _monitor.incr("jit.compiled_step_fallback")
            loss = self._run_eager(x, y, update)
        elif self._calls == 1:
            loss = self._run_eager(x, y, update)   # real warmup step
            try:
                self._discover(x, y)
            except TraceEscape as e:
                self._set_fallback(str(e), category=e.category)
            except Exception as e:  # noqa: BLE001 — any failure → eager
                self._set_fallback(
                    f"discovery failed: {type(e).__name__}: {e}")
        else:
            try:
                loss = self._run_compiled(x, y, update)
                _monitor.incr("jit.compiled_step_hit")
            except TraceEscape as e:
                self._set_fallback(str(e), category=e.category)
                loss = self._run_eager(x, y, update)
            except Exception as e:  # noqa: BLE001
                if self._donation_burned():
                    raise RuntimeError(_DONATED_FAILURE_MSG) from e
                self._set_fallback(f"{type(e).__name__}: {e}")
                loss = self._run_eager(x, y, update)
        self._micro = 0 if update else self._micro + 1
        return loss

    step = __call__

    def hlo_fingerprint(self, x, y=None):
        """sha256 (first 16 hex) of the StableHLO of the full-update
        program for this batch signature — the auditable program identity
        benchmark records carry.  None until compiled (or on lowering
        failure)."""
        import hashlib
        if self._jit_full is None:
            return None
        try:
            args = self._gather_args(x, y)
            text = self._jit_full.lower(*args).as_text()
        except Exception:
            return None
        finally:
            # _gather_args advanced the RNG counter; a fingerprint read
            # must not perturb the training stream
            _state.STATE.rng_counter -= 1
        return hashlib.sha256(text.encode()).hexdigest()[:16]

    def sync_scaler(self):
        """Materialize the device-held loss-scaling state back into the
        python ``GradScaler`` (scale / good / bad counters)."""
        if self._scaler is None or self._scaler_vec is None:
            return
        vec = np.asarray(self._scaler_vec)
        self._scaler._scale = float(vec[0])
        self._scaler._good_steps = int(vec[1])
        self._scaler._bad_steps = int(vec[2])

    # ------------------------------------------------------------------
    # eligibility & fallback
    # ------------------------------------------------------------------

    def _set_fallback(self, reason, category=UserWarning):
        self.sync_scaler()
        self._scaler_vec = None
        self._fallback_reason = reason
        if not self._warned:
            self._warned = True
            warnings.warn(
                f"compiled train step disabled ({reason}); running the "
                "eager step for this model", category)

    def check_static_eligibility(self):
        """One-time structural checks; returns None when eligible, else
        the (latched) fallback reason."""
        opt = self._opt
        from ..optimizer.optimizer import Optimizer
        if opt is None:
            self._fallback_reason = "no optimizer"
        elif type(opt).step is not Optimizer.step:
            self._set_fallback(
                f"{type(opt).__name__}.step is overridden (closure-style "
                "optimizers run eagerly)")
        elif type(opt)._fused_update is Optimizer._fused_update:
            self._set_fallback(f"{type(opt).__name__} has no fused update")
        elif getattr(opt, "_accumulator_commit_hook", None) is not None:
            self._set_fallback("ZeRO-sharded accumulators (fleet.sharding)")
        else:
            world = self._world_blocker()
            if world:
                self._set_fallback(world)
        return self._fallback_reason

    def _world_blocker(self):
        """Launched multi-process worlds ride eager unless the backend
        can genuinely run one cross-process XLA program (TPU pods with a
        global mesh); the CPU host-collective lane cannot."""
        try:
            nprocs = jax.process_count()
        except Exception:
            nprocs = 1
        if nprocs <= 1:
            return None
        plat = jax.devices()[0].platform
        if plat not in ("tpu", "axon"):
            return (f"{nprocs}-process world on {plat!r}: backend cannot "
                    "run cross-process XLA programs (host-collective "
                    "eager lane)")
        return None

    def _eligible_now(self):
        """Cheap per-call checks for state that may change mid-run."""
        if not _flag("FLAGS_compiled_train_step", True):
            return False
        if _state.STATE.tracer is not None:
            return False     # someone is tracing us: compose eagerly
        if getattr(_state.STATE, "saved_tensor_hooks", None) is not None:
            return False
        if self._network is not None:
            for layer in self._network.sublayers(include_self=True):
                if layer._forward_pre_hooks or layer._forward_post_hooks:
                    self._set_fallback("layer forward hooks installed")
                    return False
        for p in self._opt._parameter_list:
            if p._hooks:
                self._set_fallback("tensor gradient hooks installed")
                return False
        return True

    def _donation_burned(self):
        for p in self._params:
            if getattr(p._data_, "is_deleted", lambda: False)():
                return True
        return False

    # ------------------------------------------------------------------
    # eager lane
    # ------------------------------------------------------------------

    def _run_eager(self, x, y, update):
        # a mid-run fallback (ragged batch, flag flip) must not read a
        # stale host scaler: pull the device-held state down first
        if self._scaler_vec is not None:
            self.sync_scaler()
            self._scaler_vec = None
        self.last_health = None   # stale compiled health must not be
        return self._eager(x, y, update)  # mistaken for this step's

    def _default_eager_step(self, x, y, update):
        """Standalone eager semantics (scaler/clip-aware, single rank)."""
        loss = self._forward(x, y)
        bwd = loss
        if self._scaler is not None:
            bwd = self._scaler.scale(bwd)
        if self._accum > 1:
            bwd = bwd * (1.0 / self._accum)
        bwd.backward()
        if update:
            if self._scaler is not None:
                self._scaler.step(self._opt)   # unscale→found-inf→update
            else:
                self._opt.step()
            self._opt.clear_grad()
        return loss

    # ------------------------------------------------------------------
    # phase 1: discovery (side-effect-free capture of forward reads)
    # ------------------------------------------------------------------

    def _discover(self, x, y):
        opt = self._opt
        opt._ensure_state()
        # the shared capture core runs the forward once eagerly under a
        # discovery tracer (side effects — batchnorm running stats,
        # write-only counters, the RNG counter — rolled back to the
        # post-warmup state) and raises TraceEscape on any host read
        disc = run_discovery(lambda: self._forward(x, y))

        # classify captures: the optimizer's update set vs const captures
        grads_present = {id(p) for p in opt._parameter_list
                         if p.grad is not None and not p.stop_gradient}
        self._idxs = [i for i, p in enumerate(opt._parameter_list)
                      if id(p) in grads_present]
        self._params = [opt._parameter_list[i] for i in self._idxs]
        if not self._params:
            raise TraceEscape("no trainable parameters received gradients")
        # the batch tensors are per-call program INPUTS, not captures —
        # holding them in _caps would feed call 1's batch forever
        batch_ids = {id(t) for t in (x, y) if isinstance(t, Tensor)}
        param_ids = {id(p) for p in self._params}
        self._caps = [t for t in disc.capture_list
                      if id(t) not in param_ids and id(t) not in batch_ids]
        # whether the forward draws framework RNG (dropout): only then is
        # a fresh key fed per call — feeding one unconditionally would
        # advance the global RNG counter the eager lane does not touch,
        # desynchronizing everything else that draws from it (shuffling)
        self._uses_rng = disc.uses_rng
        self._lr_scales = tuple(
            p.optimize_attr.get("learning_rate", 1.0) for p in self._params)
        self._wd_mask = tuple(opt._wd_applies(p) for p in self._params)
        self._state_names = tuple(opt._state)
        self._mesh, blocked = _resolve_mesh(self._mesh_arg)
        if blocked == "mp":      # only blocked when the flag is off
            raise _MeshEscape("mesh axis 'mp' present but "
                              "FLAGS_compiled_mp_step is off")
        if blocked is not None:
            raise _MeshEscape(
                f"mesh axis '{blocked}' cannot run inside one compiled "
                "program (pipeline schedules, ZeRO resharding and "
                "context parallel keep their own lanes)")
        names = self._mesh.dim_names if self._mesh is not None else ()
        self._dp = self._mesh.get_dim_size("dp") if "dp" in names else 1
        self._mp = self._mesh.get_dim_size("mp") if "mp" in names else 1
        self._shard_map = self._mesh is not None and self._mp == 1
        self._gspmd = self._mesh is not None and self._mp > 1
        if self._gspmd:
            self._build_sharding_trees()
        self._built = True

    # ------------------------------------------------------------------
    # hybrid dp×mp: NamedSharding trees + state realignment
    # ------------------------------------------------------------------

    def _derived_sharding(self, t):
        """The NamedSharding a captured tensor carries in the hybrid
        program: its committed placements when they were declared on a
        mesh with this step's axes (the TP layers' ``mp_placement``
        annotations committed by ``fleet.distributed_model``), else its
        current NamedSharding when already on this mesh, else
        replicated."""
        from jax.sharding import NamedSharding
        from ..distributed.placement import named_sharding
        arr = t._data_
        placements = getattr(t, "placements", None)
        pmesh = getattr(t, "process_mesh", None)
        if placements and pmesh is not None and \
                tuple(pmesh.dim_names) == tuple(self._mesh.dim_names):
            return named_sharding(self._mesh, placements,
                                  len(arr.shape))
        sh = getattr(arr, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.mesh == self._mesh.jax_mesh:
            return sh
        return self._rep

    def _build_sharding_trees(self):
        """Per-axis NamedSharding trees for params / grads / optimizer
        state / captures, derived once from the model's declared
        partition.  Gradients and moments mirror their parameter's
        sharding (``zeros_like`` inheritance made explicit)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        jm = self._mesh.jax_mesh
        self._rep = NamedSharding(jm, P())
        self._psh = tuple(self._derived_sharding(p) for p in self._params)
        self._csh = tuple(self._derived_sharding(t) for t in self._caps)

    def _align_hybrid(self):
        """Realign committed state onto the derived sharding tree.  The
        warmup eager step leaves gradients / moments / buffers committed
        with whatever sharding GSPMD propagation gave them; ``jax.jit``
        raises on committed inputs whose sharding differs from
        ``in_shardings`` (and donation would be unusable).  After the
        first compiled call the program outputs already carry these
        shardings, so this degenerates to one sharding compare per
        leaf."""
        opt = self._opt
        for k, p in enumerate(self._params):
            want = self._psh[k]
            for t in (p, p.grad):
                if t is not None and t._data_.sharding != want:
                    t._data_ = jax.device_put(t._data_, want)
            for name in self._state_names:
                v = opt._state[name][self._idxs[k]]
                if v is None:
                    continue
                w = want if v._data_.shape == p._data_.shape else self._rep
                if v._data_.sharding != w:
                    v._data_ = jax.device_put(v._data_, w)
        for t, w in zip(self._caps, self._csh):
            if t._data_.sharding != w:
                t._data_ = jax.device_put(t._data_, w)
        st = opt._step_tensor
        if st._data_.sharding != self._rep:
            st._data_ = jax.device_put(st._data_, self._rep)

    def _hybrid_shardings(self, args):
        """The full in_shardings pytree mirroring ``_gather_args``'s
        ``(x, y, params, grads, caps, states, step, svec, lr, key,
        hmark)`` — batch over dp, params/grads/moments per the derived
        trees, scalars replicated."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        rep = self._rep
        bsh = NamedSharding(self._mesh.jax_mesh, P("dp")) \
            if self._dp > 1 else rep
        _xa, ya, _params, _grads, _caps, states, _step, svec, _lr, \
            _key, _hmark = args
        ssh = {name: [None if a is None else
                      (self._psh[k] if getattr(a, "shape", None)
                       == self._params[k]._data_.shape else rep)
                      for k, a in enumerate(vals)]
               for name, vals in states.items()}
        return (bsh, None if ya is None else bsh, self._psh, self._psh,
                self._csh, ssh, rep, None if svec is None else rep, rep,
                rep, rep)

    # ------------------------------------------------------------------
    # phase 2: the pure step body (replayed under jax.jit tracing)
    # ------------------------------------------------------------------

    def _traced_body(self, update, x, y, param_arrs, grad_arrs, cap_arrs,
                     states, step_arr, svec, lr, key, hmark=None):
        """Replay the step over tracer arrays; returns array pytrees.
        Runs only while jax traces — per-step python cost is zero after
        compilation."""
        from ..core.state import no_grad

        tracer = BindTracer(key, host_scalars=(lr,))
        installs = (list(zip(self._params, param_arrs))
                    + list(zip(self._caps, cap_arrs)))
        grad_seed = [(p.grad, g) for p, g in zip(self._params, grad_arrs)]
        _state.STATE.tracer = tracer
        try:
            with Installed(installs), Installed(grad_seed):
                # the forward expects framework Tensors; wrap the traced
                # batch arrays (created under the tracer, so on_read never
                # mistakes them for uncaptured state)
                x_t = Tensor(x)
                y_t = Tensor(y) if y is not None else None
                loss_t = self._forward(x_t, y_t)
                bwd_t = loss_t
                if svec is not None:
                    # scale is device state: multiply by the traced value
                    bwd_t = bwd_t * Tensor(
                        svec[0].astype(loss_t._data_.dtype))
                if self._accum > 1:
                    bwd_t = bwd_t * (1.0 / self._accum)
                bwd_t.backward()
                loss = loss_t._data_
                grads = [p.grad._data_ for p in self._params]
                grad_ids = {id(p.grad) for p in self._params}
                mut_caps = [t for t in tracer.mutated_list
                            if id(t) not in grad_ids]
                if mut_caps and self._shard_map:
                    # the GSPMD lane computes mutated state over the
                    # GLOBAL batch (single-device semantics); only the
                    # per-shard shard_map lane cannot represent it
                    raise TraceEscape(
                        "forward mutates non-parameter state (running "
                        "stats?) — per-shard divergence under dp is not "
                        "representable; run eager or dp=1")
                self._mut_caps = mut_caps
                mut_vals = tuple(t._data_ for t in mut_caps)
                if not update:
                    return loss, tuple(grads), mut_vals
                with no_grad():
                    tail = self._update_tail(grads, param_arrs, states,
                                             step_arr, svec, lr,
                                             hmark=hmark)
                (new_params, new_states, new_step, new_svec, zeroed,
                 health) = tail
                return (loss, tuple(new_params), tuple(zeroed), new_states,
                        new_step, new_svec, mut_vals, health)
        finally:
            _state.STATE.tracer = None
            # roll back any forward-mutated captures still holding
            # tracers to their pre-write concrete values
            tracer.rollback_mutations()

    def _update_tail(self, grads, param_arrs, states, step_arr, svec, lr,
                     hmark=None):
        """Unscale → dp pmean → found-inf → clip → fused update → select.
        Pure array math mirroring the eager sequence op-for-op."""
        opt = self._opt
        scaler_on = svec is not None
        if scaler_on:
            inv = 1.0 / svec[0]
            grads = [g * inv.astype(g.dtype) for g in grads]
        if self._dp > 1 and self._shard_map:
            # the in-program analogue of _sync_grads' per-tensor
            # all_reduce + divide: one psum/pmean per gradient that XLA
            # schedules/overlaps inside the step program.  (The GSPMD
            # hybrid lane needs no explicit pmean: differentiating the
            # global-batch loss already yields globally-reduced
            # gradients — XLA inserts and overlaps the dp all-reduce.)
            grads = [jax.lax.pmean(g, "dp") for g in grads]
        found = None
        if scaler_on:
            flags = [~jnp.isfinite(jnp.sum(g)) for g in grads]
            found = jnp.any(jnp.stack(flags))
            if self._dp > 1 and self._shard_map:
                # global decision — a scalar psum, not a host round-trip
                found = jax.lax.pmax(found.astype(jnp.int32),
                                     "dp").astype(jnp.bool_)
            # eager parity: the check is armed only while scaling is
            # active (GradScaler.unscale_ skips it at scale == 1.0) —
            # unless the scaler always checks (the sentinel's unit-scale
            # wrapper generalizing the skip machinery to non-AMP runs)
            if not getattr(self._scaler, "_always_check", False):
                found = jnp.logical_and(found, svec[0] != 1.0)

        health = None
        if self._sentinel:
            if found is None:
                # scaler-less runs: the sentinel arms the same
                # found-inf check the AMP machinery uses, so non-finite
                # steps are skipped in-program here too
                flags = [~jnp.isfinite(jnp.sum(g)) for g in grads]
                found = jnp.any(jnp.stack(flags))
                if self._dp > 1 and self._shard_map:
                    found = jax.lax.pmax(found.astype(jnp.int32),
                                         "dp").astype(jnp.bool_)
            # device-resident health vector [grad_norm_sq, skipped]:
            # the sentinel fetches a window of these in one batched
            # transfer at its check cadence — zero per-step host syncs.
            # The squared-norm pass costs a full read of every gradient,
            # so it runs under lax.cond only on the calls hmark flags
            # (the sentinel check cadence); other steps carry -1.0
            # ("not sampled").  The found-inf flag stays per-step — it
            # is what the skip select consumes.
            def _gnorm_sq():
                sq = [jnp.sum(jnp.square(g.astype(jnp.float32)))
                      for g in grads]
                return jnp.sum(jnp.stack(sq)) if sq \
                    else jnp.asarray(0.0, jnp.float32)

            gnorm_sq = jax.lax.cond(
                hmark > 0.5, _gnorm_sq,
                lambda: jnp.asarray(-1.0, jnp.float32))
            health = jnp.stack([gnorm_sq, found.astype(jnp.float32)])

        if opt._grad_clip is not None:
            pairs = opt._grad_clip(
                [(p, Tensor(g)) for p, g in zip(self._params, grads)])
            grads = [g._data_ for _, g in pairs]

        new_step = step_arr + 1.0
        new_params, new_states = type(opt)._fused_update(
            opt, lr, new_step, list(param_arrs), grads, states,
            lr_scales=self._lr_scales, wd_mask=self._wd_mask)

        # skip decision: the scaler's found-inf flag when one is
        # installed (bitwise-identical to the pre-sentinel program), or
        # the sentinel's own non-finite check for scaler-less runs
        skip = found
        new_svec = svec
        if skip is not None:
            take = ~skip
            new_params = [jnp.where(take, n, o)
                          for n, o in zip(new_params, param_arrs)]
            new_states = {
                name: [None if n is None else jnp.where(take, n, o)
                       for n, o in zip(vals, states[name])]
                for name, vals in new_states.items()}
            new_step = jnp.where(take, new_step, step_arr)
        if scaler_on:
            new_svec = self._scaler_update(svec, found)
        zeroed = [jnp.zeros_like(g) for g in grads]
        return new_params, new_states, new_step, new_svec, zeroed, health

    def _scaler_update(self, svec, found):
        """``GradScaler.update`` as pure in-program math."""
        sc = self._scaler
        scale, good, bad = svec[0], svec[1], svec[2]
        active = jnp.logical_and(
            jnp.asarray(bool(sc._enable and sc._dynamic)), scale != 1.0)
        bad_n = jnp.where(found, bad + 1.0, 0.0)
        good_n = jnp.where(found, 0.0, good + 1.0)
        dec = jnp.logical_and(found, bad_n >= sc._decr_every)
        inc = jnp.logical_and(~found, good_n >= sc._incr_every)
        scale_n = jnp.where(
            dec, jnp.maximum(scale * sc._decr_ratio,
                             getattr(sc, "_min_scale", 1.0)),
            jnp.where(inc, scale * sc._incr_ratio, scale))
        bad_n = jnp.where(dec, 0.0, bad_n)
        good_n = jnp.where(inc, 0.0, good_n)
        out = jnp.stack([scale_n, good_n, bad_n])
        return jnp.where(active, out, svec)

    # ------------------------------------------------------------------
    # compile + execute
    # ------------------------------------------------------------------

    def _build_jit(self, update, args):
        from ..core.op_cache import ensure_compile_cache
        ensure_compile_cache()     # tier-2 persistent XLA compile cache
        mesh = self._mesh

        def fn(x, y, params, grads, caps, states, step_arr, svec, lr,
               key, hmark):
            if self._shard_map:
                from jax.experimental.shard_map import shard_map
                from jax.sharding import PartitionSpec as P

                def body(x, y, params, grads, caps, states, step_arr,
                         svec, lr, key, hmark):
                    # decorrelate per-shard RNG like per-rank eager dp
                    key_s = jax.random.fold_in(
                        key, jax.lax.axis_index("dp"))
                    out = self._traced_body(update, x, y, params, grads,
                                            caps, states, step_arr,
                                            svec, lr, key_s,
                                            hmark=hmark)
                    loss = jax.lax.pmean(out[0], "dp")
                    return (loss,) + tuple(out[1:])
                rep = P()
                in_specs = (P("dp"), P("dp"), rep, rep, rep, rep, rep,
                            rep, rep, rep, rep)
                return shard_map(body, mesh=mesh.jax_mesh,
                                 in_specs=in_specs, out_specs=rep,
                                 check_rep=False)(
                    x, y, params, grads, caps, states, step_arr, svec,
                    lr, key, hmark)
            # single-device AND the hybrid dp×mp GSPMD lane: one global
            # program — the mesh (when present) enters through the
            # in_shardings trees and the model's own shard_constraints,
            # and the traced math is exactly the single-device step
            return self._traced_body(update, x, y, params, grads, caps,
                                     states, step_arr, svec, lr, key,
                                     hmark=hmark)

        self._donating = bool(_flag("FLAGS_jit_donate_buffers", True))
        donate = ()
        if self._donating:
            # params, grads, opt state, step counter, scaler vec — the
            # buffers the program replaces in place
            donate = (2, 3, 5, 6, 7) if update else (3,)
        kwargs = {}
        if self._shard_map:
            from jax.sharding import NamedSharding, PartitionSpec as P
            kwargs["out_shardings"] = NamedSharding(self._mesh.jax_mesh,
                                                    P())
        elif self._gspmd:
            # pin every input leaf to its derived sharding; output
            # shardings are inferred by GSPMD propagation (the update
            # chain is elementwise, so outputs land on the input
            # shardings and donation stays usable)
            kwargs["in_shardings"] = self._hybrid_shardings(args)
        return jax.jit(fn, donate_argnums=donate, **kwargs)

    def _gather_args(self, x, y):
        opt = self._opt
        if self._gspmd:
            self._align_hybrid()
        xa = x._data_ if isinstance(x, Tensor) else jnp.asarray(x)
        ya = y._data_ if isinstance(y, Tensor) else (
            None if y is None else jnp.asarray(y))
        params = tuple(p._data_ for p in self._params)
        grads = tuple(p.grad._data_ for p in self._params)
        caps = tuple(t._data_ for t in self._caps)
        states = {name: [None if opt._state[name][i] is None
                         else opt._state[name][i]._data_
                         for i in self._idxs]
                  for name in self._state_names}
        step_arr = opt._step_tensor._data_
        svec = None
        if self._scaler is not None and self._scaler._enable:
            if self._scaler_vec is None:
                sc = self._scaler
                self._scaler_vec = jnp.asarray(
                    [sc._scale, float(sc._good_steps),
                     float(sc._bad_steps)], jnp.float32)
            svec = self._scaler_vec
        lr = np.float32(opt.get_lr())
        key = jax.random.fold_in(_state.STATE.rng_key,
                                 _state.STATE.rng_counter)
        _state.STATE.rng_counter += 1
        # hmark: sample the expensive in-program grad-norm pass only on
        # the sentinel's check cadence (lax.cond skips it otherwise)
        hmark = np.float32(
            1.0 if self._sentinel
            and self._calls % self._health_every == 1 else 0.0)
        return (xa, ya, params, grads, caps, states, step_arr, svec, lr,
                key, hmark)

    def _run_compiled(self, x, y, update):
        from ..utils import monitor as _monitor
        opt = self._opt
        args = self._gather_args(x, y)
        if self._dp > 1 and (args[0].shape[0] % self._dp):
            # ragged tail batch cannot shard evenly: one-off eager step
            _monitor.incr("jit.compiled_step_ragged_fallback")
            if self._gspmd:
                # the model's own dp activation constraints cannot
                # shard a ragged batch either — lift the mesh scope for
                # this one step (sharded params compute the same values
                # through GSPMD eager propagation)
                from ..distributed import mesh as _mesh_mod
                with _mesh_mod.suspended():
                    return self._run_eager(x, y, update)
            return self._run_eager(x, y, update)
        if self._donating is not None and self._donating != bool(
                _flag("FLAGS_jit_donate_buffers", True)):
            self._jit_full = self._jit_micro = None   # flag flipped
        jit = self._jit_full if update else self._jit_micro
        if jit is None:
            jit = self._build_jit(update, args)
            if update:
                self._jit_full = jit
            else:
                self._jit_micro = jit
            _monitor.incr("jit.compiled_step_compile")
        if self._donating and self._aliased(args, update):
            _monitor.incr("jit.compiled_step_alias_fallback")
            return self._run_eager(x, y, update)

        if update:
            (loss, new_params, zeroed, new_states, new_step, new_svec,
             mut_vals, health) = jit(*args)
            self.last_health = health    # device [gnorm_sq, skipped]
            for p, arr in zip(self._params, new_params):
                p._data_ = arr
            for name in self._state_names:
                vals = opt._state[name]
                for k, i in enumerate(self._idxs):
                    nv = new_states[name][k]
                    if nv is None:
                        continue
                    if vals[i] is None:
                        vals[i] = Tensor(nv)
                    else:
                        vals[i]._data_ = nv
            opt._step_tensor._data_ = new_step
            opt._step_count += 1
            if new_svec is not None:
                self._scaler_vec = new_svec
            for p, g in zip(self._params, zeroed):
                p.grad._data_ = g
        else:
            loss, new_grads, mut_vals = jit(*args)
            for p, g in zip(self._params, new_grads):
                p.grad._data_ = g
        for t, arr in zip(self._mut_caps, mut_vals):
            t._data_ = arr
        return Tensor(loss)

    def _aliased(self, args, update):
        """Donation is unsound when one device buffer backs two donated
        leaves (tied weights sharing an array): skip this call."""
        if update:
            donated = list(args[2]) + list(args[3]) + [args[6]]
            for vals in args[5].values():
                donated.extend(a for a in vals if a is not None)
            if args[7] is not None:
                donated.append(args[7])
        else:
            donated = list(args[3])
        seen = set()
        for a in donated:
            if id(a) in seen:
                return True
            seen.add(id(a))
        return False
