"""The pull-based pipeline core: stage objects + the ``Pipeline``
orchestrator.

Checkpoint contract (Grain-style): stage state is *derivational*, not
*material* — a seed, an epoch number, a global sample position, a carry
pointer.  Restoring state re-derives every buffer from the dataset;
nothing that flows through the pipeline is ever serialized.  That is
what makes the state tiny (a few ints), valid across a dp-degree
resize, and bit-exact on resume.

Sharding model: one epoch is ``total = ceil(n / dp_degree) * dp_degree``
global sample slots (the tail wraps into the head of the shuffled
order, the ``DistributedBatchSampler`` padding convention).  Slot ``g``
belongs to rank ``g % dp_degree``; every rank advances the shared
``global_position`` by ``dp_degree`` per local sample, so in lockstep
training ``global_position`` is identical on all ranks and a checkpoint
taken on any rank re-shards to any new dp degree: the resumed world
simply continues consuming slots ``[global_position, total)`` — a
permutation-free continuation with no dropped or duplicated samples.
"""
from __future__ import annotations

import copy
import math
import time

import numpy as np

from ..utils import fault_injection as _fi
from ..utils import monitor as _monitor
from .goodput import GoodputMeter

_SKIP = object()
_EPOCH_END = object()

_STATE_VERSION = 1


class PipelineConfigError(TypeError):
    """Mis-ordered or mis-typed stage composition (e.g. ``.shuffle()``
    after ``.batch()``, or ``.device_prefetch()`` without ``.batch()``)."""


class CorruptRecordError(RuntimeError):
    """More corrupt records than ``corrupt_threshold`` were skipped.

    Individual corrupt records are skipped and counted
    (``data.records_skipped``) so one bad shard does not kill a fleet
    run; past the threshold the pipeline refuses to keep silently
    thinning the sample stream."""

    def __init__(self, skipped, threshold, last_error):
        self.skipped = int(skipped)
        self.threshold = int(threshold)
        self.last_error = str(last_error)
        super().__init__(
            f"data pipeline skipped {skipped} corrupt records "
            f"(threshold {threshold}); last error: {last_error}")


class PipelineStateError(ValueError):
    """A ``load_state_dict`` payload that cannot be applied (wrong
    version, missing stage, negative counters)."""


# ---------------------------------------------------------------------------
# stages
# ---------------------------------------------------------------------------


class _SourceStage:
    """Record fetch + corrupt-record policy over an indexable dataset."""

    name = "source"

    def __init__(self, dataset, corrupt_threshold=8):
        self.dataset = dataset
        self.corrupt_threshold = int(corrupt_threshold)
        self.records_skipped = 0
        self._last_error = ""

    def __len__(self):
        return len(self.dataset)

    def fetch(self, sample_id):
        _fi.data_fetch_delay()
        try:
            if _fi.data_record_corrupt(sample_id):
                raise ValueError(
                    f"injected corrupt record (sample {sample_id})")
            return self.dataset[sample_id]
        except Exception as e:  # noqa: BLE001 — corrupt-record policy
            self.records_skipped += 1
            self._last_error = f"sample {sample_id}: {type(e).__name__}: {e}"
            _monitor.incr("data.records_skipped")
            if self.records_skipped > self.corrupt_threshold:
                raise CorruptRecordError(
                    self.records_skipped, self.corrupt_threshold,
                    self._last_error) from e
            return _SKIP

    def state_dict(self):
        return {"records_skipped": int(self.records_skipped)}

    def load_state_dict(self, sd):
        skipped = int(sd.get("records_skipped", 0))
        if skipped < 0:
            raise PipelineStateError(
                f"source.records_skipped must be >= 0, got {skipped}")
        self.records_skipped = skipped


class _ShardStage:
    """Owns the epoch counter and the single global sample position."""

    name = "shard"

    def __init__(self, rank=0, dp_degree=1):
        rank, dp_degree = int(rank), int(dp_degree)
        if dp_degree < 1 or not (0 <= rank < dp_degree):
            raise PipelineConfigError(
                f"shard(rank={rank}, dp_degree={dp_degree}): need "
                f"0 <= rank < dp_degree")
        self.rank = rank
        self.dp_degree = dp_degree
        self.epoch = 0
        self.global_position = 0

    def positions_total(self, n):
        return int(math.ceil(n / self.dp_degree)) * self.dp_degree

    def next_position(self, n):
        """This rank's next global slot, advancing the lockstep
        position — or None at epoch end."""
        g = self.global_position + self.rank
        if g >= self.positions_total(n):
            return None
        self.global_position += self.dp_degree
        return g

    def advance_epoch(self):
        self.epoch += 1
        self.global_position = 0

    def state_dict(self):
        # dp_degree is recorded for observability only: the position is
        # global, so a resumed world applies its OWN rank/dp_degree.
        return {"epoch": int(self.epoch),
                "global_position": int(self.global_position),
                "dp_degree": int(self.dp_degree)}

    def load_state_dict(self, sd):
        epoch = int(sd.get("epoch", 0))
        pos = int(sd.get("global_position", 0))
        if epoch < 0 or pos < 0:
            raise PipelineStateError(
                f"shard state must be non-negative (epoch={epoch}, "
                f"global_position={pos})")
        self.epoch = epoch
        self.global_position = pos


class _ShuffleStage:
    """Windowed, seeded, per-epoch-reseeded permutation — computed, not
    buffered.  Slot ``g`` maps through a permutation of its window
    block, keyed by ``(seed, epoch, block)``, so random access (the
    pack carry refetch) and sequential access share one code path and
    the only state is the seed."""

    name = "shuffle"

    def __init__(self, seed=0, window=None):
        self.seed = int(seed)
        if window is not None and int(window) < 2:
            raise PipelineConfigError(
                f"shuffle(window={window}): window must be >= 2 "
                f"(or None for a full-epoch permutation)")
        self.window = None if window is None else int(window)
        self._cache_key = None
        self._cache_perm = None

    def permute(self, epoch, n, pos):
        w = self.window or n
        block = pos // w
        key = (self.seed, int(epoch), block, n)
        if self._cache_key != key:
            block_n = min(w, n - block * w)
            rng = np.random.default_rng(list(key))
            self._cache_perm = rng.permutation(block_n)
            self._cache_key = key
        return int(block * w + self._cache_perm[pos - block * w])

    def state_dict(self):
        return {"seed": int(self.seed),
                "window": self.window}

    def load_state_dict(self, sd):
        if "seed" in sd and int(sd["seed"]) != self.seed:
            # a silently different stream is the worst failure mode a
            # deterministic loader can have — refuse loudly
            raise PipelineStateError(
                f"shuffle seed mismatch: checkpoint has {sd['seed']}, "
                f"pipeline was built with {self.seed}")


class _MapStage:
    name = "map"

    def __init__(self, fn):
        if not callable(fn):
            raise PipelineConfigError(f"map(fn): {fn!r} is not callable")
        self.fn = fn

    def state_dict(self):
        return {}

    def load_state_dict(self, sd):
        pass


class _PackStage:
    """Fixed-length sequence packing: whole documents are placed
    back-to-back into rows of ``seq_len`` tokens with 1-based segment
    ids and per-document position reset (pad = segment 0).  A document
    that does not fit the remaining row opens the next row; the pending
    document is checkpointed as its *(epoch, global slot)* pointer and
    re-fetched on restore — never as tokens."""

    name = "pack"

    def __init__(self, seq_len):
        if int(seq_len) < 1:
            raise PipelineConfigError(f"pack(seq_len={seq_len}): need >= 1")
        self.seq_len = int(seq_len)
        self._carry_tokens = None   # np.ndarray — runtime only
        self._carry_slot = None     # (epoch, global_position) — the state

    def state_dict(self):
        slot = self._carry_slot
        return {"carry": None if slot is None
                else [int(slot[0]), int(slot[1])]}

    def load_state_dict(self, sd, refetch=None):
        slot = sd.get("carry")
        if slot is None:
            self._carry_tokens = None
            self._carry_slot = None
            return
        epoch, g = int(slot[0]), int(slot[1])
        if refetch is None:
            raise PipelineStateError(
                "pack carry present but no refetch path available")
        self._carry_tokens = _as_tokens(refetch(epoch, g))
        self._carry_slot = (epoch, g)


class _BatchStage:
    name = "batch"

    def __init__(self, batch_size, drop_last=True):
        if int(batch_size) < 1:
            raise PipelineConfigError(
                f"batch(batch_size={batch_size}): need >= 1")
        self.batch_size = int(batch_size)
        self.drop_last = bool(drop_last)

    def state_dict(self):
        return {}

    def load_state_dict(self, sd):
        pass


def _as_tokens(sample):
    tokens = np.asarray(sample)
    if tokens.ndim != 1:
        raise PipelineConfigError(
            f"pack() expects 1-D token sequences upstream, got shape "
            f"{tokens.shape}")
    return tokens


def _collate_host(items):
    """Stack samples into host-side numpy batches (device placement is
    the prefetch/iterator's job, so workers and producers stay
    device-free)."""
    first = items[0]
    if isinstance(first, np.ndarray):
        return np.stack(items)
    if isinstance(first, (int, float, np.integer, np.floating)):
        return np.asarray(items)
    if isinstance(first, (list, tuple)):
        return type(first)(_collate_host(list(group))
                           for group in zip(*items))
    if isinstance(first, dict):
        return {k: _collate_host([d[k] for d in items]) for k in first}
    return np.asarray(items)


# ---------------------------------------------------------------------------
# the pipeline
# ---------------------------------------------------------------------------

#: builder ordering — a stage may only be appended after stages of
#: strictly lower rank (map may repeat).
_STAGE_RANK = {"shard": 1, "shuffle": 2, "map": 3, "pack": 4, "batch": 5,
               "device_prefetch": 6}


class Pipeline:
    """Composable input pipeline; build with :func:`pipeline`.

    ``iter(p)`` yields one epoch of batches from the current position
    (so a freshly-restored pipeline resumes mid-epoch), then advances
    the epoch counter.  ``state_dict()`` between any two batches is a
    consistent resume point.
    """

    def __init__(self, dataset, corrupt_threshold=8):
        if not hasattr(dataset, "__getitem__") or not hasattr(
                dataset, "__len__"):
            raise PipelineConfigError(
                "pipeline(dataset): dataset must be indexable with a "
                "len() (map-style); IterableDataset is not resumable")
        self._source = _SourceStage(dataset, corrupt_threshold)
        self._shard = _ShardStage(0, 1)
        self._shuffle = None
        self._maps = []
        self._pack = None
        self._batch = None
        self._prefetch = None
        self._max_rank = 0
        self.goodput = GoodputMeter()
        self._committed = None  # filled lazily: state after last batch

    # -- builders ----------------------------------------------------------

    def _admit(self, kind):
        rank = _STAGE_RANK[kind]
        if rank < self._max_rank or (rank == self._max_rank
                                     and kind != "map"):
            raise PipelineConfigError(
                f".{kind}() must come before any "
                f"{[k for k, r in _STAGE_RANK.items() if r > rank]} "
                f"stage already added (canonical order: source -> shard "
                f"-> shuffle -> map -> pack -> batch -> device_prefetch)")
        self._max_rank = rank

    def shard(self, rank=None, dp_degree=None):
        self._admit("shard")
        if rank is None or dp_degree is None:
            from ..distributed import env as dist_env
            rank = dist_env.get_rank() if rank is None else rank
            dp_degree = (dist_env.get_world_size()
                         if dp_degree is None else dp_degree)
        self._shard = _ShardStage(rank, dp_degree)
        return self

    def shuffle(self, seed=0, window=None):
        self._admit("shuffle")
        self._shuffle = _ShuffleStage(seed, window)
        return self

    def map(self, fn):
        self._admit("map")
        self._maps.append(_MapStage(fn))
        return self

    def pack(self, seq_len):
        self._admit("pack")
        self._pack = _PackStage(seq_len)
        return self

    def batch(self, batch_size, drop_last=True):
        self._admit("batch")
        self._batch = _BatchStage(batch_size, drop_last)
        return self

    def device_prefetch(self, depth=2):
        self._admit("device_prefetch")
        if self._batch is None:
            raise PipelineConfigError(
                ".device_prefetch() requires a .batch() stage (device "
                "transfer is per-batch)")
        from .prefetch import DevicePrefetch
        self._prefetch = DevicePrefetch(depth)
        return self

    # -- introspection -----------------------------------------------------

    @property
    def epoch(self):
        # as-of-last-yielded-batch, NOT the live stage counter: an
        # abandoned prefetch producer may have run ahead (even into the
        # next epoch) past what the caller ever consumed
        if self._committed is not None:
            return int(self._committed["stages"]["shard"]["epoch"])
        return self._shard.epoch

    @property
    def records_skipped(self):
        return self._source.records_skipped

    def __len__(self):
        if self._pack is not None:
            raise TypeError(
                "len() is undefined with a pack() stage (rows per epoch "
                "depend on document lengths)")
        n_local = self._shard.positions_total(
            len(self._source)) // self._shard.dp_degree
        if self._batch is None:
            return n_local
        if self._batch.drop_last:
            return n_local // self._batch.batch_size
        return -(-n_local // self._batch.batch_size)

    # -- checkpoint contract ----------------------------------------------

    def state_dict(self):
        """Resume state as of the last batch *yielded to the caller*
        (prefetched-but-unconsumed batches are not counted)."""
        if self._committed is None:
            self._committed = self._host_state()
        return copy.deepcopy(self._committed)

    def load_state_dict(self, sd):
        if not isinstance(sd, dict):
            raise PipelineStateError(
                f"pipeline state must be a dict, got {type(sd).__name__}")
        if int(sd.get("version", -1)) != _STATE_VERSION:
            raise PipelineStateError(
                f"pipeline state version {sd.get('version')!r} "
                f"(this build reads version {_STATE_VERSION})")
        stages = sd.get("stages", {})
        self._source.load_state_dict(stages.get("source", {}))
        self._shard.load_state_dict(stages.get("shard", {}))
        if self._shuffle is not None:
            self._shuffle.load_state_dict(stages.get("shuffle", {}))
        if self._pack is not None:
            self._pack.load_state_dict(stages.get("pack", {}),
                                       refetch=self._refetch)
        self._committed = self._host_state()
        return self

    def _host_state(self):
        stages = {"source": self._source.state_dict(),
                  "shard": self._shard.state_dict()}
        if self._shuffle is not None:
            stages["shuffle"] = self._shuffle.state_dict()
        if self._pack is not None:
            stages["pack"] = self._pack.state_dict()
        return {"version": _STATE_VERSION, "stages": stages}

    # -- sample resolution -------------------------------------------------

    def _resolve_sample_id(self, epoch, g):
        n = len(self._source)
        pos = g % n  # padded tail wraps into the head of the order
        if self._shuffle is not None:
            return self._shuffle.permute(epoch, n, pos)
        return pos

    def _apply_maps(self, sample):
        for m in self._maps:
            sample = m.fn(sample)
        return sample

    def _refetch(self, epoch, g):
        """Random-access re-derivation of the sample at global slot
        ``g`` of ``epoch`` — the pack-carry restore path."""
        sample = self._source.fetch(self._resolve_sample_id(epoch, g))
        if sample is _SKIP:
            raise PipelineStateError(
                f"pack carry points at slot {g} of epoch {epoch}, but "
                f"that record is no longer fetchable")
        return self._apply_maps(sample)

    def _next_sample(self):
        """Next mapped sample for this rank, or ``_EPOCH_END``.
        Returns ``(sample, epoch, g)`` so pack can record carry slots."""
        n = len(self._source)
        while True:
            epoch = self._shard.epoch
            g = self._shard.next_position(n)
            if g is None:
                return _EPOCH_END
            sample = self._source.fetch(self._resolve_sample_id(epoch, g))
            if sample is _SKIP:
                continue
            return self._apply_maps(sample), epoch, g

    def _next_item(self):
        """Next row (with pack) or sample (without), or ``_EPOCH_END``."""
        if self._pack is None:
            nxt = self._next_sample()
            return nxt if nxt is _EPOCH_END else nxt[0]
        return self._next_packed_row()

    def _next_packed_row(self):
        p = self._pack
        S = p.seq_len
        tokens = np.zeros(S, dtype=np.int32)
        segments = np.zeros(S, dtype=np.int32)
        positions = np.zeros(S, dtype=np.int32)
        used = 0
        seg = 0

        def place(doc):
            nonlocal used, seg
            take = min(len(doc), S - used)
            seg += 1
            tokens[used:used + take] = doc[:take]
            segments[used:used + take] = seg
            positions[used:used + take] = np.arange(take)
            used += take

        if p._carry_tokens is not None:
            doc = p._carry_tokens
            p._carry_tokens = None
            p._carry_slot = None
            if len(doc) > S:
                _monitor.incr("data.docs_truncated")
            place(doc)
        while used < S:
            nxt = self._next_sample()
            if nxt is _EPOCH_END:
                if seg == 0:
                    return _EPOCH_END
                break
            sample, epoch, g = nxt
            doc = _as_tokens(sample)
            if len(doc) == 0:
                continue
            if len(doc) > S - used:
                if used == 0:
                    # longer than a whole row: truncate in place
                    _monitor.incr("data.docs_truncated")
                    place(doc)
                else:
                    p._carry_tokens = doc
                    p._carry_slot = (epoch, g)
                    break
            else:
                place(doc)
        return {"tokens": tokens, "segment_ids": segments,
                "positions": positions}

    # -- iteration ---------------------------------------------------------

    def _host_batches(self):
        """Yield ``(host_batch, state_after)`` for the remainder of the
        current epoch, advancing the epoch counter at the end.  States
        are deep-copied at production time so prefetch buffering cannot
        alias them."""
        target = self._batch.batch_size if self._batch else 1
        while True:
            t0 = time.perf_counter()
            items = []
            ended = False
            while len(items) < target:
                item = self._next_item()
                if item is _EPOCH_END:
                    ended = True
                    break
                items.append(item)
            keep = items and (len(items) == target
                              or self._batch is None
                              or not self._batch.drop_last)
            if ended:
                self._shard.advance_epoch()
            if keep:
                batch = (_collate_host(items) if self._batch is not None
                         else items[0])
                self.goodput.record_fetch(
                    (time.perf_counter() - t0) * 1e3)
                yield batch, copy.deepcopy(self._host_state())
            if ended:
                return

    def __iter__(self):
        if self._committed is None:
            self._committed = self._host_state()
        else:
            # re-arm from the committed point: a previous iteration
            # abandoned mid-epoch (num_iters, preemption) leaves the
            # live stages wherever its prefetch producer ran ahead to
            self.load_state_dict(self._committed)
        if self._prefetch is not None:
            src = self._prefetch.iterate(self)
        else:
            src = ((self._to_device(b), s) for b, s in self._host_batches())
        for batch, state in src:
            self._committed = state
            yield batch
        # tail-drop / epoch advance commit even when the final partial
        # batch was dropped and never yielded
        self._committed = self._host_state()

    def _to_device(self, batch):
        from .prefetch import to_device_batch
        return to_device_batch(batch)


def pipeline(dataset, corrupt_threshold=8):
    """Entry point: ``pipeline(ds).shard(r, d).shuffle(seed).map(fn)
    .batch(B).device_prefetch()`` — stages compose in canonical order;
    see :class:`Pipeline`."""
    return Pipeline(dataset, corrupt_threshold=corrupt_threshold)
