"""Collective communication API.

Reference capability: python/paddle/distributed/communication/ (all_reduce.py,
all_gather, all_to_all, reduce_scatter, broadcast, send/recv, new_group) over
ProcessGroupNCCL (reference: paddle/fluid/distributed/collective/
process_group.h:53, process_group_nccl.h:37).

TPU-native realization (SURVEY.md §5 "Distributed communication backend"):
collectives COMPILE INTO the XLA program over ICI/DCN — there is no NCCL
analog to wrap.  Two surfaces:

1. **Eager process-level API** (this module): rank == JAX process
   (multi-controller).  Each call assembles the per-process local values into
   a global array over the group's devices and runs a tiny jitted program
   containing the XLA collective; with one process it degenerates to the
   mathematically-equal local computation, so single-host code is unchanged
   (the reference gets this from ProcessGroup with world_size=1).

2. **In-graph primitives** (`paddle_tpu.distributed.functional`): named-axis
   psum/all_gather/ppermute/all_to_all for use inside shard_map regions —
   ring attention, MoE dispatch, explicit-SP layers.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from . import env as _env
from . import watchdog as _wd


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communication group = an ordered subset of global ranks
    (reference: python/paddle/distributed/communication/group.py)."""

    _next_id = 0

    def __init__(self, ranks):
        self.ranks = list(ranks)
        self.nranks = len(ranks)
        self.id = Group._next_id
        Group._next_id += 1

    @property
    def world_size(self):
        return self.nranks

    @property
    def rank(self):
        """This process's rank within the group, or -1 if not a member."""
        try:
            return self.ranks.index(_env.get_rank())
        except ValueError:
            return -1

    def get_group_rank(self, global_rank):
        return self.ranks.index(global_rank)

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks})"


_default_group: Group | None = None


def _get_default_group() -> Group:
    global _default_group
    if _default_group is None:
        _default_group = Group(list(range(_env.get_world_size())))
    return _default_group


def new_group(ranks=None, backend=None, timeout=None) -> Group:
    """reference: python/paddle/distributed/communication/group.py new_group"""
    if ranks is None:
        ranks = list(range(_env.get_world_size()))
    return Group(sorted(ranks))


def get_group(gid=0):
    return _get_default_group()


def _as_array(x):
    if isinstance(x, Tensor):
        return x._data_
    return jnp.asarray(x)


def _wrap(x, like=None):
    t = Tensor(x)
    if like is not None and isinstance(like, Tensor):
        t.stop_gradient = like.stop_gradient
    return t


def _group_devices(group: Group):
    """Devices backing the group — one per member process (multi-controller:
    each process contributes its first addressable device)."""
    devs = jax.devices()
    per_proc = {}
    for d in devs:
        per_proc.setdefault(d.process_index, d)
    missing = [r for r in group.ranks if r not in per_proc]
    if missing:
        raise RuntimeError(
            f"group {group} includes ranks {missing} with no visible "
            f"devices (visible process indices: {sorted(per_proc)})")
    return [per_proc[r] for r in group.ranks]


#: None = untested, True = the XLA backend runs cross-process programs,
#: False = it raised "Multiprocess computations aren't implemented" and
#: every collective since rides the host lane (gloo analog).
_XLA_MULTIPROC_OK = None
_HOST_FALLBACK_WARNED = False


def _np_reduce(op, stacked):
    """Reduce a host-gathered ``[nranks, ...]`` stack with XLA-matching
    dtype semantics (sum/max/min/prod preserve dtype; mean of integers
    promotes to float32 like jnp.mean under x32)."""
    reducers = {ReduceOp.SUM: np.sum, ReduceOp.MAX: np.max,
                ReduceOp.MIN: np.min, ReduceOp.PROD: np.prod,
                ReduceOp.AVG: np.mean}
    res = np.asarray(reducers[op](stacked, axis=0))
    if op == ReduceOp.AVG and stacked.dtype.kind not in "fc":
        return res.astype(np.float32)
    return res.astype(stacked.dtype)


def _host_collective(local, group, op, host_fn):
    from . import host_collectives as _hc
    host = _hc.bootstrap()
    if host is None:
        raise RuntimeError(
            f"collective {op!r}: host backend has no store — launch "
            "through paddle_tpu.distributed.launch (guardian store) or "
            "initialize jax.distributed (coordination-service KV)")
    return host_fn(host.gather(group, np.asarray(local)))


def _multiproc_collective(local, group, jitted_fn, op="collective",
                          host_fn=None):
    """Assemble per-process local arrays into a global stacked array over the
    group's devices, run the collective program, return this rank's slice.

    This is the single choke point every real (nranks>1) collective goes
    through, so it hosts two cross-cutting layers:

    - **hang guardian** (docs/RESILIENCE.md): the call registers
      (op, group, seq, start-time) with the collective watchdog, which
      converts a stall into a stall dump + `CollectiveTimeoutError` (or
      a dead peer's original error) instead of an unbounded block.  With
      the guardian off (`FLAGS_collective_timeout_s=0`, no trap store,
      no collective fault points) `begin()` returns None after a few
      dict lookups.
    - **backend selection** (`FLAGS_collective_backend`): the XLA lane
      compiles the collective into a cross-process program; backends
      that cannot (jaxlib CPU raises "Multiprocess computations aren't
      implemented") fall back to the host lane — a store-mediated
      gather + local combine (`host_collectives.py`, the reference's
      ProcessGroupGloo analog) with identical semantics.
    """
    from jax.sharding import Mesh, NamedSharding, PartitionSpec
    if group.rank < 0:
        raise ValueError(
            f"process rank {_env.get_rank()} is not a member of {group}; "
            "collectives must only be called by group members (reference: "
            "ProcessGroup membership contract, process_group.h:53)")
    token = _wd.begin(op, group)
    try:
        _wd.preflight(token)    # fault injection + peer check + desync
        global _XLA_MULTIPROC_OK, _HOST_FALLBACK_WARNED
        from ..utils.flags import flag as _flag
        backend = str(_flag("FLAGS_collective_backend", "auto"))
        if host_fn is not None and (
                backend == "host" or
                (backend == "auto" and _XLA_MULTIPROC_OK is False)):
            return _host_collective(local, group, op, host_fn)
        try:
            devs = _group_devices(group)
            mesh = Mesh(np.array(devs, dtype=object), axis_names=("g",))
            stacked_shape = (group.nranks,) + tuple(local.shape)
            sharding = NamedSharding(mesh, PartitionSpec("g"))
            garr = jax.make_array_from_single_device_arrays(
                stacked_shape, sharding,
                [jax.device_put(local[None], devs[group.rank])])
            out = jitted_fn(garr, mesh)
            _XLA_MULTIPROC_OK = True
            return out
        except Exception as e:
            if backend == "auto" and host_fn is not None and \
                    "Multiprocess computations aren't implemented" \
                    in str(e):
                # this backend will never run a cross-process program;
                # remember and ride the host lane from now on
                _XLA_MULTIPROC_OK = False
                if not _HOST_FALLBACK_WARNED:
                    _HOST_FALLBACK_WARNED = True
                    import sys as _sys
                    _sys.stderr.write(
                        "[collective] XLA backend cannot run cross-"
                        "process programs here; falling back to host-"
                        "mediated collectives (FLAGS_collective_backend"
                        "=host to silence)\n")
                return _host_collective(local, group, op, host_fn)
            raise
    except BaseException as exc:
        # an async-raised GuardianError arrives as a bare class; swap in
        # the rich instance the watchdog prepared (op/seq/blame attrs)
        rich = _wd.translate(token, exc)
        if rich is not exc:
            raise rich from None
        raise
    finally:
        _wd.end(token)


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------


_COLLECTIVE_CALLS = None


def _count_collective(op_name):
    """Per-op collective-call counter (``dist.collective_calls{op=...}``
    in the observability registry) — the cheapest possible answer to
    "is this run communication-bound, and on which primitive"."""
    global _COLLECTIVE_CALLS
    if _COLLECTIVE_CALLS is None:
        from ..observability import registry as _metrics
        _COLLECTIVE_CALLS = _metrics.counter(
            "dist.collective_calls", "collective ops issued",
            labelnames=("op",))
    _COLLECTIVE_CALLS.labels(op=op_name).inc()


_REDUCERS = {ReduceOp.SUM: jnp.sum, ReduceOp.MAX: jnp.max,
             ReduceOp.MIN: jnp.min, ReduceOp.PROD: jnp.prod,
             ReduceOp.AVG: jnp.mean}


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In-place all-reduce of `tensor` across the group
    (reference: communication/all_reduce.py)."""
    _count_collective("all_reduce")
    group = group or _get_default_group()
    x = _as_array(tensor)
    if group.nranks <= 1:
        return tensor
    reducer = _REDUCERS[op]

    def prog(garr, mesh):
        out = jax.jit(lambda a: reducer(a, axis=0),
                      out_shardings=jax.sharding.NamedSharding(
                          mesh, jax.sharding.PartitionSpec()))(garr)
        return np.asarray(out.addressable_shards[0].data)

    res = _multiproc_collective(x, group, prog, op="all_reduce",
                                host_fn=lambda st: _np_reduce(op, st))
    if isinstance(tensor, Tensor):
        tensor._data_ = jnp.asarray(res)
        return tensor
    return _wrap(jnp.asarray(res))


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    """Gather `tensor` from every rank into `tensor_list`
    (reference: communication/all_gather.py)."""
    _count_collective("all_gather")
    group = group or _get_default_group()
    x = _as_array(tensor)
    if group.nranks <= 1:
        if tensor_list is not None:
            tensor_list.append(_wrap(x, tensor))
            return tensor_list
        return [_wrap(x, tensor)]

    def prog(garr, mesh):
        out = jax.jit(lambda a: a,
                      out_shardings=jax.sharding.NamedSharding(
                          mesh, jax.sharding.PartitionSpec()))(garr)
        return np.asarray(out.addressable_shards[0].data)

    res = _multiproc_collective(x, group, prog, op="all_gather",
                                host_fn=lambda st: st)
    parts = [_wrap(jnp.asarray(res[i])) for i in range(group.nranks)]
    if tensor_list is not None:
        tensor_list.extend(parts)
        return tensor_list
    return parts


def broadcast(tensor, src=0, group=None, sync_op=True):
    """reference: communication/broadcast.py"""
    _count_collective("broadcast")
    group = group or _get_default_group()
    if group.nranks <= 1:
        return tensor
    if src not in group.ranks:
        raise ValueError(
            f"broadcast src={src} is not a member of {group}")
    parts = all_gather(None, tensor, group=group)
    data = parts[group.get_group_rank(src)]._data_
    if isinstance(tensor, Tensor):
        tensor._data_ = data
        return tensor
    return _wrap(data)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reduce to `dst`: every rank participates, only dst's buffer is
    updated (reference semantics: process_group.h:172 — non-dst outputs
    are unspecified, the reference leaves them untouched)."""
    _count_collective("reduce")
    group = group or _get_default_group()
    if group.nranks <= 1:
        return tensor
    before = _as_array(tensor)
    out = all_reduce(tensor, op=op, group=group)
    if _env.get_rank() != dst:
        if isinstance(tensor, Tensor):
            tensor._data_ = before
            return tensor
        return _wrap(before)
    return out


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    _count_collective("scatter")
    group = group or _get_default_group()
    if group.nranks <= 1:
        if tensor_list:
            tensor._data_ = _as_array(tensor_list[0])
        return tensor
    # src materializes the list; everyone receives its slice via broadcast
    stacked = None
    if group.rank == group.get_group_rank(src) and tensor_list:
        stacked = jnp.stack([_as_array(t) for t in tensor_list])
    else:
        stacked = jnp.zeros((group.nranks,) + tuple(_as_array(tensor).shape),
                            _as_array(tensor).dtype)
    holder = _wrap(stacked)
    broadcast(holder, src=src, group=group)
    tensor._data_ = holder._data_[group.rank]
    return tensor


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """Real reduce-scatter: the compiled program's output is SHARDED over
    the group axis, so XLA lowers it to a reduce-scatter collective — each
    rank only materializes its own slice (reference:
    communication/reduce_scatter.py over ProcessGroup::ReduceScatter)."""
    _count_collective("reduce_scatter")
    group = group or _get_default_group()
    if group.nranks <= 1:
        tensor._data_ = _as_array(tensor_list[0])
        return tensor
    stacked = jnp.stack([_as_array(t) for t in tensor_list])
    reducer = _REDUCERS[op]

    def prog(garr, mesh):
        # garr: [g(sharded), nranks, ...] → sum over g, shard result rows
        out = jax.jit(lambda a: reducer(a, axis=0),
                      out_shardings=jax.sharding.NamedSharding(
                          mesh, jax.sharding.PartitionSpec("g")))(garr)
        return np.asarray(out.addressable_shards[0].data)[0]

    res = _multiproc_collective(
        stacked, group, prog, op="reduce_scatter",
        host_fn=lambda st: _np_reduce(op, st)[group.rank])
    tensor._data_ = jnp.asarray(res)
    return tensor


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """Real all-to-all: transpose the (source, destination) axes of the
    global array with a sharded output — XLA lowers it to an all-to-all
    collective, not an all-gather (reference: communication/all_to_all.py)."""
    _count_collective("all_to_all")
    group = group or _get_default_group()
    if group.nranks <= 1:
        out_tensor_list.extend(_wrap(_as_array(t)) for t in in_tensor_list)
        return out_tensor_list
    stacked = jnp.stack([_as_array(t) for t in in_tensor_list])

    def prog(garr, mesh):
        # garr: [src(g), dst, ...] → [dst(g), src, ...]
        out = jax.jit(lambda a: jnp.swapaxes(a, 0, 1),
                      out_shardings=jax.sharding.NamedSharding(
                          mesh, jax.sharding.PartitionSpec("g")))(garr)
        return np.asarray(out.addressable_shards[0].data)[0]

    res = _multiproc_collective(
        stacked, group, prog, op="all_to_all",
        host_fn=lambda st: np.swapaxes(st, 0, 1)[group.rank])
    for r in range(group.nranks):
        out_tensor_list.append(_wrap(jnp.asarray(res[r])))
    return out_tensor_list


_PAIR_GROUPS: dict = {}


def _pair_group(a, b):
    """Cached 2-rank groups: send/recv must not build a fresh Group (and
    Mesh) per call."""
    key = (a, b) if a < b else (b, a)
    g = _PAIR_GROUPS.get(key)
    if g is None:
        g = new_group(list(key))
        _PAIR_GROUPS[key] = g
    return g


def send(tensor, dst=0, group=None, sync_op=True):
    """Point-to-point send.  Eager p2p between processes is realized as a
    cached sub-group broadcast (XLA collective-permute in-graph is the fast
    path — see functional.ppermute).  The world=1 degenerate path queues
    per (group, peer) so an unmatched send can't leak into an unrelated
    recv; `p2p_drained()` asserts the queues are empty."""
    _count_collective("send")
    group = group or _get_default_group()
    if group.nranks <= 1:
        _P2P_BUF.setdefault((id(group), dst), []).append(
            _as_array(tensor))
        return tensor
    return broadcast(tensor, src=_env.get_rank(),
                     group=_pair_group(_env.get_rank(), dst))


def recv(tensor, src=0, group=None, sync_op=True):
    _count_collective("recv")
    group = group or _get_default_group()
    if group.nranks <= 1:
        q = _P2P_BUF.get((id(group), _env.get_rank()))
        if q:
            tensor._data_ = q.pop(0)
        return tensor
    return broadcast(tensor, src=src,
                     group=_pair_group(src, _env.get_rank()))


_P2P_BUF: dict = {}   # (group id, dst rank) -> queued payloads (world=1)


def p2p_drained():
    """True when no world=1 send is waiting for its recv — call between
    tests/steps to catch unmatched p2p traffic."""
    return not any(_P2P_BUF.values())


def p2p_reset():
    _P2P_BUF.clear()


def barrier(group=None):
    """reference: communication/batch_isend_irecv.py barrier"""
    _count_collective("barrier")
    group = group or _get_default_group()
    if group.nranks <= 1:
        return
    tok = _wrap(jnp.zeros((1,), jnp.float32))
    all_reduce(tok, group=group)
    jax.block_until_ready(tok._data_)


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op, self.tensor, self.peer, self.group = op, tensor, peer, group


def batch_isend_irecv(p2p_op_list):
    for op in p2p_op_list:
        op.op(op.tensor, op.peer, group=op.group)
    return []
