"""Continuous-batching serving engine (paddle_tpu/serving/): slot KV
caches, admission control, deadlines, stats, clean shutdown."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTForCausalLM, gpt_config
from paddle_tpu.serving import (
    DeadlineExceededError, Engine, EngineShutdownError, QueueFullError,
    SamplingParams, ServingConfig, SlotKVCache, serving_stats,
)


def _np(t):
    return np.asarray(t._data_)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_config(
        "gpt2-124m", num_layers=2, hidden_size=128, num_heads=4,
        vocab_size=512, max_seq_len=64))
    m.eval()
    return m


def _prompts(lens, seed=0, vocab=512):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (n,)).astype("int32") for n in lens]


def _ref_greedy(model, prompt, max_new, eos_token_id=None):
    ids = model.generate(paddle.to_tensor(prompt[None, :]),
                         max_new_tokens=max_new, temperature=0.0,
                         eos_token_id=eos_token_id)
    return _np(ids)[0, prompt.size:]


def _wait_active(eng, n, timeout=60.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if serving_stats()["active_slots"] >= n:
            return
        time.sleep(0.01)
    raise AssertionError(f"engine never reached {n} active slot(s)")


def test_mixed_age_slots_match_sequential_greedy(model):
    """Five requests of different prompt lengths through 2 slots: every
    multi-tenant decode result must equal the per-request generate()
    greedy output, and the stats snapshot must be coherent."""
    prompts = _prompts([5, 9, 3, 7, 6])
    with Engine(model, ServingConfig(num_slots=2)) as eng:
        futs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        outs = [f.result(timeout=300) for f in futs]
        snap = eng.stats()
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(o.output_ids, _ref_greedy(model, p, 6))
        assert o.finish_reason == "length"
        assert o.ttft_ms > 0 and o.latency_ms >= o.ttft_ms
        np.testing.assert_array_equal(
            o.ids, np.concatenate([p, o.output_ids]))
    assert snap["requests_submitted"] == 5
    assert snap["requests_completed"] == 5
    assert snap["tokens_generated"] == 30
    assert snap["prefill_steps"] == 5
    # 5 requests x 5 post-prefill tokens over 2 slots needs >= 13 steps
    assert snap["decode_steps"] >= 13
    assert 0.0 < snap["slot_occupancy"] <= 1.0
    assert snap["ttft_ms_avg"] > 0 and snap["per_token_ms_avg"] > 0
    assert snap["tokens_per_sec"] > 0


def test_eos_slot_refill_mid_flight(model):
    """A request finishing on EOS frees its slot, which is refilled by a
    queued request WITHOUT draining the still-running batch."""
    pa, pb, pc = _prompts([5, 9, 3], seed=7)
    # eos := the 3rd greedy token of pa, so pa finishes a few steps in
    eos = int(_ref_greedy(model, pa, 3)[-1])
    with Engine(model, ServingConfig(num_slots=2)) as eng:
        fa = eng.submit(pa, max_new_tokens=20, eos_token_id=eos)
        fb = eng.submit(pb, max_new_tokens=12)
        fc = eng.submit(pc, max_new_tokens=6)      # waits for a slot
        oa, ob, oc = (f.result(timeout=300) for f in (fa, fb, fc))
    assert oa.finish_reason == "eos"
    assert oa.output_ids[-1] == eos and oa.output_ids.size <= 3
    np.testing.assert_array_equal(
        oa.output_ids, _ref_greedy(model, pa, 20, eos_token_id=eos))
    # b decoded straight through; c rode the refilled slot
    np.testing.assert_array_equal(ob.output_ids, _ref_greedy(model, pb, 12))
    np.testing.assert_array_equal(oc.output_ids, _ref_greedy(model, pc, 6))


def test_queue_full_rejection(model):
    (p,) = _prompts([5])
    eng = Engine(model, ServingConfig(num_slots=1, max_queue=1)).start()
    try:
        slow = eng.submit(p, max_new_tokens=40)
        _wait_active(eng, 1)                 # the slot is now occupied
        queued = eng.submit(p, max_new_tokens=2)   # fills the queue
        with pytest.raises(QueueFullError, match="queue is full"):
            eng.submit(p, max_new_tokens=2)
        assert serving_stats()["requests_rejected_queue_full"] == 1
        assert slow.result(timeout=300).output_ids.size == 40
        assert queued.result(timeout=300).output_ids.size == 2
    finally:
        eng.shutdown()


def test_deadline_eviction_frees_slot(model):
    (p,) = _prompts([5])
    with Engine(model, ServingConfig(num_slots=1)) as eng:
        doomed = eng.submit(p, max_new_tokens=10000, deadline_s=0.05)
        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=300)
        assert serving_stats()["requests_evicted_deadline"] == 1
        # the slot came back: a normal request completes
        ok = eng.submit(p, max_new_tokens=4).result(timeout=300)
        np.testing.assert_array_equal(ok.output_ids,
                                      _ref_greedy(model, p, 4))


def test_deadline_policy_ignore(model):
    (p,) = _prompts([5])
    with Engine(model, ServingConfig(num_slots=1,
                                     deadline_policy="ignore")) as eng:
        out = eng.submit(p, max_new_tokens=4,
                         deadline_s=0.0).result(timeout=300)
    assert out.finish_reason == "length"
    assert out.output_ids.size == 4


def test_clean_shutdown_with_inflight_requests(model):
    before = {t.ident for t in threading.enumerate()}
    prompts = _prompts([5, 7, 9])
    eng = Engine(model, ServingConfig(num_slots=1)).start()
    futs = [eng.submit(p, max_new_tokens=50) for p in prompts]
    _wait_active(eng, 1)
    eng.shutdown()
    # every future resolves promptly: completed or EngineShutdownError
    shut = 0
    for f in futs:
        assert f.done()
        if f.exception() is not None:
            assert isinstance(f.exception(), EngineShutdownError)
            shut += 1
    assert shut >= 1                 # 150 tokens >> time before shutdown
    leaked = {t.ident for t in threading.enumerate()} - before
    assert not leaked
    # a dead engine rejects new work instead of hanging clients
    with pytest.raises(EngineShutdownError):
        eng.submit(prompts[0])


def test_per_request_sampling_params(model):
    """Slots apply each request's own processor chain: one greedy + one
    sampled request coexist in the batch."""
    pg, ps = _prompts([5, 6], seed=3)
    with Engine(model, ServingConfig(num_slots=2)) as eng:
        fg = eng.submit(pg, max_new_tokens=5)
        fs = eng.submit(ps, max_new_tokens=5, sampling=SamplingParams(
            temperature=0.8, top_k=20, repetition_penalty=1.3))
        og, os_ = fg.result(timeout=300), fs.result(timeout=300)
    np.testing.assert_array_equal(og.output_ids, _ref_greedy(model, pg, 5))
    assert os_.output_ids.size == 5
    assert (os_.output_ids >= 0).all() and (os_.output_ids < 512).all()


def test_submit_validation_and_capacity(model):
    with Engine(model, ServingConfig(num_slots=1)) as eng:
        with pytest.raises(ValueError, match="empty"):
            eng.submit(np.zeros(0, np.int32))
        with pytest.raises(ValueError, match="no room"):
            eng.submit(np.zeros(64, np.int32))       # == max_seq_len
        with pytest.raises(ValueError, match="top_p"):
            eng.submit(np.zeros(4, np.int32),
                       sampling=SamplingParams(temperature=1.0, top_p=0.0))
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit(np.zeros(4, np.int32), max_new_tokens=0)
        # a prompt that fills all-but-one position finishes by capacity
        (p,) = _prompts([5])
        out = eng.submit(np.zeros(63, np.int32),
                         max_new_tokens=50).result(timeout=300)
        assert out.finish_reason == "length"
        assert out.output_ids.size == 1              # 63 + 1 == capacity
    with pytest.raises(ValueError, match="num_slots"):
        Engine(model, ServingConfig(num_slots=0))
    with pytest.raises(ValueError, match="deadline_policy"):
        Engine(model, ServingConfig(deadline_policy="nope"))


def test_slot_kv_cache_bookkeeping():
    cache = SlotKVCache(num_layers=2, num_slots=3, max_len=8,
                        num_kv_heads=2, head_dim=4)
    assert cache.free_slots == 3
    s0, s1 = cache.allocate(), cache.allocate()
    assert {s0, s1} == {0, 1} and cache.free_slots == 1
    cache.release(s0)
    with pytest.raises(ValueError, match="already free"):
        cache.release(s0)
    assert cache.free_slots == 2
    assert cache.allocate() in (s0, 2)
    with pytest.raises(ValueError, match="capacity"):
        cache.write_prefill(s1, [], 9)
    # offsets propagate to every layer as one shared [num_slots] tensor
    cache.offsets[s1] = 5
    cache.advance([s1])
    offs = _np(cache.layer_caches()[0]["offset"])
    assert offs[s1] == 6
    assert cache.layer_caches()[0]["offset"] is \
        cache.layer_caches()[1]["offset"]


def test_monitor_thread_safety():
    """Satellite: utils.monitor incr/observe/all_stats race-free under
    concurrent writers (the serving scheduler vs stat readers)."""
    from paddle_tpu.utils import monitor
    monitor.reset("t.counter")
    monitor.reset("t.lat.sum")
    monitor.reset("t.lat.count")
    errs = []

    def worker():
        try:
            for _ in range(500):
                monitor.incr("t.counter")
                monitor.observe("t.lat", 2.0)
                monitor.all_stats()
        except Exception as e:          # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert monitor.get_monitor_value("t.counter") == 8 * 500
    assert monitor.get_monitor_value("t.lat.count") == 8 * 500
    assert monitor.get_monitor_value("t.lat.sum") == 8 * 500 * 2.0
    for k in ("t.counter", "t.lat.sum", "t.lat.count"):
        monitor.reset(k)


def test_predictor_pool_and_config_validation(tmp_path):
    """Satellite: PredictorPool.retrieve names the pool size on a bad
    index; Config rejects nonexistent model paths at construction."""
    from paddle_tpu import inference, nn, static

    with pytest.raises(FileNotFoundError, match="does not exist"):
        inference.Config(str(tmp_path / "nope"))
    with pytest.raises(FileNotFoundError, match="nope.onnx"):
        inference.Config(str(tmp_path / "nope.onnx"))

    prefix = str(tmp_path / "m")
    static.save_inference_model(
        prefix, [static.InputSpec([1, 4], "float32", "x")], None,
        layer=nn.Linear(4, 2))
    pool = inference.PredictorPool(inference.Config(prefix), size=2)
    assert pool.retrieve(1) is not None
    with pytest.raises(IndexError, match="holds 2 predictor"):
        pool.retrieve(2)
    with pytest.raises(IndexError, match="0..1"):
        pool.retrieve(-1)


def test_serving_with_llama_gqa():
    """Per-slot offsets through the rope + GQA decode path (llama):
    mixed-age slot decode equals per-request greedy."""
    from paddle_tpu.models import LlamaForCausalLM, llama_config
    paddle.seed(3)
    llama = LlamaForCausalLM(llama_config("tiny", max_seq_len=64))
    llama.eval()
    prompts = _prompts([4, 8, 6], seed=11)
    with Engine(llama, ServingConfig(num_slots=2)) as eng:
        futs = [eng.submit(p, max_new_tokens=5) for p in prompts]
        outs = [f.result(timeout=300) for f in futs]
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(o.output_ids,
                                      _ref_greedy(llama, p, 5))


def test_profiler_captures_serving_spans(model):
    """serving::prefill / serving::decode spans land in profiler traces
    (the scheduler thread is instrumented like any op dispatch)."""
    from paddle_tpu.profiler import Profiler, ProfilerTarget
    (p,) = _prompts([5])
    prof = Profiler(targets=[ProfilerTarget.CPU], timer_only=True)
    prof.start()
    try:
        with Engine(model, ServingConfig(num_slots=1)) as eng:
            eng.submit(p, max_new_tokens=4).result(timeout=300)
    finally:
        prof.stop()
    names = {e["name"] for e in prof.events}
    assert "serving::prefill" in names
    assert "serving::decode" in names
