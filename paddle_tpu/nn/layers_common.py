"""Common layers (reference: python/paddle/nn/layer/{common,conv,norm}.py)."""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from .layer import Layer
from . import functional as F
from .initializer import (Constant, Normal, XavierNormal, KaimingUniform,
                          Uniform, _apply_initializer)
from ..core.tensor import Tensor, Parameter
from ..core import dtype as _dtype
from ..tensor_ops import creation


class Linear(Layer):
    """y = xW + b, W: [in_features, out_features] (reference layout)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierNormal())
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (out_features,), attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in={self.in_features}, out={self.out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=Normal(0.0, 1.0))
        if padding_idx is not None:
            self.weight._data = self.weight._data.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)


class Conv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, (tuple, list)) \
            else (kernel_size, kernel_size)
        self._stride, self._padding, self._dilation = stride, padding, dilation
        self._groups, self._data_format = groups, data_format
        fan_in = in_channels * k[0] * k[1] // groups
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, k[0], k[1]),
            attr=weight_attr,
            default_initializer=Uniform(-math.sqrt(1 / fan_in),
                                        math.sqrt(1 / fan_in)))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (out_channels,), attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, stride=self._stride,
                        padding=self._padding, dilation=self._dilation,
                        groups=self._groups, data_format=self._data_format)


class Conv1D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, (tuple, list)) \
            else (kernel_size,)
        self._cfg = (stride, padding, dilation, groups, data_format)
        fan_in = in_channels * k[0] // groups
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, k[0]), attr=weight_attr,
            default_initializer=Uniform(-math.sqrt(1 / fan_in),
                                        math.sqrt(1 / fan_in)))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        s, p, d, g, df = self._cfg
        return F.conv1d(x, self.weight, self.bias, stride=s, padding=p,
                        dilation=d, groups=g, data_format=df)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, (tuple, list)) \
            else (kernel_size, kernel_size)
        self._cfg = (stride, padding, output_padding, groups, dilation, data_format)
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups, k[0], k[1]),
            attr=weight_attr, default_initializer=XavierNormal())
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        s, p, op, g, d, df = self._cfg
        return F.conv2d_transpose(x, self.weight, self.bias, stride=s,
                                  padding=p, output_padding=op, groups=g,
                                  dilation=d, data_format=df)


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)


class RMSNorm(Layer):
    """reference capability: rms_norm kernel (paddle/phi/kernels/gpu/
    rms_norm_kernel.cu); here the Pallas/XLA fused path."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=weight_attr,
            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class BatchNorm2D(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._momentum, self._epsilon = momentum, epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None
        self.register_buffer("_mean", creation.zeros((num_features,)))
        self.register_buffer("_variance", creation.ones((num_features,)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)


BatchNorm1D = BatchNorm2D
BatchNorm3D = BatchNorm2D


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training,
                           data_format=self.data_format)


# activations as layers
def _act_layer(fn_name, **defaults):
    fn = getattr(F, fn_name)

    class _Act(Layer):
        def __init__(self, name=None, **kw):
            super().__init__()
            self._kw = {**defaults, **kw}

        def forward(self, x):
            return fn(x, **self._kw)
    _Act.__name__ = fn_name.title().replace("_", "")
    return _Act


ReLU = _act_layer("relu")
ReLU6 = _act_layer("relu6")
GELU = _act_layer("gelu")
Silu = _act_layer("silu")
Sigmoid = _act_layer("sigmoid") if hasattr(F, "sigmoid") else None
LeakyReLU = _act_layer("leaky_relu")
ELU = _act_layer("elu")
SELU = _act_layer("selu")
Hardswish = _act_layer("hardswish")
Hardsigmoid = _act_layer("hardsigmoid")
Softplus = _act_layer("softplus")
Softshrink = _act_layer("softshrink")
Hardshrink = _act_layer("hardshrink")
Tanhshrink = _act_layer("tanhshrink")
Mish = _act_layer("mish")
Softsign = _act_layer("softsign")


class Tanh(Layer):
    def forward(self, x):
        from ..tensor_ops import math as M
        return M.tanh(x)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self._data_format)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCHW", name=None):
        super().__init__()
        self._cfg = (kernel_size, stride, padding, ceil_mode, data_format)

    def forward(self, x):
        k, s, p, cm, df = self._cfg
        return F.max_pool2d(x, k, s, p, ceil_mode=cm, data_format=df)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._cfg = (kernel_size, stride, padding, ceil_mode, exclusive,
                     divisor_override, data_format)

    def forward(self, x):
        k, s, p, cm, ex, dv, df = self._cfg
        return F.avg_pool2d(x, k, s, p, ceil_mode=cm, exclusive=ex,
                            divisor_override=dv, data_format=df)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._output_size, self._data_format)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ..tensor_ops.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self._cfg = (size, scale_factor, mode, align_corners, align_mode,
                     data_format)

    def forward(self, x):
        size, sf, mode, ac, am, df = self._cfg
        return F.interpolate(x, size, sf, mode, ac, am, df)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self._cfg = (padding, mode, value, data_format)

    def forward(self, x):
        p, m, v, df = self._cfg
        return F.pad(x, p, m, v, df)
