"""Training-sentinel drill worker (docs/RESILIENCE.md).

Modes (argv[1]):

- ``rollback <outdir>``: single process.  Trains a tiny regression fit
  with the sentinel armed while ``FLAGS_fault_inject`` (set by the
  caller, e.g. ``loss_spike:at_step=7,scale=1e6``) poisons one step.
  Writes ``report.json`` (sentinel report + final weights) and a
  sentinel dump under the caller's ``FLAGS_sentinel_dump_path``.

- ``blame <outdir>``: 2-process (launched by CollectiveController).
  Rank 1's gradients are repeatedly corrupted via ``grad_bitflip``; the
  sentinel must skip the poisoned steps globally, attribute the
  anomalies to rank 1 locally, publish blame over the guardian store,
  and escalate with SentinelError.  Each rank writes
  ``blame_report.<rank>.json``.
"""
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

MODE = sys.argv[1]
OUTDIR = sys.argv[2]

if MODE == "blame":
    jax.distributed.initialize(
        coordinator_address=os.environ["PADDLE_MASTER"],
        num_processes=int(os.environ["WORLD_SIZE"]),
        process_id=int(os.environ["PADDLE_TRAINER_ID"]))

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu import nn  # noqa: E402
from paddle_tpu.framework.sentinel import SentinelError  # noqa: E402


class ToyData:
    """Deterministic per-index regression batches."""

    def __init__(self, n=48):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        rng = np.random.default_rng(i)
        x = rng.normal(size=(8,)).astype(np.float32)
        return x, np.tanh(np.sum(x, keepdims=True)).astype(np.float32)


def build():
    paddle.seed(3)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.AdamW(0.01,
                                         parameters=net.parameters()),
        loss=nn.MSELoss())
    return model, net


def grab_sentinel(model):
    holder = {}
    orig = paddle.Model._install_sentinel

    def patched(self, cb):
        s = orig(self, cb)
        holder["sentinel"] = s
        return s

    paddle.Model._install_sentinel = patched
    return holder


def main():
    os.makedirs(OUTDIR, exist_ok=True)
    if MODE == "rollback":
        paddle.set_flags({
            "FLAGS_sentinel": True,
            "FLAGS_compiled_train_step": False,   # loss_spike is an
            "FLAGS_sentinel_check_every": 4,      # eager-lane seam
            "FLAGS_sentinel_anchor_every": 4,
        })
        model, net = build()
        holder = grab_sentinel(model)
        model.fit(ToyData(), batch_size=4, epochs=1, verbose=0,
                  shuffle=False, save_dir=os.path.join(OUTDIR, "ckpts"))
        sen = holder["sentinel"]
        report = sen.report()
        sen.dump(action="rollback", step=report["quarantined"][0]
                 if report["quarantined"] else 0,
                 anchor_step=report["anchor_it"])
        weights = {k: np.asarray(v._data_).tolist()
                   for k, v in net.state_dict().items()}
        with open(os.path.join(OUTDIR, "report.json"), "w") as f:
            json.dump({"report": report, "weights": weights}, f)
        return 0

    if MODE == "blame":
        import paddle_tpu.distributed as dist
        dist.init_parallel_env()
        rank = dist.get_rank()
        paddle.set_flags({
            "FLAGS_sentinel": True,
            "FLAGS_sentinel_check_every": 2,
            "FLAGS_sentinel_max_skips": 3,
            "FLAGS_fault_inject": "grad_bitflip:rank=1,count=6",
        })
        model, net = build()
        holder = grab_sentinel(model)
        outcome = "completed"
        try:
            model.fit(ToyData(32), batch_size=4, epochs=2, verbose=0,
                      shuffle=False)
        except SentinelError as e:
            outcome = f"sentinel-error: {e}"
        sen = holder["sentinel"]
        with open(os.path.join(OUTDIR, f"blame_report.{rank}.json"),
                  "w") as f:
            json.dump({"rank": rank, "outcome": outcome,
                       "report": sen.report()}, f)
        return 0

    raise SystemExit(f"unknown mode {MODE!r}")


if __name__ == "__main__":
    sys.exit(main())
