"""Parameter-server stack (TPU-native analog).

Reference capability: the PS training mode —
paddle/fluid/distributed/ps/service/brpc_ps_server.{h,cc} (brpc servers
hosting sparse/dense tables), brpc_ps_client, table storage
(ps/table/memory_sparse_table), and the python runtime
`TheOnePSRuntime` (python/paddle/distributed/ps/the_one_ps.py:1027 —
build tables from the strategy, server/worker lifecycle).

TPU-native realization: the dense compute path belongs on the TPU via
SPMD — a PS is only warranted for host-resident *sparse* state too large
for HBM (recommender embeddings).  So the tables live in host memory on
server processes; transport is the stdlib authenticated-TCP channel the
RPC module already uses (brpc is not in this image); workers pull rows
before the device step and push gradients after it.  `PSEmbedding` wires
that into the eager layer API: pull on forward, push via a gradient hook
on backward — the DistributedLookupTable analog.
"""
from __future__ import annotations

import pickle
import threading
import time

import numpy as np
import jax.numpy as jnp

from multiprocessing.connection import Listener, Client

from ...utils import monitor
from ...utils.log import get_logger

_AUTHKEY = b"paddle_tpu_ps"
log = get_logger("paddle_tpu.ps")


# ------------------------------------------------------------------
# tables (reference: ps/table/ memory_dense_table / memory_sparse_table)
# ------------------------------------------------------------------

class DenseTable:
    def __init__(self, shape, lr=0.1, optimizer="sgd", init=None):
        self.value = (np.zeros(shape, np.float32) if init is None
                      else np.array(init, np.float32))
        self.lr = lr
        self.optimizer = optimizer
        self._accum = np.zeros_like(self.value)  # adagrad accumulator

    def pull(self):
        return self.value

    def push(self, grad):
        grad = np.asarray(grad, np.float32)
        if self.optimizer == "adagrad":
            self._accum += grad * grad
            self.value -= self.lr * grad / (np.sqrt(self._accum) + 1e-8)
        else:
            self.value -= self.lr * grad


class _RowsView:
    """dict-like facade over the slab for call sites that address rows
    individually (the geo client, tests).  Reads COPY out: the slab
    reallocates as it grows, so a held view would silently detach —
    mutate through push/apply_delta or item assignment, never through a
    read result."""

    def __init__(self, table):
        self._t = table

    def __getitem__(self, k):
        return self._t._data[self._t._slot[int(k)]].copy()

    def __setitem__(self, k, v):
        t = self._t
        sl = t._slots([int(k)])
        t._data[sl[0]] = v

    def get(self, k, default=None):
        s = self._t._slot.get(int(k))
        return default if s is None else self._t._data[s].copy()

    def __contains__(self, k):
        return int(k) in self._t._slot

    def __len__(self):
        return len(self._t._slot)

    def __iter__(self):
        return iter(self._t._slot)

    def items(self):
        for k, s in self._t._slot.items():
            yield k, self._t._data[s].copy()


class SparseTable:
    """id → row; rows are created on first pull (reference:
    memory_sparse_table lazy init).

    Storage is a growable [capacity, dim] float32 slab plus an id→slot
    dict, so a server-side batch pull is ONE fancy-index gather and a
    push ONE scatter (np.subtract.at) — the vectorization that lets the
    wire transport run at memory speed instead of python-per-row speed
    (reference bar: brpc_ps_server's batched table ops)."""

    def __init__(self, dim, lr=0.1, optimizer="sgd", initializer=None,
                 seed=0):
        self.dim = dim
        self.lr = lr
        self.optimizer = optimizer
        self._slot: dict[int, int] = {}
        self._data = np.zeros((0, dim), np.float32)
        self._acc = np.zeros((0, dim), np.float32)
        self.rows = _RowsView(self)
        self._rng = np.random.default_rng(seed)
        self._init = initializer or (
            lambda: (self._rng.standard_normal(dim) * 0.01)
            .astype(np.float32))

    def _slots(self, ids, create=True):
        """Resolve ids to slab slots, materializing missing rows."""
        slot = self._slot
        out = np.empty(len(ids), np.int64)
        missing = []
        for i, k in enumerate(ids):
            s = slot.get(int(k), -1)
            out[i] = s
            if s < 0:
                missing.append(i)
        if not missing:
            return out
        if not create:
            raise KeyError(int(ids[missing[0]]))
        for i in missing:
            k = int(ids[i])
            s = slot.get(k)
            if s is None:                    # first sight (dedup repeats)
                s = len(slot)
                if s >= len(self._data):
                    cap = max(64, 2 * len(self._data))
                    grown = np.zeros((cap, self.dim), np.float32)
                    grown[:s] = self._data[:s]
                    self._data = grown
                    if self.optimizer == "adagrad":
                        ga = np.zeros((cap, self.dim), np.float32)
                        ga[:s] = self._acc[:s]
                        self._acc = ga
                slot[k] = s
                self._data[s] = self._init()
            out[i] = s
        return out

    def pull(self, ids):
        sl = self._slots(ids)
        return self._data[sl]

    def push(self, ids, grads):
        grads = np.asarray(grads, np.float32)
        sl = self._slots(ids)
        if self.optimizer == "adagrad":
            if len(np.unique(sl)) != len(sl):
                # duplicate ids in one batch: keep per-row sequential
                # semantics (accumulator updates feed later rows)
                for s, g in zip(sl, grads):
                    self._acc[s] += g * g
                    self._data[s] -= self.lr * g / (
                        np.sqrt(self._acc[s]) + 1e-8)
            else:
                self._acc[sl] += grads * grads
                self._data[sl] -= self.lr * grads / (
                    np.sqrt(self._acc[sl]) + 1e-8)
        else:
            # scatter-subtract sums duplicate-id updates, matching the
            # sequential SGD result exactly
            np.subtract.at(self._data, sl, self.lr * grads)

    def apply_delta(self, ids, deltas):
        """row += delta — the geo-SGD merge op (reference: geo mode sends
        parameter diffs, not gradients; the_one_ps.py geo strategy)."""
        deltas = np.asarray(deltas, np.float32)
        sl = self._slots(ids)
        np.add.at(self._data, sl, deltas)

    def all_rows(self):
        """Materialize every live row (checkpoint/save path)."""
        return {k: self._data[s].copy() for k, s in self._slot.items()}


_REC_MAGIC = b"PTS2"
_REC_HDR = __import__("struct").Struct("<4sqI")  # magic, key i64, crc32
# one-time superblock at the head of log and WAL files: geometry guard —
# reopening with a different dim/optimizer must ERROR, not mis-scan (a
# crc mismatch from wrong record framing would silently truncate to zero)
_SB_MAGIC = b"PTSH"
_SB = __import__("struct").Struct("<4sIII")      # magic, version, planes, dim


class SSDSparseTable(SparseTable):
    """Two-tier sparse table: hot rows in an LRU RAM cache, cold rows in
    a log-structured disk file — host tables larger than RAM.

    Reference capability: the SSD/hierarchical table tier —
    paddle/fluid/distributed/ps/table/ssd_sparse_table.{h,cc} (rocksdb
    cold tier under memory_sparse_table) and the HeterPS pull path that
    stages cold rows upward (paddle/fluid/framework/fleet/
    ps_gpu_wrapper.h:114).  rocksdb is not in this image, so the cold
    store is an append-only record log with an in-RAM {id → offset}
    index and threshold-triggered compaction: same capability, stdlib
    machinery.  Updates hit the cache; eviction appends the fresh record
    and abandons the old one (`_dead_bytes`); compaction rewrites live
    records when dead bytes exceed live bytes.

    Crash story (the rocksdb-WAL analog): every record carries a
    [magic, key, crc32] header, so reopening an existing path rebuilds
    the index by scanning the log (later records win) and TRUNCATES a
    torn tail at the first bad magic/crc.  Hot-tier mutations
    write-ahead the full post-update row to `<path>.wal` before the
    push/apply_delta returns; recovery replays the WAL over the
    rebuilt index, so acknowledged updates survive a killed process.
    flush() spills dirty rows, fsyncs the log, and truncates the WAL
    (also triggered automatically when the WAL outgrows the live log).
    """

    def __init__(self, dim, lr=0.1, optimizer="sgd", initializer=None,
                 seed=0, cache_rows=4096, path=None, wal=True):
        super().__init__(dim, lr=lr, optimizer=optimizer,
                         initializer=initializer, seed=seed)
        import collections
        import os
        import tempfile
        self.rows = collections.OrderedDict()   # hot tier (LRU)
        self._accum = collections.OrderedDict()
        self.cache_rows = int(cache_rows)
        self._with_accum = (optimizer == "adagrad")
        self._planes = 2 if self._with_accum else 1
        self._rec_bytes = self._planes * dim * 4
        self._rec_total = _REC_HDR.size + self._rec_bytes
        if path is None:
            fd, self.path = tempfile.mkstemp(
                prefix="paddle_tpu_ssd_table_", suffix=".bin")
            self._file = os.fdopen(fd, "r+b")
        else:
            self.path = path
            self._file = open(path, "a+b")
        self._index: dict[int, int] = {}  # id → record offset (cold tier)
        self._end = 0
        self._dead_bytes = 0
        self._dirty: set[int] = set()  # hot rows mutated since load/spill
        self._recover_log()
        self._wal_path = self.path + ".wal"
        self._wal = None
        self._wal_bytes = 0
        if wal:
            self._replay_wal()
            self._wal = open(self._wal_path, "ab")
            if self._wal.tell() == 0:
                self._wal.write(_SB.pack(_SB_MAGIC, 1, self._planes,
                                         self.dim))
                self._wal.flush()
            self._wal_bytes = self._wal.tell()
        elif os.path.exists(self._wal_path) and \
                os.path.getsize(self._wal_path) > _SB.size:
            # a leftover WAL holds acknowledged-but-unflushed updates;
            # silently skipping it would drop them now AND replay the
            # stale entries over newer state at a later wal=True open
            raise ValueError(
                f"a write-ahead log with pending updates exists at "
                f"{self._wal_path}; open with wal=True to recover it, "
                f"or delete it to discard those updates")

    # -- cold-tier record IO ------------------------------------------
    def _pack_record(self, key, row, acc):
        import zlib
        payload = (np.concatenate([row, acc]) if self._with_accum
                   else np.asarray(row)).astype(np.float32).tobytes()
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        return _REC_HDR.pack(_REC_MAGIC, int(key), crc) + payload

    def _write_record(self, key, row, acc):
        off = self._end
        self._file.seek(off)
        self._file.write(self._pack_record(key, row, acc))
        self._end = off + self._rec_total
        if key in self._index:
            self._dead_bytes += self._rec_total
        self._index[key] = off

    def _read_record(self, off):
        self._file.seek(off + _REC_HDR.size)
        rec = np.frombuffer(self._file.read(self._rec_bytes),
                            np.float32).copy()
        if self._with_accum:
            return rec[:self.dim], rec[self.dim:]
        return rec, None

    def _check_superblock(self, f, what):
        """Validate (or write, when the file is empty) the geometry
        superblock.  Returns the scan start offset."""
        f.seek(0, 2)
        if f.tell() == 0:
            f.seek(0)
            f.write(_SB.pack(_SB_MAGIC, 1, self._planes, self.dim))
            f.flush()
            return _SB.size
        f.seek(0)
        head = f.read(_SB.size)
        try:
            magic, version, planes, dim = _SB.unpack(head)
        except Exception:
            magic = None
        if magic != _SB_MAGIC:
            raise ValueError(
                f"{what} at {self.path!r} is not a PTSH table file")
        if planes != self._planes or dim != self.dim:
            raise ValueError(
                f"{what} geometry mismatch: file has dim={dim} "
                f"planes={planes}, table configured dim={self.dim} "
                f"planes={self._planes} (optimizer={self.optimizer!r}) — "
                f"reopen with the original configuration")
        return _SB.size

    def _scan_log(self, f, on_record, start):
        """Walk [header|payload] records from `start`; returns the offset
        of the first torn/invalid record (= valid length)."""
        import zlib
        f.seek(0, 2)
        end = f.tell()
        off = start
        while off + self._rec_total <= end:
            f.seek(off)
            hdr = f.read(_REC_HDR.size)
            try:
                magic, key, crc = _REC_HDR.unpack(hdr)
            except Exception:
                break
            if magic != _REC_MAGIC:
                break
            payload = f.read(self._rec_bytes)
            if len(payload) < self._rec_bytes or \
                    (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                break
            on_record(key, off, payload)
            off += self._rec_total
        return off

    def _recover_log(self):
        """Rebuild the {id → offset} index by scanning the log (later
        records win, counting superseded ones as dead bytes) and truncate
        a torn tail — reopening after a crash loses nothing that reached
        the log."""
        start = self._check_superblock(self._file, "sparse-table log")

        def seen(key, off, _payload):
            if key in self._index:
                self._dead_bytes += self._rec_total
            self._index[key] = off

        valid = self._scan_log(self._file, seen, start)
        self._end = valid
        self._file.truncate(valid)

    def _replay_wal(self):
        """Apply write-ahead entries (full post-update row states) over
        the rebuilt index, then truncate the WAL's own torn tail — a new
        process appending after garbage would make its acknowledged
        updates unrecoverable (the scan stops at the tear)."""
        import os
        if not os.path.exists(self._wal_path):
            return
        with open(self._wal_path, "r+b") as w:
            start = self._check_superblock(w, "write-ahead log")

            def apply(key, _off, payload):
                rec = np.frombuffer(payload, np.float32).copy()
                if self._with_accum:
                    self.rows[key] = rec[:self.dim]
                    self._accum[key] = rec[self.dim:]
                else:
                    self.rows[key] = rec
                self.rows.move_to_end(key)
                self._dirty.add(key)

            valid = self._scan_log(w, apply, start)
            w.truncate(valid)
        self._evict_to_fit()

    def _wal_append(self, key, row, acc):
        if self._wal is None:
            return
        self._wal.write(self._pack_record(key, row, acc))
        self._wal_bytes += self._rec_total
        live = max(self._end - self._dead_bytes, 1 << 16)
        if self._wal_bytes > max(live, 1 << 20):
            self.flush()

    def _wal_sync(self):
        """Flush WAL bytes to the OS before a push/apply batch returns:
        the OS page cache survives a killed process (the ack contract),
        while python's userspace buffer does not.  fsync (machine-crash
        durability) is deliberately left to flush()."""
        if self._wal is not None:
            self._wal.flush()

    def flush(self):
        """Spill every dirty hot row to the log, fsync it, and truncate
        the WAL — the durable-checkpoint op (rocksdb Flush analog)."""
        import os
        for key in list(self._dirty):
            row = self.rows.get(key)
            if row is None:
                self._dirty.discard(key)
                continue
            acc = self._accum.get(key)
            if acc is None and self._with_accum:
                acc = np.zeros(self.dim, np.float32)
            self._write_record(key, row, acc)
            self._dirty.discard(key)
        self._file.flush()
        try:
            os.fsync(self._file.fileno())
        except OSError:
            pass
        if self._wal is not None:
            self._wal.truncate(_SB.size)   # keep the geometry superblock
            self._wal.flush()
            self._wal_bytes = _SB.size

    def _evict_to_fit(self):
        while len(self.rows) > self.cache_rows:
            key, row = self.rows.popitem(last=False)
            acc = self._accum.pop(key, None)
            # clean eviction of a row that already has a cold copy costs
            # zero IO — only mutated (or never-spilled) rows are written
            if key in self._dirty or key not in self._index:
                if acc is None and self._with_accum:
                    acc = np.zeros(self.dim, np.float32)
                self._write_record(key, row, acc)
            self._dirty.discard(key)
        live = self._end - self._dead_bytes
        if self._dead_bytes > max(live, 1 << 16):
            self.compact()

    def compact(self):
        """Rewrite live records into a sidecar file, then swap it in —
        memory stays O(one record), since the cold tier may exceed RAM."""
        import os
        tmp_path = self.path + ".compact"
        new_index = {}
        off = _SB.size
        with open(tmp_path, "w+b") as out:
            out.write(_SB.pack(_SB_MAGIC, 1, self._planes, self.dim))
            for key, old in self._index.items():
                self._file.seek(old)
                out.write(self._file.read(self._rec_total))
                new_index[key] = off
                off += self._rec_total
        self._file.close()
        os.replace(tmp_path, self.path)
        self._file = open(self.path, "r+b")
        self._index, self._end, self._dead_bytes = new_index, off, 0

    # -- hot-tier access ----------------------------------------------
    def _fetch(self, key, create=True):
        row = self.rows.get(key)
        if row is not None:
            self.rows.move_to_end(key)
            return row
        off = self._index.get(key)
        if off is not None:
            row, acc = self._read_record(off)
            self.rows[key] = row
            if self._with_accum:
                self._accum[key] = acc
            return row
        if not create:
            return None
        row = self._init()
        self.rows[key] = row
        if self._with_accum:
            self._accum[key] = np.zeros(self.dim, np.float32)
        # creation is a visible state change: flush() must persist rows a
        # worker pulled and trained against, and recovery must not redraw
        # them from a differently-positioned RNG stream
        self._dirty.add(key)
        self._wal_append(key, row, self._accum.get(key))
        return row

    def pull(self, ids):
        out = np.empty((len(ids), self.dim), np.float32)
        for i, key in enumerate(ids):
            out[i] = self._fetch(int(key))
        self._wal_sync()    # row creations above are WAL'd
        self._evict_to_fit()
        return out

    def push(self, ids, grads):
        grads = np.asarray(grads, np.float32)
        for key, g in zip(ids, grads):
            key = int(key)
            row = self._fetch(key)
            if self._with_accum:
                acc = self._accum[key]
                acc += g * g
                row -= self.lr * g / (np.sqrt(acc) + 1e-8)
            else:
                acc = None
                row -= self.lr * g
            self._dirty.add(key)
            self._wal_append(key, row, acc)
        self._wal_sync()
        self._evict_to_fit()

    def apply_delta(self, ids, deltas):
        deltas = np.asarray(deltas, np.float32)
        for key, d in zip(ids, deltas):
            key = int(key)
            self._fetch(key)
            self.rows[key] += d
            self._dirty.add(key)
            self._wal_append(key, self.rows[key],
                             self._accum.get(key) if self._with_accum
                             else None)
        self._wal_sync()
        self._evict_to_fit()

    @property
    def num_cold_rows(self):
        return sum(1 for k in self._index if k not in self.rows)

    def all_rows(self):
        out = {}
        for key, off in self._index.items():
            row, _ = self._read_record(off)
            out[key] = row
        out.update(self.rows)   # hot tier is authoritative
        return out

    def close(self):
        try:
            self.flush()
        except (OSError, ValueError):
            pass
        for f in (self._file, self._wal):
            try:
                if f is not None:
                    f.close()
            except OSError:
                pass


# ------------------------------------------------------------------
# server / client (reference: brpc_ps_server / brpc_ps_client)
# ------------------------------------------------------------------

# binary frames for the hot table ops: [op u8 | table_id i32 | n_ids u32]
# + ids (int64 raw) + payload (float32 raw).  Responses: [status u8] +
# raw float32 rows (pulls) / empty (pushes) / pickle (save, errors, the
# infrequent dense+control ops, which ride op 0 as a pickled dict).
# Replaces per-request dict pickling — the difference between ~20 MB/s
# and memory-speed loopback (reference bar: brpc's zero-copy IOBuf,
# ps/service/brpc_ps_client).
_FRAME = __import__("struct").Struct("<BiI")
_OP_PICKLED = 0
_OP_PULL_SPARSE = 3
_OP_PUSH_SPARSE = 4
_OP_PUSH_DELTA = 5
_ST_OK = b"\x00"
_ST_ERR = b"\x01"
_PULL_DIM = __import__("struct").Struct("<I")   # row dim in pull responses


def _set_nodelay(conn):
    """Disable Nagle on a multiprocessing Connection's TCP socket: the
    request/response pattern (small frame one way, megabyte of rows the
    other) otherwise hits the 40 ms delayed-ACK stall on every pull."""
    import socket
    try:
        s = socket.socket(fileno=conn.fileno())
    except OSError:
        return
    try:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass
    finally:
        s.detach()   # release without closing the shared fd


class PSServer:
    """Hosts tables, serves pull/push over authenticated TCP."""

    def __init__(self, address=("127.0.0.1", 0)):
        self.tables: dict[int, object] = {}
        self._listener = Listener(address, authkey=_AUTHKEY)
        self.address = self._listener.address
        self._stop = threading.Event()
        self._threads = []
        self._lock = threading.Lock()
        self._accept_thread = None

    def add_dense_table(self, table_id, shape, **kw):
        self.tables[table_id] = DenseTable(shape, **kw)

    def add_sparse_table(self, table_id, dim, **kw):
        self.tables[table_id] = SparseTable(dim, **kw)

    def add_ssd_sparse_table(self, table_id, dim, **kw):
        self.tables[table_id] = SSDSparseTable(dim, **kw)

    def start(self):
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn = self._listener.accept()
            except OSError:
                return
            _set_nodelay(conn)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn):
        try:
            while not self._stop.is_set():
                try:
                    buf = conn.recv_bytes()
                except EOFError:
                    return
                if buf and buf[0] != _OP_PICKLED:
                    self._serve_binary(conn, buf)
                    continue
                req = pickle.loads(memoryview(buf)[1:])
                op = req["op"]
                if op == "stop":
                    conn.send_bytes(_ST_OK + pickle.dumps({"ok": True}))
                    self._stop.set()
                    try:
                        self._listener.close()
                    except OSError:
                        pass
                    return
                table = self.tables.get(req.get("table_id"))
                # every request gets a response — a table-op error must
                # come back as {"ok": False}, never kill the handler and
                # leave the client blocked in recv()
                try:
                    with self._lock:
                        if op in ("pull_dense", "push_dense",
                                  "pull_sparse", "push_sparse",
                                  "push_sparse_delta") and \
                                table is None:
                            resp = {"ok": False,
                                    "error": f"no table "
                                             f"{req.get('table_id')!r}"}
                        elif op == "pull_dense":
                            resp = {"ok": True, "value": table.pull()}
                        elif op == "push_dense":
                            table.push(req["grad"])
                            resp = {"ok": True}
                        elif op == "pull_sparse":
                            resp = {"ok": True,
                                    "value": table.pull(req["ids"])}
                        elif op == "push_sparse":
                            table.push(req["ids"], req["grad"])
                            resp = {"ok": True}
                        elif op == "push_sparse_delta":
                            table.apply_delta(req["ids"], req["delta"])
                            resp = {"ok": True}
                        elif op == "save":
                            resp = {"ok": True, "state": {
                                tid: (t.all_rows()
                                      if isinstance(t, SparseTable)
                                      else t.value)
                                for tid, t in self.tables.items()}}
                        else:
                            resp = {"ok": False,
                                    "error": f"unknown op {op!r}"}
                except Exception as e:   # table-op failure → error resp
                    resp = {"ok": False,
                            "error": f"{type(e).__name__}: {e}"}
                conn.send_bytes(_ST_OK + pickle.dumps(resp))
        except (OSError, EOFError):
            return

    def _serve_binary(self, conn, buf):
        """One zero-pickle table op: parse the frame, run the vectorized
        table method under the lock, reply with raw row bytes."""
        try:
            op, table_id, n = _FRAME.unpack_from(buf)
            view = memoryview(buf)[_FRAME.size:]
            ids = np.frombuffer(view[:n * 8], np.int64)
            payload = view[n * 8:]
            table = self.tables.get(table_id)
            if table is None:
                raise KeyError(f"no table {table_id!r}")
            resp = None
            # serialize under the lock, but SEND outside it: a pull
            # response is megabyte-scale, and a stalled client socket
            # must not head-of-line-block every other connection
            with self._lock:
                if op == _OP_PULL_SPARSE:
                    rows = table.pull(ids)
                    resp = (_ST_OK
                            + _PULL_DIM.pack(int(table.dim))
                            + np.ascontiguousarray(
                                rows, np.float32).tobytes())
                else:
                    grad = np.frombuffer(payload, np.float32).reshape(
                        n, table.dim)
                    if op == _OP_PUSH_SPARSE:
                        table.push(ids, grad)
                    elif op == _OP_PUSH_DELTA:
                        table.apply_delta(ids, grad)
                    else:
                        raise ValueError(f"unknown binary op {op}")
                    resp = _ST_OK
            conn.send_bytes(resp)
        except Exception as e:
            conn.send_bytes(_ST_ERR
                            + f"{type(e).__name__}: {e}".encode())

    def run(self):
        """Block until a client sends stop (reference: run_server)."""
        if self._accept_thread is None:
            self.start()
        while not self._stop.is_set():
            self._stop.wait(0.2)

    def stop(self):
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass


class PSClient:
    def __init__(self, address):
        self._conn = Client(tuple(address), authkey=_AUTHKEY)
        _set_nodelay(self._conn)
        self._lock = threading.Lock()

    def _call(self, **req):
        import pickle
        with self._lock:
            self._conn.send_bytes(bytes([_OP_PICKLED])
                                  + pickle.dumps(req))
            resp = pickle.loads(memoryview(self._conn.recv_bytes())[1:])
        if not resp.get("ok"):
            raise RuntimeError(resp.get("error", "ps request failed"))
        return resp

    def _call_binary(self, op, table_id, ids, payload=b""):
        ids = np.ascontiguousarray(ids, np.int64)
        frame = _FRAME.pack(op, int(table_id), len(ids)) \
            + ids.tobytes() + payload
        with self._lock:
            self._conn.send_bytes(frame)
            resp = self._conn.recv_bytes()
        if resp[:1] != _ST_OK:
            raise RuntimeError(resp[1:].decode(errors="replace")
                               or "ps request failed")
        return memoryview(resp)[1:]

    def pull_dense(self, table_id):
        return self._call(op="pull_dense", table_id=table_id)["value"]

    def push_dense(self, table_id, grad):
        self._call(op="push_dense", table_id=table_id,
                   grad=np.asarray(grad, np.float32))

    def pull_sparse(self, table_id, ids):
        raw = self._call_binary(_OP_PULL_SPARSE, table_id, ids)
        dim = _PULL_DIM.unpack_from(raw)[0]
        # .copy(): frombuffer over the response frame is a read-only view
        # (callers mutating pulled rows in place would raise), and the
        # copy releases the full response buffer immediately
        return np.frombuffer(raw[_PULL_DIM.size:],
                             np.float32).reshape(len(ids), dim).copy()

    def push_sparse(self, table_id, ids, grad):
        self._call_binary(
            _OP_PUSH_SPARSE, table_id, ids,
            np.ascontiguousarray(grad, np.float32).tobytes())

    def push_sparse_delta(self, table_id, ids, delta):
        self._call_binary(
            _OP_PUSH_DELTA, table_id, ids,
            np.ascontiguousarray(delta, np.float32).tobytes())

    def save(self):
        return self._call(op="save")["state"]

    def stop_server(self):
        try:
            self._call(op="stop")
        except (OSError, EOFError):
            pass

    def close(self):
        try:
            self._conn.close()
        except OSError:
            pass


# ------------------------------------------------------------------
# runtime facade (reference: the_one_ps.py:1027 TheOnePSRuntime)
# ------------------------------------------------------------------

class TheOnePSRuntime:
    """Build tables from a config dict; drive server/worker lifecycle.

    config = {"tables": {0: {"type": "sparse", "dim": 8, "lr": 0.1},
                         1: {"type": "dense", "shape": [4], "lr": 0.1}}}
    """

    def __init__(self, role, config, server_address=None):
        if role not in ("server", "worker"):
            raise ValueError("role must be 'server' or 'worker'")
        self.role = role
        self.config = config
        self.server_address = server_address
        self._server = None
        self._client = None

    def init_server(self, address=("127.0.0.1", 0)):
        self._server = PSServer(address)
        for tid, spec in self.config.get("tables", {}).items():
            spec = dict(spec)
            kind = spec.pop("type")
            if kind == "sparse":
                self._server.add_sparse_table(int(tid), **spec)
            elif kind == "ssd_sparse":
                self._server.add_ssd_sparse_table(int(tid), **spec)
            else:
                self._server.add_dense_table(int(tid),
                                             tuple(spec.pop("shape")),
                                             **spec)
        self._server.start()
        self.server_address = self._server.address
        return self._server

    def run_server(self):
        self._server.run()

    def init_worker(self):
        self._client = PSClient(self.server_address)
        return self._client

    def stop_worker(self):
        if self._client is not None:
            self._client.close()
            self._client = None

    def stop(self):
        if self._client is not None:
            self._client.stop_server()
            self._client.close()
        if self._server is not None:
            self._server.stop()


# ------------------------------------------------------------------
# PSEmbedding: DistributedLookupTable analog for the eager layer API
# ------------------------------------------------------------------

class PSEmbedding:
    """Embedding whose rows live on the PS: pull on forward, push grads
    via a backward hook (reference: distributed lookup_table +
    fleet.utils ps embedding passes)."""

    def __init__(self, client, table_id, dim):
        self.client = client
        self.table_id = table_id
        self.dim = dim

    def __call__(self, ids):
        from ...core.tensor import Tensor
        ids_np = np.asarray(
            ids._data_ if isinstance(ids, Tensor) else ids).reshape(-1)
        rows = self.client.pull_sparse(self.table_id, ids_np.tolist())
        emb = Tensor(jnp.asarray(rows), stop_gradient=False)

        client, table_id = self.client, self.table_id
        id_list = ids_np.tolist()

        def push_hook(grad):
            client.push_sparse(table_id, id_list, np.asarray(grad._data_))
            return grad

        emb.register_hook(push_hook)
        shape = tuple(np.shape(
            ids._data_ if isinstance(ids, Tensor) else ids)) + (self.dim,)
        from ...tensor_ops import manipulation
        return manipulation.reshape(emb, shape), emb


# ------------------------------------------------------------------
# multi-server sharding + async communicator (reference:
# distributed/ps/service/communicator/ async communicator + sharded
# brpc tables; this is the capability — id-hash sharding across servers,
# pulls fanned out in parallel, pushes drained by a background thread
# that overlaps device compute)
# ------------------------------------------------------------------

class ShardedPSClient:
    """Client over N servers: sparse rows shard by id % N (reference:
    sparse tables sharded by feasign across PServer instances), dense
    tables route by table_id % N.  Per-shard requests run in parallel
    threads — pull latency is max-of-shards, not sum."""

    def __init__(self, addresses):
        self._clients = [PSClient(a) for a in addresses]
        self._n = len(self._clients)
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(max_workers=max(2, self._n))

    @property
    def num_shards(self):
        return self._n

    def shard_of(self, id_):
        return int(id_) % self._n

    def pull_dense(self, table_id):
        return self._clients[table_id % self._n].pull_dense(table_id)

    def push_dense(self, table_id, grad):
        self._clients[table_id % self._n].push_dense(table_id, grad)

    def _partition(self, ids):
        buckets = [[] for _ in range(self._n)]
        pos = [[] for _ in range(self._n)]
        for i, id_ in enumerate(ids):
            s = int(id_) % self._n
            buckets[s].append(int(id_))
            pos[s].append(i)
        return buckets, pos

    def pull_sparse(self, table_id, ids):
        buckets, pos = self._partition(ids)
        futs = [self._pool.submit(self._clients[s].pull_sparse, table_id,
                                  buckets[s])
                for s in range(self._n) if buckets[s]]
        shards = [s for s in range(self._n) if buckets[s]]
        out = [None] * len(ids)
        for s, f in zip(shards, futs):
            rows = f.result()
            for p, row in zip(pos[s], rows):
                out[p] = row
        return np.asarray(out, np.float32)

    def _push_fanout(self, method, table_id, ids, rows):
        """Shard-parallel row push: bucket by id, one future per shard,
        join — shared by the gradient and geo-delta paths."""
        rows = np.asarray(rows, np.float32)
        buckets, pos = self._partition(ids)
        futs = []
        for s in range(self._n):
            if buckets[s]:
                futs.append(self._pool.submit(
                    getattr(self._clients[s], method), table_id,
                    buckets[s], rows[pos[s]]))
        for f in futs:
            f.result()

    def push_sparse(self, table_id, ids, grad):
        self._push_fanout("push_sparse", table_id, ids, grad)

    def push_sparse_delta(self, table_id, ids, delta):
        self._push_fanout("push_sparse_delta", table_id, ids, delta)

    def save(self):
        return [c.save() for c in self._clients]

    def stop_server(self):
        for c in self._clients:
            c.stop_server()

    def close(self):
        for c in self._clients:
            c.close()
        self._pool.shutdown(wait=False)


class PSFlushTimeoutError(RuntimeError):
    """The push-drain barrier did not complete: the background thread is
    wedged (or dead) with updates still queued.  Raised instead of
    silently pretending the barrier completed — a trainer that proceeds
    past a fake barrier reads stale rows and diverges quietly."""


class Communicator:
    """Async push channel (reference: ps/service/communicator/
    communicator.h AsyncCommunicator): gradient pushes enqueue and a
    background thread drains them, overlapping the device's next
    forward/backward; flush() (reference barrier/pull_dense sync point)
    blocks until the queue is empty so the next pull sees every update."""

    def __init__(self, client, send_queue_size=128):
        import queue
        self._client = client
        self._q = queue.Queue(maxsize=send_queue_size)
        self._exc = None
        self._running = True
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _pending(self):
        with self._q.all_tasks_done:
            return self._q.unfinished_tasks

    def _drain(self):
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            kind, args = item
            try:
                if kind == "sparse":
                    self._client.push_sparse(*args)
                else:
                    self._client.push_dense(*args)
            except Exception as e:  # surfaced at the next flush()
                self._exc = e
            finally:
                self._q.task_done()

    def push_sparse_async(self, table_id, ids, grad):
        self._q.put(("sparse", (table_id, list(ids),
                                np.asarray(grad, np.float32))))

    def push_dense_async(self, table_id, grad):
        self._q.put(("dense", (table_id, np.asarray(grad, np.float32))))

    def flush(self, timeout=None):
        """Barrier: wait until every enqueued push is applied.  With a
        ``timeout`` (seconds) the wait is bounded — a wedged or dead
        drain thread raises :class:`PSFlushTimeoutError` (and bumps the
        ``ps.flush_timeouts`` counter) instead of blocking forever or,
        worse, returning as if the barrier completed."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._q.all_tasks_done:
            while self._q.unfinished_tasks:
                if not self._thread.is_alive():
                    monitor.incr("ps.flush_timeouts")
                    raise PSFlushTimeoutError(
                        f"ps push thread died with "
                        f"{self._q.unfinished_tasks} update(s) still "
                        "queued; the barrier can never complete")
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    monitor.incr("ps.flush_timeouts")
                    raise PSFlushTimeoutError(
                        f"ps flush barrier timed out after {timeout}s "
                        f"with {self._q.unfinished_tasks} update(s) "
                        "still queued (push thread wedged?)")
                self._q.all_tasks_done.wait(
                    0.5 if remaining is None else min(remaining, 0.5))
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise exc

    def stop(self, timeout=5.0):
        """Stop the drain thread.  A thread that ignores the stop token
        for ``timeout`` seconds is wedged mid-push: raise loudly (with
        the ``ps.flush_timeouts`` counter bumped) — returning silently
        here used to let callers believe every queued update landed."""
        if self._running:
            self._running = False
            self._q.put(None)
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                monitor.incr("ps.flush_timeouts")
                pending = self._pending()
                log.error(
                    "ps Communicator.stop: push thread still alive "
                    "after %.1fs with %d update(s) queued — updates "
                    "may be lost", timeout, pending)
                raise PSFlushTimeoutError(
                    f"ps push thread failed to stop within {timeout}s "
                    f"({pending} update(s) still queued)")


class AsyncPSEmbedding(PSEmbedding):
    """PSEmbedding whose gradient pushes ride the Communicator (async)
    and whose next batch's rows can be prefetched while the device works
    on the current one (reference: communicator geo/async modes +
    prefetch in distributed lookup tables)."""

    def __init__(self, client, table_id, dim, communicator=None):
        super().__init__(client, table_id, dim)
        self.comm = communicator or Communicator(client)
        from concurrent.futures import ThreadPoolExecutor
        self._prefetch_pool = ThreadPoolExecutor(max_workers=1)
        self._prefetched = {}

    def prefetch(self, ids):
        """Start pulling `ids` on a background thread; the matching
        __call__ consumes the future instead of a blocking pull."""
        from ...core.tensor import Tensor
        ids_np = np.asarray(
            ids._data_ if isinstance(ids, Tensor) else ids).reshape(-1)
        key = ids_np.tobytes()
        self._prefetched[key] = self._prefetch_pool.submit(
            self.client.pull_sparse, self.table_id, ids_np.tolist())

    def __call__(self, ids):
        from ...core.tensor import Tensor
        ids_np = np.asarray(
            ids._data_ if isinstance(ids, Tensor) else ids).reshape(-1)
        key = ids_np.tobytes()
        fut = self._prefetched.pop(key, None)
        if fut is not None:
            rows = fut.result()
        else:
            rows = self.client.pull_sparse(self.table_id, ids_np.tolist())
        emb = Tensor(jnp.asarray(rows), stop_gradient=False)
        comm, table_id = self.comm, self.table_id
        id_list = ids_np.tolist()

        def push_hook(grad):
            comm.push_sparse_async(table_id, id_list,
                                   np.asarray(grad._data_))
            return grad

        emb.register_hook(push_hook)
        shape = tuple(np.shape(
            ids._data_ if isinstance(ids, Tensor) else ids)) + (self.dim,)
        from ...tensor_ops import manipulation
        return manipulation.reshape(emb, list(shape))


# ------------------------------------------------------------------
# geo-SGD (reference: the_one_ps.py geo strategy + communicator.h
# GeoCommunicator — workers train a LOCAL parameter copy and exchange
# parameter DIFFS with the server every geo_step steps, not per-step
# gradients; stale-tolerant async mode for sparse recommender training)
# ------------------------------------------------------------------

class GeoSGDCommunicator:
    """Worker-side geo-SGD driver for one sparse table.

    Training applies SGD to a local row copy; `base` remembers the row
    value at the last server sync.  Every `geo_step` pushes, the
    accumulated local movement (local − base) for every touched id is
    sent as a delta (server: row += delta) and the local copy refreshes
    from the server, folding in the other trainers' deltas.  Matching
    the reference semantics, updates between syncs cost zero RPCs.
    """

    def __init__(self, client, table_id, dim, lr=0.1, geo_step=10,
                 initializer=None, seed=0):
        self.client = client
        self.table_id = table_id
        self.dim = dim
        self.geo_step = int(geo_step)
        self.local = SparseTable(dim, lr=lr, optimizer="sgd",
                                 initializer=initializer, seed=seed)
        self._base: dict[int, np.ndarray] = {}
        self._dirty: set[int] = set()
        self._pushes = 0

    def _ensure_local(self, ids):
        missing = [int(i) for i in ids if int(i) not in self._base]
        if missing:
            rows = np.asarray(
                self.client.pull_sparse(self.table_id, missing),
                np.float32)
            for key, row in zip(missing, rows):
                self._base[key] = row.copy()
                self.local.rows[key] = row.copy()

    def pull(self, ids):
        """Rows come from the LOCAL copy — no RPC unless unseen."""
        self._ensure_local(ids)
        return self.local.pull([int(i) for i in ids])

    def push(self, ids, grads):
        """Apply the gradient locally; sync with the server only every
        geo_step-th push."""
        ids = [int(i) for i in ids]
        self._ensure_local(ids)
        self.local.push(ids, grads)
        self._dirty.update(ids)
        self._pushes += 1
        if self._pushes % self.geo_step == 0:
            self.sync()

    def sync(self):
        """Push accumulated deltas; refresh local/base from the server."""
        if not self._dirty:
            return
        ids = sorted(self._dirty)
        delta = np.stack([self.local.rows[k] - self._base[k]
                          for k in ids])
        self.client.push_sparse_delta(self.table_id, ids, delta)
        fresh = np.asarray(self.client.pull_sparse(self.table_id, ids),
                           np.float32)
        for key, row in zip(ids, fresh):
            self._base[key] = row.copy()
            self.local.rows[key] = row.copy()
        self._dirty.clear()
