#!/usr/bin/env python
"""Seeded chaos campaign against a live thread-mode serving fleet
(ISSUE 17).

Reference capability: the reference proves its fleet layer with
scripted failover drills; this runner generalizes them into a seeded
CAMPAIGN: a reproducible sequence of fault episodes — gray failures
(`rpc_slow`, `engine_slow`: slow-but-alive, heartbeats healthy),
connect-time drops (`rpc_drop`), and abrupt replica loss (`kill`, the
thread-mode SIGKILL analog: heartbeat stops and the rpc endpoint snaps
mid-call with NO deregistration, so the router must detect it) — driven
against a live 3-replica fleet with the gray-failure guardian armed
(health ejection + hedged dispatch + breakers + retry budget).

After EVERY episode the invariant auditors run:

  * zero lost requests — every submitted future resolves;
  * zero duplicates — outputs bit-equal to the clean greedy reference
    (a double-decoded or torn stream cannot be bit-equal);
  * pool-drain audit identical to an idle engine — every replica ends
    the episode with no pending/queued/active requests and ZERO KV
    pages in use (a hedge loser whose cancel leaked pages fails here);
  * the fleet converges back to full membership (killed replicas
    respawn under a bumped join generation).

The whole campaign derives from ``--seed``: same seed, same episode
sequence, same fault parameters, same prompts.  The summary JSON is
schema-gated by ``tools/check_telemetry.py --campaign-summary`` and the
guardian counters it leaves in the metrics registry by
``--gray-failure`` (tools/run_ci.sh chaos lane).

Usage:
    python tools/chaos_campaign.py --seed 0 --episodes 20 \
        --out /tmp/chaos_summary.json --episode-log /tmp/chaos_log.jsonl \
        --prom-out /tmp/chaos.prom --ejection-drill
"""
from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

VOCAB = 256
FAULT_KINDS = ("rpc_slow", "rpc_drop", "engine_slow", "kill")


def make_model():
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, gpt_config
    paddle.seed(0)
    m = GPTForCausalLM(gpt_config(
        "gpt2-124m", num_layers=2, hidden_size=64, num_heads=4,
        vocab_size=VOCAB, max_seq_len=64))
    m.eval()
    return m


class _RefCache:
    """Clean-run greedy references, computed once per (prompt, len)."""

    def __init__(self, model):
        self.model = model
        self._memo = {}

    def get(self, prompt, max_new):
        import paddle_tpu as paddle
        key = (prompt.tobytes(), int(max_new))
        if key not in self._memo:
            ids = self.model.generate(
                paddle.to_tensor(prompt[None, :]),
                max_new_tokens=int(max_new), temperature=0.0)
            self._memo[key] = np.asarray(
                ids._data_)[0, prompt.size:]
        return self._memo[key]


class ChaosFleet:
    """Thread-mode fleet under test: one TCPStore master, N mixed
    replicas, and a guardian-armed router.  `kill()` emulates SIGKILL
    (no drain, no deregister — the lease must expire and the socket
    must snap); `respawn()` brings the victim back under a bumped join
    generation, exactly like a relaunched process."""

    def __init__(self, model, num_replicas=3):
        from paddle_tpu.distributed.store import TCPStore
        from paddle_tpu.serving import (ReplicaConfig, RouterConfig,
                                        ReplicaServer, ServingConfig,
                                        ServingRouter)
        self.model = model
        self.master = TCPStore(is_master=True)
        self._scfg = ServingConfig(num_slots=2, max_queue=64)
        # a generous lease: thread-mode replicas share one CPU with the
        # router, the canaries, and XLA compiles — a 1.2s TTL turns a
        # compile stall into a spurious (and sticky, by the anti-flap
        # rejoin protocol) death of the whole fleet
        self._rcfg = ReplicaConfig(heartbeat_interval_s=0.25,
                                   heartbeat_ttl_s=3.0).validate()
        self.reps = {}
        for i in range(num_replicas):
            self._spawn(f"rep-{i}")
        self.router = ServingRouter(
            TCPStore("127.0.0.1", self.master.port),
            RouterConfig(
                heartbeat_ttl_s=3.0, poll_interval_s=0.1,
                rpc_timeout_s=60.0, retry_after_s=0.2,
                health_ejection=True, health_alpha=0.3,
                eject_zscore=3.0, eject_min_samples=4,
                canary_interval_s=0.3, canary_timeout_s=10.0,
                readmit_canaries=2,
                hedge_percentile=95.0, hedge_min_samples=8,
                breaker_failures=4, breaker_window_s=5.0,
                breaker_cooldown_s=0.8,
                retry_budget_per_s=20.0,
                retry_budget_burst=40)).start()
        self.wait_members(num_replicas)

    def _spawn(self, name):
        from paddle_tpu.distributed.store import TCPStore
        from paddle_tpu.serving import ReplicaServer
        self.reps[name] = ReplicaServer(
            name, self.model, TCPStore("127.0.0.1", self.master.port),
            self._scfg, self._rcfg)

    def wait_members(self, n, timeout_s=30.0):
        deadline = time.monotonic() + timeout_s
        while len(self.router.ring.members) < n:
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"fleet never reached {n} members: "
                    f"{self.router.replicas()}")
            time.sleep(0.05)

    def kill(self, name):
        rep = self.reps[name]
        rep._closed = True                  # make close() a no-op later
        rep._stop.set()                     # heartbeat stops beating
        rep._beat.join(5.0)
        rep.rpc_server.close()              # in-flight calls snap
        rep.engine.shutdown()               # free threads; NO drain,
        #                                     NO deregister, lease left
        #                                     to expire (SIGKILL analog)
        from paddle_tpu.serving import fleet as fleet_mod
        if fleet_mod._REPLICAS.get(name) is rep:
            del fleet_mod._REPLICAS[name]

    def respawn(self, name, timeout_s=30.0):
        self._spawn(name)
        deadline = time.monotonic() + timeout_s
        while name not in self.router.ring.members:
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"{name} never rejoined: {self.router.replicas()}")
            time.sleep(0.05)

    def heal(self):
        """Supervisor analog: bounce any replica the router has marked
        dead while its server object is actually alive (a heartbeat
        lease blip under CPU contention — sticky by the anti-flap
        rejoin protocol, so without an external restart the fleet
        shrinks permanently).  A bounce re-registers under a bumped
        join generation, exactly the rejoin path a real supervisor
        restart takes."""
        healed = []
        for name, state in sorted(self.router.replicas().items()):
            rep = self.reps.get(name)
            if state != "dead" or rep is None or \
                    getattr(rep, "_closed", False):
                continue
            self.kill(name)
            self.respawn(name)
            healed.append(name)
        return healed

    def close(self):
        self.router.close()
        for rep in self.reps.values():
            rep.close()
        self.master.close()


def _collect(fleet, futs, jobs, timeout_s, episode):
    """Resolve every future, honoring shed backpressure
    (``QueueFullError.retry_after_s`` -> sleep and resubmit).  Returns
    (outputs, errors, lost) with one entry per submitted request."""
    from paddle_tpu.serving import QueueFullError
    outs, errors, lost = [], [], 0
    for j, fut in enumerate(futs):
        prompt, max_new = jobs[j]
        deadline = time.monotonic() + timeout_s
        for _ in range(16):
            try:
                outs.append(fut.result(
                    timeout=max(0.1, deadline - time.monotonic())))
                break
            except QueueFullError as e:
                hint = getattr(e, "retry_after_s", None) or 0.2
                if time.monotonic() + hint >= deadline:
                    outs.append(None)
                    errors.append(f"req {j}: shed past deadline: {e!r}")
                    lost += 1
                    break
                time.sleep(hint)
                fut = fleet.router.submit(
                    prompt, max_new_tokens=max_new,
                    session_id=f"ep{episode}-{j}")
            except Exception as e:          # noqa: BLE001
                outs.append(None)
                errors.append(f"req {j}: {e!r}")
                lost += 1
                break
        else:
            outs.append(None)
            errors.append(f"req {j}: shed retries exhausted")
            lost += 1
    return outs, errors, lost


def _audit_idle(fleet, skip=(), timeout_s=20.0):
    """The pool-drain auditor: every live replica must end the episode
    indistinguishable from an idle engine — nothing pending, nothing
    queued, nothing in a slot, zero KV pages in use."""
    leaks = []
    deadline = time.monotonic() + timeout_s
    for name, rep in sorted(fleet.reps.items()):
        if name in skip:
            continue
        eng = rep.engine
        while time.monotonic() < deadline:
            busy = (len(getattr(eng, "_pending", ())) or
                    len(getattr(eng, "_queue", ())) or
                    len(getattr(eng, "_active", ())) or
                    getattr(eng.cache, "pages_in_use", 0))
            if not busy:
                break
            time.sleep(0.05)
        pend = len(getattr(eng, "_pending", ()))
        queue = len(getattr(eng, "_queue", ()))
        active = len(getattr(eng, "_active", ()))
        pages = getattr(eng.cache, "pages_in_use", 0)
        if pend or queue or active or pages:
            leaks.append(f"{name}: pending={pend} queue={queue} "
                         f"active={active} pages_in_use={pages}")
    return leaks


def _fault_spec(kind, victim, rng):
    if kind == "rpc_slow":
        return (f"rpc_slow:to={victim},"
                f"delay_s={float(rng.uniform(0.2, 0.4)):.3f},"
                f"count={int(rng.integers(2, 5))}")
    if kind == "engine_slow":
        return (f"engine_slow:to={victim},"
                f"delay_s={float(rng.uniform(0.15, 0.3)):.3f},"
                f"count={int(rng.integers(4, 10))}")
    if kind == "rpc_drop":
        return f"rpc_drop:to={victim},count={int(rng.integers(1, 3))}"
    return ""                               # kill needs no flag


def run_episode(i, kind, fleet, refs, rng, args):
    from paddle_tpu.utils.flags import set_flags
    victim = str(rng.choice(sorted(fleet.reps)))
    spec = _fault_spec(kind, victim, rng)
    jobs = []
    for _ in range(args.requests):
        n = int(rng.integers(3, 10))
        prompt = rng.integers(0, VOCAB, (n,)).astype("int32")
        jobs.append((prompt, int(rng.integers(3, 7))))
    t0 = time.monotonic()
    killed = False
    fleet.heal()                            # enter with a full fleet
    set_flags({"FLAGS_fault_inject": spec})
    try:
        futs = [fleet.router.submit(p, max_new_tokens=m,
                                    session_id=f"ep{i}-{j}")
                for j, (p, m) in enumerate(jobs)]
        if kind == "kill":
            time.sleep(0.15)                # let load land first
            fleet.kill(victim)
            killed = True
        outs, errors, lost = _collect(fleet, futs, jobs,
                                      args.timeout_s, i)
    finally:
        set_flags({"FLAGS_fault_inject": ""})
    if killed:
        fleet.respawn(victim)
    healed = fleet.heal()
    mismatches = 0
    for (prompt, max_new), out in zip(jobs, outs):
        if out is None:
            continue
        if not np.array_equal(out.output_ids,
                              refs.get(prompt, max_new)):
            mismatches += 1
    leaks = _audit_idle(fleet, skip=())
    rec = {
        "episode": i, "fault": kind, "victim": victim, "spec": spec,
        "requests": len(jobs), "lost": lost, "mismatches": mismatches,
        "leaks": leaks, "errors": errors, "healed": healed,
        "wall_s": round(time.monotonic() - t0, 3),
    }
    rec["ok"] = not (lost or mismatches or leaks)
    return rec


def run_ejection_drill(fleet, refs, rng, args):
    """The headline gray-failure scenario: `engine_slow` on 1-of-3
    replicas (10x+ per-iteration stall, heartbeats perfectly healthy)
    must trigger health-scored ejection; clearing the fault must bring
    the replica back through canary readmission.  Latency p99 is
    measured clean / ejected and must recover to <=1.5x the healthy
    baseline once the victim is out of the candidate order."""
    from paddle_tpu.serving import serving_stats
    from paddle_tpu.utils.flags import set_flags

    def p99(xs):
        return float(np.percentile(np.asarray(xs), 99)) if xs else 0.0

    seq = iter(range(10**9))

    def round_trip(tag, n):
        lats = []
        for _ in range(n):
            # session ids must stay unique across calls: reusing them
            # would pin every round to the same ring subset and could
            # starve the victim of the load the detector feeds on
            j = next(seq)
            prompt = rng.integers(0, VOCAB,
                                  (int(rng.integers(3, 10)),)) \
                .astype("int32")
            t0 = time.monotonic()
            out = fleet.router.generate(prompt, max_new_tokens=4,
                                        session_id=f"{tag}-{j}",
                                        timeout=args.timeout_s)
            lats.append(time.monotonic() - t0)
            assert np.array_equal(out.output_ids, refs.get(prompt, 4)), \
                f"{tag}-{j}: output diverged from clean reference"
        return lats

    victim = sorted(fleet.reps)[0]
    rec = {"victim": victim}
    clean = round_trip("drill-clean", 24)
    rec["p99_clean_s"] = round(p99(clean), 3)
    # settle to steady state before arming the fault: first-request JIT
    # compiles are slow enough to look like gray failures themselves —
    # wait out any warmup ejection (canaries readmit it) and drop the
    # warmup-contaminated EWMAs so detection is measured from clean
    deadline = time.monotonic() + 60.0
    while fleet.router._ejected:
        if time.monotonic() >= deadline:
            raise RuntimeError("warmup ejection never readmitted: "
                               f"{dict(fleet.router._ejected)}")
        time.sleep(0.1)
    with fleet.router._lock:
        fleet.router._health.clear()
    base = serving_stats()
    set_flags({"FLAGS_fault_inject":
               f"engine_slow:to={victim},delay_s=0.5,count=10000"})
    try:
        # drive load until the guardian ejects the victim
        deadline = time.monotonic() + 60.0
        while serving_stats()["router_ejections"] == \
                base["router_ejections"]:
            if time.monotonic() >= deadline:
                raise RuntimeError("guardian never ejected the "
                                   "engine_slow victim")
            round_trip("drill-load", 6)
        rec["ejections"] = (serving_stats()["router_ejections"]
                           - base["router_ejections"])
        # with the victim out of the candidate order, p99 must recover
        post = round_trip("drill-post", 24)
        rec["p99_ejected_s"] = round(p99(post), 3)
        limit = max(1.5 * p99(clean), p99(clean) + 0.25)
        if p99(post) > limit:
            raise RuntimeError(
                f"p99 after ejection {p99(post):.3f}s did not recover "
                f"to <=1.5x healthy baseline {p99(clean):.3f}s")
    finally:
        set_flags({"FLAGS_fault_inject": ""})
    # fault cleared: canary probes must readmit the victim
    deadline = time.monotonic() + 60.0
    while serving_stats()["router_readmissions"] == \
            base["router_readmissions"]:
        if time.monotonic() >= deadline:
            raise RuntimeError("canaries never readmitted the "
                               "recovered victim")
        time.sleep(0.1)
    rec["readmissions"] = (serving_stats()["router_readmissions"]
                          - base["router_readmissions"])
    leaks = _audit_idle(fleet)
    if leaks:
        raise RuntimeError(f"ejection drill leaked: {leaks}")
    return rec


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="seeded chaos campaign with invariant auditors")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--episodes", type=int, default=20)
    ap.add_argument("--requests", type=int, default=6,
                    help="requests per episode")
    ap.add_argument("--timeout-s", type=float, default=60.0)
    ap.add_argument("--out", help="summary JSON path")
    ap.add_argument("--episode-log", help="per-episode JSONL path")
    ap.add_argument("--prom-out",
                    help="Prometheus dump path (guardian counter gate)")
    ap.add_argument("--ejection-drill", action="store_true",
                    help="run the engine_slow ejection/readmission "
                         "scenario before the episode loop")
    ap.add_argument("--trace-dir",
                    help="arm distributed tracing (ISSUE 19): spool "
                         "spans here, write <dir>/merged.json, and "
                         "audit exactly-one tail-sampling decision "
                         "per request — under chaos, a hedged/killed/"
                         "resubmitted request must still decide once")
    args = ap.parse_args(argv)

    if args.trace_dir:
        from paddle_tpu.observability import tracing
        from paddle_tpu.utils.flags import set_flags
        tracing.reset()
        # threshold 0 keeps every trace's spans: the campaign artifact
        # is also the analyzer's input, so sample nothing out
        set_flags({"FLAGS_trace_dir": args.trace_dir,
                   "FLAGS_trace_latency_threshold_ms": 0.0})

    rng = np.random.default_rng(args.seed)
    t_start = time.monotonic()
    model = make_model()
    refs = _RefCache(model)
    fleet = ChaosFleet(model)
    records = []
    drill = None
    try:
        if args.ejection_drill:
            drill = run_ejection_drill(fleet, refs, rng, args)
            print(f"ejection drill OK: victim {drill['victim']} "
                  f"ejected (p99 {drill['p99_clean_s']}s clean -> "
                  f"{drill['p99_ejected_s']}s ejected) and readmitted")
        # shuffled round-robin: every kind covered, order seeded
        kinds = []
        while len(kinds) < args.episodes:
            batch = list(FAULT_KINDS)
            rng.shuffle(batch)
            kinds.extend(batch)
        kinds = kinds[:args.episodes]
        log_f = open(args.episode_log, "w") if args.episode_log \
            else None
        try:
            for i, kind in enumerate(kinds):
                rec = run_episode(i, kind, fleet, refs, rng, args)
                records.append(rec)
                if log_f:
                    log_f.write(json.dumps(rec) + "\n")
                    log_f.flush()
                status = "ok" if rec["ok"] else "FAILED"
                print(f"episode {i:2d} [{kind:>11s} -> "
                      f"{rec['victim']}] {status}: "
                      f"{rec['requests']} reqs, lost={rec['lost']}, "
                      f"mismatches={rec['mismatches']}, "
                      f"leaks={len(rec['leaks'])}, "
                      f"{rec['wall_s']:.2f}s")
        finally:
            if log_f:
                log_f.close()
        from paddle_tpu.serving import serving_stats
        snap = serving_stats()
    finally:
        fleet.close()
    if args.prom_out:
        import paddle_tpu.observability as obs
        with open(args.prom_out, "w") as f:
            f.write(obs.render_prometheus())
    faults: dict = {}
    for rec in records:
        faults[rec["fault"]] = faults.get(rec["fault"], 0) + 1
    summary = {
        "schema_version": 1,
        "seed": args.seed,
        "episodes": len(records),
        "faults": faults,
        "requests": sum(r["requests"] for r in records),
        "lost_requests": sum(r["lost"] for r in records),
        "duplicate_requests": sum(r["mismatches"] for r in records),
        "mismatches": sum(r["mismatches"] for r in records),
        "leaks": sum(len(r["leaks"]) for r in records),
        "failed_episodes": [r["episode"] for r in records
                            if not r["ok"]],
        "wall_s": round(time.monotonic() - t_start, 3),
        "guardian": {k: snap[k] for k in (
            "router_ejections", "router_readmissions",
            "router_hedges", "router_hedge_wins",
            "router_breaker_open", "router_retry_budget_exhausted",
            "requests_cancelled")},
    }
    if drill is not None:
        summary["ejection_drill"] = drill
    if args.trace_dir:
        # trace audit: every request that resolved (none were lost if
        # we got here) must have decided its trace exactly once — a
        # hedged winner + cancelled loser, a SIGKILL resubmission, or
        # a drain bounce shows up as EXTRA spans, never extra
        # decisions, and a request that finished without deciding is
        # an untraced p99 outlier waiting to happen
        from paddle_tpu.observability import tracing
        tracing.spool_now(args.trace_dir)
        merged = tracing.merge_spools(args.trace_dir)
        import os as _os
        tracing.write_merged(
            merged, _os.path.join(args.trace_dir, "merged.json"))
        counts = [t.get("decision_count", 0)
                  for t in merged.get("traces", [])]
        summary["trace"] = {
            "requests": len(counts),
            "decided": sum(1 for c in counts if c == 1),
            "multi_decision": sum(1 for c in counts if c > 1),
            "undecided": sum(1 for c in counts if c == 0),
            "kept": sum(1 for t in merged.get("traces", [])
                        if t.get("sampled")),
        }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=2, sort_keys=True)
    print(json.dumps(summary, indent=2, sort_keys=True))
    ok = (not summary["failed_episodes"]
          and summary["lost_requests"] == 0
          and summary["mismatches"] == 0
          and summary["leaks"] == 0
          and summary.get("trace", {}).get("multi_decision", 0) == 0
          and summary.get("trace", {}).get("undecided", 0) == 0)
    print(f"chaos campaign {'OK' if ok else 'FAILED'}: "
          f"{summary['episodes']} episodes, seed {args.seed}, "
          f"{summary['wall_s']:.1f}s")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
