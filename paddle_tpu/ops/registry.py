"""Op registry: the single source of truth for op metadata.

Reference capability: the declarative YAML op definitions
(reference: paddle/phi/api/yaml/ops.yaml + generators) that drive codegen of
the C++ API, autograd nodes and SPMD rules.  TPU-native realization: a runtime
registry — the "codegen" targets collapse because JAX provides autodiff
(jax.vjp) and GSPMD provides sharding propagation; what remains useful is a
queryable table of {name → impl, differentiability, spmd rule, flops fn} used
by introspection, AMP lists, the profiler and the auto-parallel layer.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass
class OpDef:
    name: str
    fn: Callable                      # pure JAX implementation
    nondiff: bool = False             # no gradient defined
    spmd_rule: Optional[Callable] = None   # sharding propagation hint
    flops: Optional[Callable] = None       # flops estimator for profiler/MFU
    tags: tuple = field(default_factory=tuple)


OPS: dict[str, OpDef] = {}


def register_op(name, fn, nondiff=False, spmd_rule=None, flops=None, tags=()):
    OPS[name] = OpDef(name, fn, nondiff=nondiff, spmd_rule=spmd_rule,
                      flops=flops, tags=tuple(tags))
    return OPS[name]


def get_op(name) -> Optional[OpDef]:
    return OPS.get(name)


def list_ops():
    return sorted(OPS)
