"""nn functional ops (reference: python/paddle/nn/functional/).

Convs and matmuls lower to the MXU via lax.conv_general_dilated / dot_general;
norms and activations fuse into neighbours under jit.  Fused ops the reference
implements as CUDA kernels (fused rope, rms_norm, flash attention —
paddle/phi/kernels/fusion/gpu/) live in paddle_tpu.incubate.nn.functional with
Pallas implementations.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import defop, apply_op
from ..core.tensor import Tensor
from ..core import state as _state

# ---------------- activations ----------------


@defop("relu")
def relu(x, name=None):
    return jax.nn.relu(x)


@defop("relu6")
def relu6(x, name=None):
    return jax.nn.relu6(x)


@defop("gelu")
def gelu(x, approximate=False, name=None):
    return jax.nn.gelu(x, approximate=approximate)


@defop("silu")
def silu(x, name=None):
    return jax.nn.silu(x)


swish = silu


@defop("leaky_relu")
def leaky_relu(x, negative_slope=0.01, name=None):
    return jax.nn.leaky_relu(x, negative_slope)


@defop("elu")
def elu(x, alpha=1.0, name=None):
    return jax.nn.elu(x, alpha)


@defop("celu")
def celu(x, alpha=1.0, name=None):
    return jax.nn.celu(x, alpha)


@defop("selu")
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@defop("prelu")
def prelu(x, weight, data_format="NCHW", name=None):
    w = weight
    if w.ndim == 1 and w.shape[0] > 1 and x.ndim > 1:
        ch_axis = 1 if data_format == "NCHW" else x.ndim - 1
        shape = [1] * x.ndim
        shape[ch_axis] = w.shape[0]
        w = w.reshape(shape)
    return jnp.where(x > 0, x, w * x)


@defop("hardshrink")
def hardshrink(x, threshold=0.5, name=None):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@defop("softshrink")
def softshrink(x, threshold=0.5, name=None):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


@defop("tanhshrink")
def tanhshrink(x, name=None):
    return x - jnp.tanh(x)


@defop("hardsigmoid")
def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@defop("hardswish")
def hardswish(x, name=None):
    return x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


@defop("hardtanh")
def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return jnp.clip(x, min, max)


@defop("softplus")
def softplus(x, beta=1.0, threshold=20.0, name=None):
    return jnp.where(x * beta > threshold, x,
                     jnp.log1p(jnp.exp(beta * x)) / beta)


@defop("softsign")
def softsign(x, name=None):
    return x / (1.0 + jnp.abs(x))


@defop("mish")
def mish(x, name=None):
    return x * jnp.tanh(jax.nn.softplus(x))


@defop("glu")
def glu(x, axis=-1, name=None):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


@defop("maxout")
def maxout(x, groups, axis=1, name=None):
    ax = axis % x.ndim
    c = x.shape[ax]
    shape = list(x.shape)
    shape[ax:ax + 1] = [c // groups, groups]
    return jnp.max(x.reshape(shape), axis=ax + 1)


@defop("softmax")
def softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        x = x.astype(dtype)
    return jax.nn.softmax(x, axis=axis)


@defop("log_softmax")
def log_softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        x = x.astype(dtype)
    return jax.nn.log_softmax(x, axis=axis)


@defop("rrelu")
def rrelu(x, lower=0.125, upper=0.3333333, training=True, name=None):
    slope = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, slope * x)


@defop("thresholded_relu")
def thresholded_relu(x, threshold=1.0, name=None):
    return jnp.where(x > threshold, x, 0.0)


# ---------------- linear / embedding ----------------


@defop("linear")
def linear(x, weight, bias=None, name=None):
    """y = x @ W + b; W is [in, out] (reference convention)."""
    if x.dtype in (jnp.bfloat16, jnp.float16):
        y = jnp.matmul(x, weight, preferred_element_type=jnp.float32).astype(x.dtype)
    else:
        y = jnp.matmul(x, weight)
    if bias is not None:
        y = y + bias
    return y


@defop("embedding")
def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return out


@defop("one_hot")
def one_hot(x, num_classes, name=None):
    return jax.nn.one_hot(x, num_classes)


# ---------------- dropout ----------------


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    key = _state.next_rng_key()

    def fn(x_):
        shape = list(x_.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, x_ / (1.0 - p), jnp.zeros((), x_.dtype))
        return jnp.where(keep, x_, jnp.zeros((), x_.dtype))
    return apply_op("dropout", fn, (x,))


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = _state.next_rng_key()
    alpha = -1.7580993408473766

    def fn(x_):
        keep = jax.random.bernoulli(key, 1.0 - p, x_.shape)
        a = ((1.0 - p) * (1.0 + p * alpha ** 2)) ** -0.5
        b = -a * alpha * p
        return a * jnp.where(keep, x_, alpha) + b
    return apply_op("alpha_dropout", fn, (x,))


# ---------------- normalization ----------------


@defop("layer_norm")
def layer_norm(x, normalized_shape=None, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(normalized_shape) if normalized_shape else 1
    axes = tuple(range(x.ndim - n_axes, x.ndim))
    # compute statistics in f32 for bf16 inputs (numerics parity with the
    # reference's fused_layernorm which accumulates in float)
    xf = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


@defop("rms_norm")
def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    from ..pallas import fused as _pf
    if weight is not None and _pf.rms_norm_supported(x, weight):
        return _pf.rms_norm_pallas(x, weight, epsilon)
    xf = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = (xf * jax.lax.rsqrt(ms + epsilon)).astype(x.dtype)
    if weight is not None:
        out = out * weight
    return out


@defop("grid_sample")
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x at normalized grid locations (reference:
    nn/functional/vision.py grid_sample over grid_sample_kernel.cu).

    x: [N, C, H, W]; grid: [N, Hg, Wg, 2] with (x, y) in [-1, 1].
    Pure gather + lerp: traces into the surrounding program."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"unknown mode {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"unknown padding_mode {padding_mode!r}")
    n, c, h, w = x.shape
    gx, gy = grid[..., 0], grid[..., 1]
    if align_corners:
        fx = (gx + 1.0) * 0.5 * (w - 1)
        fy = (gy + 1.0) * 0.5 * (h - 1)
    else:
        fx = ((gx + 1.0) * w - 1.0) * 0.5
        fy = ((gy + 1.0) * h - 1.0) * 0.5

    def _reflect(v, size):
        # reflect about -0.5 / size-0.5 (align_corners=False convention)
        # or 0 / size-1 (align_corners=True)
        if align_corners:
            span = max(size - 1, 1)
            v = jnp.abs(v) % (2 * span)
            return jnp.where(v > span, 2 * span - v, v)
        span = size
        v = (v + 0.5) % (2 * span)
        v = jnp.where(v < 0, v + 2 * span, v)
        return jnp.where(v > span, 2 * span - v, v) - 0.5

    if padding_mode == "reflection":
        fx = _reflect(fx, w)
        fy = _reflect(fy, h)

    def _gather(iy, ix):
        """Clamped gather with a zeros mask when padding_mode='zeros'."""
        inside = (iy >= 0) & (iy < h) & (ix >= 0) & (ix < w)
        iyc = jnp.clip(iy, 0, h - 1)
        ixc = jnp.clip(ix, 0, w - 1)
        vals = jax.vmap(lambda img, yy, xx: img[:, yy, xx])(x, iyc, ixc)
        if padding_mode == "zeros":
            vals = jnp.where(inside[:, None], vals, 0.0)
        return vals

    def sample_nearest(fy_, fx_):
        return _gather(jnp.round(fy_).astype(jnp.int32),
                       jnp.round(fx_).astype(jnp.int32))

    def sample_bilinear(fy_, fx_):
        y0 = jnp.floor(fy_)
        x0 = jnp.floor(fx_)
        wy = fy_ - y0
        wx = fx_ - x0
        out = 0.0
        for dy, sy in ((0, 1.0), (1, 0.0)):
            for dx, sx in ((0, 1.0), (1, 0.0)):
                wgt = (jnp.abs(sy - wy)) * (jnp.abs(sx - wx))
                vals = _gather((y0 + dy).astype(jnp.int32),
                               (x0 + dx).astype(jnp.int32))
                out = out + vals * wgt[:, None]
        return out

    # flatten grid, sample, restore [N, C, Hg, Wg]
    hg, wg = grid.shape[1], grid.shape[2]
    fyf = fy.reshape(n, -1)
    fxf = fx.reshape(n, -1)
    vals = (sample_nearest(fyf, fxf) if mode == "nearest"
            else sample_bilinear(fyf, fxf))       # [N, C, Hg*Wg]
    return vals.reshape(n, c, hg, wg)


@defop("batch_norm_infer")
def _batch_norm_infer(x, running_mean, running_var, weight, bias, epsilon,
                      data_format):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    mean = running_mean.reshape(shape)
    var = running_var.reshape(shape)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    if use_global_stats is None:
        use_global_stats = not training
    if use_global_stats:
        return apply_op("batch_norm_infer", _batch_norm_infer.__wrapped__,
                        (x, running_mean, running_var, weight, bias),
                        static={"epsilon": epsilon, "data_format": data_format})
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)

    def fn(x_, w, b):
        mean = jnp.mean(x_, axis=axes)
        var = jnp.var(x_, axis=axes)
        shape = [1] * x_.ndim
        shape[ch_axis] = x_.shape[ch_axis]
        out = (x_ - mean.reshape(shape)) * jax.lax.rsqrt(
            var.reshape(shape) + epsilon)
        if w is not None:
            out = out * w.reshape(shape)
        if b is not None:
            out = out + b.reshape(shape)
        return out, mean, var

    out, mean, var = apply_op("batch_norm", fn, (x, weight, bias))
    # update running stats in-place (host-side state, like the reference)
    if running_mean is not None:
        m = momentum
        running_mean.set_value(m * running_mean._data + (1 - m) * mean._data)
        running_var.set_value(m * running_var._data + (1 - m) * var._data)
    return out


@defop("instance_norm")
def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    spatial = tuple(i for i in range(2, x.ndim)) if ch_axis == 1 else \
        tuple(i for i in range(1, x.ndim - 1))
    mean = jnp.mean(x, axis=spatial, keepdims=True)
    var = jnp.var(x, axis=spatial, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        shape = [1] * x.ndim
        shape[ch_axis] = x.shape[ch_axis]
        out = out * weight.reshape(shape) + (bias.reshape(shape)
                                             if bias is not None else 0.0)
    return out


@defop("group_norm")
def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    if data_format == "NHWC":
        x_t = jnp.moveaxis(x, -1, 1)
    else:
        x_t = x
    n, c = x_t.shape[0], x_t.shape[1]
    g = num_groups
    grouped = x_t.reshape((n, g, c // g) + x_t.shape[2:])
    axes = tuple(range(2, grouped.ndim))
    mean = jnp.mean(grouped, axis=axes, keepdims=True)
    var = jnp.var(grouped, axis=axes, keepdims=True)
    out = ((grouped - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x_t.shape)
    if weight is not None:
        shape = [1, c] + [1] * (x_t.ndim - 2)
        out = out * weight.reshape(shape)
    if bias is not None:
        shape = [1, c] + [1] * (x_t.ndim - 2)
        out = out + bias.reshape(shape)
    if data_format == "NHWC":
        out = jnp.moveaxis(out, 1, -1)
    return out


@defop("normalize")
def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    nrm = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(nrm, epsilon)


@defop("local_response_norm")
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    sq = jnp.square(x)
    half = size // 2
    moved = jnp.moveaxis(sq, ch_axis, -1)
    padded = jnp.pad(moved, [(0, 0)] * (x.ndim - 1) + [(half, size - 1 - half)])
    windows = jnp.stack([padded[..., i:i + moved.shape[-1]]
                         for i in range(size)], axis=-1)
    s = jnp.sum(windows, axis=-1)
    s = jnp.moveaxis(s, -1, ch_axis)
    return x / jnp.power(k + alpha * s, beta)


# ---------------- conv / pool ----------------


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)
    return (int(v),) * n


def _conv_padding(padding, n_spatial, kernel, dilation):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n_spatial
    padding = list(padding)
    if len(padding) == n_spatial:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n_spatial:
        return [(int(padding[2 * i]), int(padding[2 * i + 1]))
                for i in range(n_spatial)]
    raise ValueError(f"bad padding {padding}")


@defop("conv2d")
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    """reference: paddle.nn.functional.conv2d over cuDNN; here
    lax.conv_general_dilated → MXU."""
    stride = _pair(stride)
    dilation = _pair(dilation)
    pad = _conv_padding(padding, 2, weight.shape[2:], dilation)
    dn = ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else \
         ("NHWC", "OIHW", "NHWC")
    # no preferred_element_type=f32 here: the TPU MXU accumulates bf16
    # in f32 regardless, the output is cast back to x.dtype anyway, and
    # a widened conv output makes the VJP transpose bind conv(bf16 x,
    # f32 cotangent) — which lax rejects (mixed-dtype conv)
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, feature_group_count=groups,
        dimension_numbers=dn)
    out = out.astype(x.dtype)
    if bias is not None:
        shape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
        out = out + bias.reshape(shape)
    return out


@defop("conv1d")
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    stride = _pair(stride, 1)
    dilation = _pair(dilation, 1)
    pad = _conv_padding(padding, 1, weight.shape[2:], dilation)
    dn = ("NCH", "OIH", "NCH") if data_format == "NCL" else ("NHC", "OIH", "NHC")
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, feature_group_count=groups,
        dimension_numbers=dn)
    if bias is not None:
        shape = [1, -1, 1] if data_format == "NCL" else [1, 1, -1]
        out = out + bias.reshape(shape)
    return out


@defop("conv3d")
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    stride = _pair(stride, 3)
    dilation = _pair(dilation, 3)
    pad = _conv_padding(padding, 3, weight.shape[2:], dilation)
    dn = ("NCDHW", "OIDHW", "NCDHW")
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, feature_group_count=groups,
        dimension_numbers=dn)
    if bias is not None:
        out = out + bias.reshape([1, -1, 1, 1, 1])
    return out


@defop("conv2d_transpose")
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCHW", output_size=None, name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, 2, "NCHW", "OIHW",
                              groups=groups, output_size=output_size)


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, n, lhs_spec, rhs_spec, groups=1,
                       output_size=None):
    """Transpose conv matching the reference/torch semantics (verified
    element-wise against torch.conv_transpose*d): paddle's [in, out, *k]
    kernel is the forward conv's [O, I, *k] under transpose_kernel=True,
    and user padding p maps to jax padding dilation·(k−1) − p with
    output_padding added on the high side.  groups are realized by
    channel-slicing (lax.conv_transpose has no feature_group_count);
    output_size resolves to the equivalent output_padding."""
    stride = _pair(stride, n)
    dilation = _pair(dilation, n)
    k = weight.shape[2:]
    if isinstance(padding, str):
        if padding.upper() not in ("SAME", "VALID"):
            raise ValueError(f"unsupported padding {padding!r}")
        padding = [0] * n if padding.upper() == "VALID" else \
            [(dilation[d] * (k[d] - 1)) // 2 for d in range(n)]
    padding = _pair(padding, n)
    out_pad = _pair(output_padding, n)
    if output_size is not None:
        sizes = list(output_size)[-n:]
        out_pad = tuple(
            int(sizes[d]) - ((x.shape[2 + d] - 1) * stride[d]
                             - 2 * padding[d] + dilation[d] * (k[d] - 1)
                             + 1)
            for d in range(n))
    pad = [(dilation[d] * (k[d] - 1) - padding[d],
            dilation[d] * (k[d] - 1) - padding[d] + out_pad[d])
           for d in range(n)]

    def one_group(xg, wg):
        return jax.lax.conv_transpose(
            xg, wg, strides=stride, padding=pad, rhs_dilation=dilation,
            dimension_numbers=(lhs_spec, rhs_spec, lhs_spec),
            transpose_kernel=True)

    if groups == 1:
        out = one_group(x, weight)
    else:
        cin = x.shape[1] // groups
        outs = [one_group(
            jax.lax.slice_in_dim(x, g * cin, (g + 1) * cin, axis=1),
            jax.lax.slice_in_dim(weight, g * cin, (g + 1) * cin, axis=0))
            for g in range(groups)]
        out = jnp.concatenate(outs, axis=1)
    if bias is not None:
        out = out + bias.reshape([1, -1] + [1] * n)
    return out


def _pool(x, op, init, kernel, stride, padding, data_format, n_spatial,
          ceil_mode=False):
    kernel = _pair(kernel, n_spatial)
    stride = _pair(stride if stride is not None else kernel, n_spatial)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        pad = _conv_padding(padding, n_spatial, kernel, (1,) * n_spatial)
        if ceil_mode:
            # extend the high-side pad so partial windows yield an output
            # (reduce_window floors otherwise); init-padding is neutral
            sp_off = 2 if data_format.startswith("NC") else 1
            pad = list(pad)
            for d in range(n_spatial):
                size = x.shape[sp_off + d] + pad[d][0] + pad[d][1]
                rem = (size - kernel[d]) % stride[d]
                if rem:
                    pad[d] = (pad[d][0], pad[d][1] + stride[d] - rem)
    if data_format.startswith("NC"):
        dims = (1, 1) + kernel
        strides = (1, 1) + stride
        if not isinstance(pad, str):
            pad = [(0, 0), (0, 0)] + pad
    else:
        dims = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        if not isinstance(pad, str):
            pad = [(0, 0)] + pad + [(0, 0)]
    return jax.lax.reduce_window(x, init, op, dims, strides, pad)


@defop("max_pool2d")
def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    if return_mask:
        from .functional_extra import _max_pool_with_index
        return _max_pool_with_index(x, kernel_size, stride, padding, 2,
                                    ceil_mode=ceil_mode,
                                    data_format=data_format)
    return _pool(x, jax.lax.max, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                 else jnp.iinfo(x.dtype).min,
                 kernel_size, stride, padding, data_format, 2, ceil_mode)


@defop("avg_pool2d")
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    summed = _pool(x, jax.lax.add, 0.0, kernel_size, stride, padding,
                   data_format, 2, ceil_mode)
    k = _pair(kernel_size, 2)
    if divisor_override:
        div = divisor_override
    elif exclusive and (padding != 0 or ceil_mode):
        ones = jnp.ones_like(x)
        div = _pool(ones, jax.lax.add, 0.0, kernel_size, stride, padding,
                    data_format, 2, ceil_mode)
        return summed / div
    else:
        div = k[0] * k[1]
    return summed / div


@defop("max_pool1d")
def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    if return_mask:
        from .functional_extra import _max_pool_with_index
        return _max_pool_with_index(x, kernel_size, stride, padding, 1,
                                    ceil_mode=ceil_mode)
    return _pool(x, jax.lax.max, -jnp.inf, kernel_size, stride, padding,
                 "NCL", 1, ceil_mode)


@defop("avg_pool1d")
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    summed = _pool(x, jax.lax.add, 0.0, kernel_size, stride, padding,
                   "NCL", 1, ceil_mode)
    k = _pair(kernel_size, 1)
    return summed / k[0]


@defop("adaptive_avg_pool2d")
def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    out = _pair(output_size, 2)
    if data_format == "NCHW":
        h, w = x.shape[2], x.shape[3]
    else:
        h, w = x.shape[1], x.shape[2]
    if h % out[0] == 0 and w % out[1] == 0:
        kh, kw = h // out[0], w // out[1]
        return avg_pool2d.__wrapped__(x, (kh, kw), (kh, kw), 0,
                                      data_format=data_format)
    # general case: mean over computed bins
    def pool_axis(arr, axis, n_out):
        size = arr.shape[axis]
        starts = (np.arange(n_out) * size) // n_out
        ends = ((np.arange(n_out) + 1) * size + n_out - 1) // n_out
        pieces = [jnp.mean(jax.lax.slice_in_dim(arr, int(s), int(e), axis=axis),
                           axis=axis, keepdims=True)
                  for s, e in zip(starts, ends)]
        return jnp.concatenate(pieces, axis=axis)
    ha = 2 if data_format == "NCHW" else 1
    x = pool_axis(x, ha, out[0])
    x = pool_axis(x, ha + 1, out[1])
    return x


@defop("adaptive_max_pool2d")
def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        from .functional_extra import _adaptive_max_with_index
        return _adaptive_max_with_index(x, output_size, 2)
    out = _pair(output_size, 2)
    h, w = x.shape[2], x.shape[3]
    kh, kw = h // out[0], w // out[1]
    return max_pool2d.__wrapped__(x, (kh, kw), (kh, kw), 0)


@defop("unfold_op")
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: paddle.nn.functional.unfold)."""
    k = _pair(kernel_sizes, 2)
    s = _pair(strides, 2)
    d = _pair(dilations, 2)
    p = _conv_padding(paddings, 2, k, d)
    n, c = x.shape[0], x.shape[1]
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=k, window_strides=s, padding=p, rhs_dilation=d,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # [N, C*kh*kw, L]
    return patches.reshape(n, c * k[0] * k[1], -1)


@defop("interpolate")
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    if data_format == "NCHW":
        spatial = x.shape[2:]
    else:
        spatial = x.shape[1:-1]
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * len(spatial)
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    size = [int(v) for v in (size if isinstance(size, (list, tuple)) else [size])]
    method = {"nearest": "nearest", "bilinear": "bilinear", "linear": "linear",
              "bicubic": "cubic", "trilinear": "trilinear", "area": "linear"}[mode]
    if data_format == "NCHW":
        out_shape = x.shape[:2] + tuple(size)
    else:
        out_shape = (x.shape[0],) + tuple(size) + (x.shape[-1],)
    return jax.image.resize(x, out_shape, method=method)


upsample = interpolate


@defop("pixel_shuffle")
def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor
    n, c, h, w = x.shape
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return x.reshape(n, c // (r * r), h * r, w * r)


# ---------------- losses ----------------


@defop("mse_loss")
def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    loss = jnp.square(input - label)
    return _reduce(loss, reduction)


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@defop("l1_loss")
def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return _reduce(jnp.abs(input - label), reduction)


@defop("smooth_l1_loss")
def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    d = jnp.abs(input - label)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce(loss, reduction)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _softmax_xent_fused(logits, label, ignore_index):
    """Fused softmax + cross-entropy (hard labels, last axis).

    Reference capability: the fused softmax_with_cross_entropy kernel
    (paddle/phi/kernels/fusion; c_softmax_with_cross_entropy).  Memory
    win that matters at LM head scale ([tokens, vocab]): the VJP saves
    only the *original-dtype* logits + the fp32 logsumexp and recomputes
    the softmax in backward, instead of jax.vjp storing the fp32
    log-softmax and its residuals (3× the logits bytes at bf16).
    """
    loss, _ = _softmax_xent_fwd_impl(logits, label, ignore_index)
    return loss


def _softmax_xent_fwd_impl(logits, label, ignore_index):
    x32 = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(x32, axis=-1, keepdims=True)
    lbl = jnp.clip(label, 0, logits.shape[-1] - 1).astype(jnp.int32)
    picked = jnp.take_along_axis(x32, lbl[..., None], axis=-1)[..., 0]
    mask = label != ignore_index
    loss = jnp.where(mask, lse[..., 0] - picked, 0.0)
    return loss, (logits, label, lse)


def _softmax_xent_vjp_fwd(logits, label, ignore_index):
    loss, res = _softmax_xent_fwd_impl(logits, label, ignore_index)
    return loss, res


def _softmax_xent_vjp_bwd(ignore_index, res, g):
    logits, label, lse = res
    mask = label != ignore_index
    gm = jnp.where(mask, g, 0.0).astype(jnp.float32)
    p = jnp.exp(logits.astype(jnp.float32) - lse)
    lbl = jnp.clip(label, 0, logits.shape[-1] - 1).astype(jnp.int32)
    # (p - onehot) * g via a broadcasted-iota compare: pure elementwise,
    # fuses into the exp — a row scatter here lowers to a serial loop on TPU
    onehot = jax.lax.broadcasted_iota(
        jnp.int32, p.shape, p.ndim - 1) == lbl[..., None]
    d = (p - onehot.astype(jnp.float32)) * gm[..., None]
    return (d.astype(logits.dtype),
            np.zeros(label.shape, dtype=jax.dtypes.float0))


_softmax_xent_fused.defvjp(_softmax_xent_vjp_fwd, _softmax_xent_vjp_bwd)


@defop("cross_entropy")
def cross_entropy(input, label, weight=None, ignore_index=-100,  # noqa: A002
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """reference: python/paddle/nn/functional/loss.py cross_entropy.

    Computes log-softmax in f32 regardless of input dtype (AMP black-list
    behavior of the reference).  The common LM-head case (hard labels,
    last axis, no weight/smoothing) routes through the fused
    softmax-cross-entropy VJP above.
    """
    if (use_softmax and not soft_label and label_smoothing == 0.0
            and weight is None and axis in (-1, input.ndim - 1)):
        lbl = label
        if lbl.ndim == input.ndim:
            lbl = jnp.squeeze(lbl, axis)
        loss = _softmax_xent_fused(input, lbl, ignore_index)
        if reduction == "mean":
            mask = lbl != ignore_index
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(mask.astype(loss.dtype)), 1.0)
        return _reduce(loss, reduction)
    x = input.astype(jnp.float32) if input.dtype in (jnp.bfloat16, jnp.float16) \
        else input
    if use_softmax:
        logp = jax.nn.log_softmax(x, axis=axis)
    else:
        logp = jnp.log(jnp.clip(x, 1e-30, None))
    if soft_label:
        lbl = label.astype(logp.dtype)
        if label_smoothing > 0.0:
            n = logp.shape[axis]
            lbl = lbl * (1 - label_smoothing) + label_smoothing / n
        loss = -jnp.sum(lbl * logp, axis=axis)
    else:
        lbl = label
        if lbl.ndim == logp.ndim:
            lbl = jnp.squeeze(lbl, axis)
        lbl_clipped = jnp.clip(lbl, 0, logp.shape[axis] - 1)
        picked = jnp.take_along_axis(
            logp, lbl_clipped[..., None].astype(jnp.int32), axis=axis
        )[..., 0]
        if label_smoothing > 0.0:
            n = logp.shape[axis]
            smooth = jnp.mean(logp, axis=axis)
            loss = -(1 - label_smoothing) * picked - label_smoothing * smooth
        else:
            loss = -picked
        mask = (lbl != ignore_index)
        loss = jnp.where(mask, loss, 0.0)
        if weight is not None:
            w = jnp.take(weight, lbl_clipped.astype(jnp.int32))
            loss = loss * w
            if reduction == "mean":
                denom = jnp.sum(jnp.where(mask, w, 0.0))
                return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
        if reduction == "mean":
            denom = jnp.sum(mask.astype(loss.dtype))
            return jnp.sum(loss) / jnp.maximum(denom, 1.0)
    return _reduce(loss, reduction)


@defop("nll_loss")
def nll_loss(input, label, weight=None, ignore_index=-100,  # noqa: A002
             reduction="mean", name=None):
    picked = jnp.take_along_axis(input, label[..., None].astype(jnp.int32),
                                 axis=-1)[..., 0] if input.ndim == label.ndim + 1 \
        else jnp.take_along_axis(input, label.astype(jnp.int32), axis=1)
    loss = -picked
    mask = label != ignore_index
    loss = jnp.where(mask, loss, 0.0)
    if weight is not None:
        loss = loss * jnp.take(weight, jnp.clip(label, 0, None))
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
    return _reduce(loss, reduction)


@defop("binary_cross_entropy")
def binary_cross_entropy(input, label, weight=None, reduction="mean",  # noqa: A002
                         name=None):
    x = jnp.clip(input, 1e-12, 1.0 - 1e-12)
    loss = -(label * jnp.log(x) + (1.0 - label) * jnp.log1p(-x))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@defop("binary_cross_entropy_with_logits")
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    max_val = jnp.clip(-logit, 0, None)
    if pos_weight is not None:
        log_w = (pos_weight - 1.0) * label + 1.0
        loss = (1.0 - label) * logit + log_w * (
            jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        loss = (1.0 - label) * logit + max_val + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@defop("kl_div")
def kl_div(input, label, reduction="mean", log_target=False, name=None):  # noqa: A002
    if log_target:
        loss = jnp.exp(label) * (label - input)
    else:
        loss = label * (jnp.log(jnp.clip(label, 1e-12, None)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


@defop("softmax_with_cross_entropy")
def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1, name=None):
    logp = jax.nn.log_softmax(
        logits.astype(jnp.float32) if logits.dtype in (jnp.bfloat16, jnp.float16)
        else logits, axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = label
        if lbl.ndim == logp.ndim:
            lbl = jnp.squeeze(lbl, axis)
        picked = jnp.take_along_axis(logp, lbl[..., None].astype(jnp.int32),
                                     axis=axis)
        loss = -picked
        loss = jnp.where((lbl != ignore_index)[..., None], loss, 0.0)
    if return_softmax:
        return loss, jnp.exp(logp)
    return loss


@defop("margin_ranking_loss")
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",  # noqa: A002
                        name=None):
    loss = jnp.clip(-label * (input - other) + margin, 0, None)
    return _reduce(loss, reduction)


@defop("hinge_embedding_loss")
def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    loss = jnp.where(label == 1, input, jnp.clip(margin - input, 0, None))
    return _reduce(loss, reduction)


@defop("cosine_similarity")
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.clip(n1 * n2, eps, None)


@defop("cosine_embedding_loss")
def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    cos = cosine_similarity.__wrapped__(input1, input2, axis=1)
    loss = jnp.where(label == 1, 1.0 - cos, jnp.clip(cos - margin, 0, None))
    return _reduce(loss, reduction)


@defop("triplet_margin_loss")
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,  # noqa: A002
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def dist(a, b):
        return jnp.sum(jnp.abs(a - b) ** p + epsilon, axis=-1) ** (1.0 / p)
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    loss = jnp.clip(d_pos - d_neg + margin, 0, None)
    return _reduce(loss, reduction)


@defop("square_error_cost")
def square_error_cost(input, label):  # noqa: A002
    return jnp.square(input - label)


# ---------------- attention ----------------


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """reference: paddle.nn.functional.scaled_dot_product_attention
    (flash-attn kernel at paddle/phi/kernels/gpu/flash_attn_kernel.cu:203).
    Inputs [batch, seq, heads, head_dim].  Uses the Pallas flash-attention
    kernel on TPU when available, else the XLA fallback."""
    from ..pallas import flash_attention as fa
    return fa.flash_attention(query, key, value, attn_mask=attn_mask,
                              dropout=dropout_p, causal=is_causal,
                              training=training)


def _sdpa_xla(q, k, v, attn_mask=None, causal=False, scale=None):
    """Plain XLA attention on [B, S, H, D]."""
    d = q.shape[-1]
    scale = scale or (1.0 / np.sqrt(d))
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    if causal:
        s_q, s_k = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), jnp.bool_), k=s_k - s_q)
        logits = jnp.where(mask, logits, -1e30)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, -1e30)
        else:
            logits = logits + attn_mask
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out


# ---------------- misc ----------------


@defop("label_smooth")
def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    n = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / n


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    from ..core.dispatch import apply_op as _ap
    from ..core.dtype import convert_dtype
    if maxlen is None:
        maxlen = int(np.asarray(lengths.numpy()).max())

    def fn(l):  # noqa: E741
        return (jnp.arange(maxlen)[None, :] < l[..., None]).astype(
            convert_dtype(dtype))
    return _ap("sequence_mask", fn, (lengths,), nondiff=True)


@defop("temporal_shift")
def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    nt, c, h, w = x.shape
    n = nt // seg_num
    x5 = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    left = jnp.concatenate([x5[:, 1:, :fold], jnp.zeros_like(x5[:, :1, :fold])], 1)
    right = jnp.concatenate([jnp.zeros_like(x5[:, :1, fold:2 * fold]),
                             x5[:, :-1, fold:2 * fold]], 1)
    mid = x5[:, :, 2 * fold:]
    return jnp.concatenate([left, right, mid], axis=2).reshape(nt, c, h, w)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    from ..tensor_ops.manipulation import pad as _pad
    return _pad(x, pad, mode=mode, value=value, data_format=data_format)


# ---- long-tail surface (1D/3D pools, unpool, loss zoo, decode) ----
from .functional_extra import (  # noqa: F401,E402
    max_pool3d, avg_pool3d, adaptive_avg_pool1d, adaptive_max_pool1d,
    adaptive_avg_pool3d, adaptive_max_pool3d, max_unpool1d, max_unpool2d,
    max_unpool3d, conv1d_transpose, conv3d_transpose, fold,
    pixel_unshuffle, channel_shuffle, zeropad2d, sigmoid, tanh,
    log_sigmoid, gumbel_softmax, pairwise_distance,
    bilinear, diag_embed, log_loss, dice_loss, npair_loss,
    sigmoid_focal_loss, soft_margin_loss, multi_label_soft_margin_loss,
    multi_margin_loss, poisson_nll_loss, gaussian_nll_loss,
    triplet_margin_with_distance_loss, hsigmoid_loss,
    margin_cross_entropy, ctc_loss, rnnt_loss, affine_grid, gather_tree,
    sparse_attention, class_center_sample,
)
from ..tensor_ops.inplace import _make_inplace as _mk_ip  # noqa: E402

relu_ = _mk_ip(relu, "relu_")
elu_ = _mk_ip(elu, "elu_")
hardtanh_ = _mk_ip(hardtanh, "hardtanh_")
leaky_relu_ = _mk_ip(leaky_relu, "leaky_relu_")
softmax_ = _mk_ip(softmax, "softmax_")
tanh_ = _mk_ip(tanh, "tanh_")
thresholded_relu_ = _mk_ip(thresholded_relu, "thresholded_relu_")
