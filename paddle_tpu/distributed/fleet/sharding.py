"""ZeRO-style sharded training (stages 1/2/3).

Reference capability: DygraphShardingOptimizer (reference:
fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:39),
GroupShardedOptimizerStage2 (sharding/group_sharded_optimizer_stage2.py:53),
GroupShardedStage2/3 (group_sharded_stage2.py:46, group_sharded_stage3.py:59)
— rank-bucketed parameter ownership, reduce-scattered grads, broadcast of
updated params, fused storage buffers.

TPU-native realization: ZeRO is a *sharding layout*, not a protocol.
- stage 1 (optimizer state sharded): moment tensors committed with Shard(0)
  over the dp/sharding axis; params stay replicated.  XLA turns the update
  into compute-on-shard + all-gather.
- stage 2 (+grad sharded): gradients get a reduce-scatter instead of
  all-reduce — GSPMD picks this automatically when the consumer (moment
  update) is sharded.
- stage 3 (+params sharded): params themselves committed Shard(0); forward
  all-gathers weights just-in-time (XLA schedules/overlaps), exactly the
  reference's stage-3 broadcast-on-use.
All three fall out of `shard_parameters`/`shard_optimizer_states` below; the
bucketed storage/fused-buffer machinery (group_sharded_storage.py) is
unnecessary — XLA manages device memory.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ..mesh import get_mesh
from ..placement import Shard, Replicate, named_sharding, commit_param, shardable_on


def shard_parameters(parameters, axis="sharding", mesh=None):
    """Commit params Shard(0) over `axis` (ZeRO-3 layout)."""
    mesh = mesh or get_mesh()
    for p in parameters:
        placements = list(p.placements) if p.placements else \
            [Replicate() for _ in mesh.dim_names]
        if shardable_on(p._data_.shape, mesh, axis) and not any(
                isinstance(pl, Shard) and pl.dim == 0 for pl in placements):
            placements[mesh.dim_names.index(axis)] = Shard(0)
        commit_param(p, mesh, placements)
    return parameters


def shard_optimizer_states(optimizer, axis="sharding", mesh=None):
    """Commit existing accumulators Shard(0) over `axis` (ZeRO-1 layout) and
    install a factory so future accumulators are born sharded."""
    mesh = mesh or get_mesh()

    def commit(arr):
        if shardable_on(arr.shape, mesh, axis):
            sh = named_sharding(mesh, [
                Shard(0) if n == axis else Replicate()
                for n in mesh.dim_names], arr.ndim)
            return jax.device_put(arr, sh)
        return arr

    state = getattr(optimizer, "_state", None)
    if state:
        for name, vals in state.items():
            for t in vals:
                if isinstance(t, Tensor) and hasattr(t._data_, "ndim"):
                    t._data_ = commit(t._data_)
    optimizer._accumulator_commit_hook = commit
    return optimizer


class DygraphShardingOptimizer:
    """reference: dygraph_sharding_optimizer.py:39 — stage-1 wrapper."""

    def __init__(self, optimizer, hcg=None, axis="sharding"):
        self._inner = optimizer
        self._axis = axis
        shard_optimizer_states(optimizer, axis=axis)

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self):
        return self._inner.step()

    def clear_grad(self, *a, **k):
        return self._inner.clear_grad(*a, **k)


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """reference: python/paddle/distributed/sharding/group_sharded.py
    group_sharded_parallel(level='os'|'os_g'|'p_g_os').

    level='os'     → stage 1 (optimizer states sharded)
    level='os_g'   → stage 2 (+grads reduce-scattered — automatic)
    level='p_g_os' → stage 3 (+params sharded)
    """
    mesh = get_mesh()
    axis = "sharding" if (mesh and "sharding" in mesh.dim_names
                          and mesh.get_dim_size("sharding") > 1) else "dp"
    if level in ("p_g_os",):
        shard_parameters(list(model.parameters()), axis=axis, mesh=mesh)
    shard_optimizer_states(optimizer, axis=axis, mesh=mesh)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """reference: group_sharded.py save_group_sharded_model — gathers the
    full (unsharded) state for a portable checkpoint."""
    from ...framework.io import save
    state = {k: Tensor(jax.device_get(v._data_))
             for k, v in model.state_dict().items()}
    save(state, output + ".pdparams" if not output.endswith(".pdparams")
         else output)
    if optimizer is not None:
        ostate = optimizer.state_dict()
        save(ostate, output + ".pdopt")
