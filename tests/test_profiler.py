"""Profiler tests (reference: test/legacy_test profiler tests — scheduler
state machine, span capture, chrome export)."""
import json
import os

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.profiler import (
    Profiler, ProfilerState, ProfilerTarget, RecordEvent, make_scheduler,
)


def test_make_scheduler_states():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=1)
    states = [sched(i) for i in range(6)]
    assert states[0] == ProfilerState.CLOSED
    assert states[1] == ProfilerState.READY
    assert states[2] == ProfilerState.RECORD
    assert states[3] == ProfilerState.RECORD_AND_RETURN
    assert states[4] == ProfilerState.CLOSED  # repeat exhausted


def test_profiler_records_spans_and_exports(tmp_path):
    done = []
    prof = Profiler(targets=[ProfilerTarget.CPU],
                    scheduler=make_scheduler(closed=0, ready=0, record=2,
                                             repeat=1),
                    on_trace_ready=lambda p: done.append(p),
                    timer_only=True)
    prof.start()
    for step in range(3):
        with RecordEvent("forward"):
            x = paddle.randn([32, 32])
            (x @ x).numpy()
        with RecordEvent("backward"):
            pass
        prof.step()
    prof.stop()
    names = {e["name"] for e in prof.events}
    assert "forward" in names
    assert any(n.startswith("ProfileStep") for n in names)

    out = str(tmp_path / "trace.json")
    prof.export(out)
    data = json.load(open(out))
    assert len(data["traceEvents"]) > 0

    table = prof.summary()
    assert "forward" in table


def test_record_event_outside_profiler_is_noop():
    with RecordEvent("orphan"):
        pass  # must not raise or leak into the next profiler


def test_benchmark_ips():
    bm = profiler.benchmark()
    bm.begin()
    for _ in range(3):
        bm.before_reader()
        bm.after_reader()
        bm.after_step(num_samples=4)
    assert bm.ips > 0
    assert "ips" in bm.step_info()


def test_mfu_calculator():
    # 1 TFLOP step in 0.1s on a nominal-1TFLOPs cpu device = 10x? no:
    # mfu = flops/time/peak; just sanity-check monotonicity + bounds
    m1 = profiler.mfu(1e12, 1.0, n_devices=1)
    m2 = profiler.mfu(1e12, 2.0, n_devices=1)
    assert m1 > m2 > 0
