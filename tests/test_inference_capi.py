"""C inference API: build libpaddle_inference_c.so, compile a C host
program against it, and predict from pure C (reference:
paddle/fluid/inference/capi_exp/ + test/cpp/inference/api smokes)."""
import os
import subprocess
import sys
import sysconfig

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, static
from paddle_tpu.jit import InputSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_c_api_predicts_from_c_host(tmp_path):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    x = np.ones((2, 8), np.float32)
    ref = net(paddle.to_tensor(x)).numpy()

    prefix = str(tmp_path / "model")
    static.save_inference_model(
        prefix, [InputSpec([2, 8], "float32", "x")], None, layer=net)

    from paddle_tpu.inference.capi import build_c_api, header_path
    so = build_c_api(output_dir=str(tmp_path))
    assert os.path.exists(so) and os.path.exists(header_path())

    exe = str(tmp_path / "capi_smoke")
    smoke = os.path.join(os.path.dirname(__file__), "capi_smoke.c")
    r = subprocess.run(
        ["gcc", smoke, "-o", exe,
         f"-I{os.path.dirname(header_path())}",
         f"-L{os.path.dirname(so)}", f"-Wl,-rpath,{os.path.dirname(so)}",
         "-lpaddle_inference_c"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr

    env = dict(os.environ,
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""),
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    r = subprocess.run([exe, prefix], capture_output=True, text=True,
                       timeout=600, env=env)
    assert r.returncode == 0, f"stdout:{r.stdout}\nstderr:{r.stderr}"
    parts = r.stdout.split()
    assert parts[0] == "OK" and int(parts[1]) == ref.size
    got = np.array([float(v) for v in parts[2:]]).reshape(ref.shape)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    # int8 path from C: output within weight-only-quant tolerance
    r = subprocess.run([exe, prefix, "1"], capture_output=True, text=True,
                       timeout=600, env=env)
    assert r.returncode == 0, f"stdout:{r.stdout}\nstderr:{r.stderr}"
    parts = r.stdout.split()
    got8 = np.array([float(v) for v in parts[2:]]).reshape(ref.shape)
    np.testing.assert_allclose(got8, ref, rtol=0.1, atol=0.1)
