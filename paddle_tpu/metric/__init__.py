"""Metrics (reference capability: python/paddle/metric/metrics.py —
Metric base + Accuracy/Precision/Recall/Auc used by hapi.Model)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    if isinstance(x, Tensor):
        return np.asarray(x._data_)
    return np.asarray(x)


class Metric:
    def __init__(self, name=None):
        self._name = name or type(self).__name__.lower()

    def name(self):
        return self._name

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def compute(self, pred, label, *args):
        """Optional pre-processing hook run on device outputs."""
        return pred, label


class Accuracy(Metric):
    """reference: metric/metrics.py Accuracy (top-k)."""

    def __init__(self, topk=(1,), name="acc"):
        super().__init__(name)
        self.topk = topk if isinstance(topk, (tuple, list)) else (topk,)
        self.reset()

    def reset(self):
        self.correct = np.zeros(len(self.topk))
        self.total = 0

    def compute(self, pred, label, *args):
        p = _np(pred)
        lbl = _np(label).reshape(-1)
        k = max(self.topk)
        top = np.argsort(-p, axis=-1)[..., :k].reshape(len(lbl), k)
        return top, lbl

    def update(self, correct, label=None):
        if label is not None:
            top, lbl = correct, label
        else:
            top, lbl = correct
        top = _np(top)
        lbl = _np(lbl).reshape(-1)
        for i, k in enumerate(self.topk):
            self.correct[i] += (top[:, :k] == lbl[:, None]).any(-1).sum()
        self.total += len(lbl)
        return self.correct[0] / max(self.total, 1)

    def accumulate(self):
        acc = [c / max(self.total, 1) for c in self.correct]
        return acc[0] if len(acc) == 1 else acc


class Precision(Metric):
    """Binary precision (reference: metrics.py Precision)."""

    def __init__(self, name="precision"):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds).reshape(-1) > 0.5).astype(np.int64)
        y = _np(labels).reshape(-1).astype(np.int64)
        self.tp += int(((p == 1) & (y == 1)).sum())
        self.fp += int(((p == 1) & (y == 0)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fp, 1)


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds).reshape(-1) > 0.5).astype(np.int64)
        y = _np(labels).reshape(-1).astype(np.int64)
        self.tp += int(((p == 1) & (y == 1)).sum())
        self.fn += int(((p == 0) & (y == 1)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fn, 1)


class Auc(Metric):
    """Approximate ROC-AUC via histogram buckets
    (reference: metrics.py Auc num_thresholds binning)."""

    def __init__(self, num_thresholds=4095, name="auc"):
        super().__init__(name)
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._pos = np.zeros(self.num_thresholds + 1)
        self._neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        y = _np(labels).reshape(-1)
        idx = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        np.add.at(self._pos, idx[y == 1], 1)
        np.add.at(self._neg, idx[y == 0], 1)

    def accumulate(self):
        tot_pos = self._pos.sum()
        tot_neg = self._neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate TPR over FPR from the histogram (trapezoid)
        pos_c = np.cumsum(self._pos[::-1])
        neg_c = np.cumsum(self._neg[::-1])
        tpr = pos_c / tot_pos
        fpr = neg_c / tot_neg
        return float(np.trapezoid(tpr, fpr))


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    """Functional top-k accuracy (reference: metric/metrics.py accuracy)."""
    import jax.numpy as jnp
    pred = input._data_ if isinstance(input, Tensor) else jnp.asarray(input)
    lbl = (label._data_ if isinstance(label, Tensor)
           else jnp.asarray(label)).reshape(-1)
    topk = jnp.argsort(-pred, axis=-1)[:, :k]
    hit = jnp.any(topk == lbl[:, None], axis=-1)
    return Tensor(jnp.mean(hit.astype(jnp.float32)))
