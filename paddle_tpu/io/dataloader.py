"""DataLoader (reference: python/paddle/io/dataloader/dataloader_iter.py:150,358
— multiprocess workers + shared-memory queues + C++ blocking queue).

TPU-native realization: a thread-pool prefetch pipeline feeding device
transfers asynchronously (jax device_put is async).  Multiprocess workers via
`num_workers` use a thread pool here — on TPU the input pipeline is host-CPU
bound but GIL-released inside numpy/jax, so threads provide the overlap the
reference gets from worker processes, without shared-memory plumbing.  A C++
ring-buffer feeder (csrc/) can be slotted under this when IO becomes the
bottleneck.
"""
from __future__ import annotations

import queue
import threading
import warnings

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


class DataLoaderTimeoutError(TimeoutError):
    """``DataLoader(timeout=T)`` expired while waiting for a batch —
    names the batch so a hung worker is attributable."""

    def __init__(self, batch_index, timeout):
        self.batch_index = int(batch_index)
        self.timeout = float(timeout)
        super().__init__(
            f"DataLoader timed out after {timeout:g}s waiting for "
            f"batch {batch_index}")


class DataLoaderWarning(UserWarning):
    """Typed warning for DataLoader args this loader accepts for
    reference-API compatibility but does not implement."""


_WARNED_ARGS = set()


def _warn_unsupported(name, why):
    if name in _WARNED_ARGS:
        return
    _WARNED_ARGS.add(name)
    warnings.warn(f"DataLoader({name}=...) is not supported by the "
                  f"TPU-native loader and is ignored: {why}",
                  DataLoaderWarning, stacklevel=3)


class _WorkerFailure:
    """In-queue wrapper distinguishing a worker exception from a batch
    that happens to BE an Exception instance."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


def default_collate_fn(batch):
    """Stack samples into batched Tensors (reference: collate.py)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp
        return Tensor(jnp.stack([s._data for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(group))
                            for group in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self.shm_slot_size = 16 << 20  # 16 MiB per batch slot
        self.prefetch_factor = max(prefetch_factor, 2)
        self.timeout = float(timeout or 0)
        if self.timeout < 0:
            raise ValueError(f"DataLoader(timeout={timeout}): must be >= 0")
        if persistent_workers:
            _warn_unsupported(
                "persistent_workers",
                "workers are per-epoch (threads are cheap; shm worker "
                "processes rebind the dataset each epoch)")
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _fetch(self, indices):
        import time
        from ..utils import monitor as _monitor
        _monitor.incr("io.batches_fetched")
        t0 = time.perf_counter()
        samples = [self.dataset[i] for i in indices]
        batch = self.collate_fn(samples)
        # reader cost distribution (histogram in the metrics registry):
        # the number that says whether input pipeline or device bounds a
        # training run
        _monitor.observe("io.fetch_ms", (time.perf_counter() - t0) * 1e3)
        return batch

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def __iter__(self):
        if self._iterable_mode:
            yield from self._iter_iterable()
            return
        if self.num_workers == 0:
            for indices in self.batch_sampler:
                yield self._fetch(indices)
            return
        if self.use_shared_memory:
            try:
                yield from self._iter_multiprocess()
                return
            except ImportError:
                pass
            except OSError:
                pass  # g++/shm unavailable → threaded fallback
        yield from self._iter_threaded()

    def _iter_multiprocess(self):
        """Worker PROCESSES + the native shared-memory ring queue
        (reference: dataloader_iter.py:358 _DataLoaderIterMultiProcess with
        use_shared_memory=True over the C++ blocking queue)."""
        import multiprocessing as mp
        from .shm_queue import ShmQueue, QueueClosed
        from ..utils.cpp_extension import BuildError

        all_batches = list(enumerate(self.batch_sampler))
        n_batches = len(all_batches)
        if n_batches == 0:
            return
        nw = self.num_workers
        try:
            out_q = ShmQueue(capacity=max(2 * nw, 4),
                             slot_size=self.shm_slot_size)
        except BuildError as e:
            raise OSError(str(e))

        ctx = mp.get_context("fork")

        def worker(worker_id):
            try:
                from . import worker_info as _wi
                _wi._WORKER_INFO = _wi.WorkerInfo(
                    id=worker_id, num_workers=nw, dataset=self.dataset)
                if self.worker_init_fn is not None:
                    self.worker_init_fn(worker_id)
                for i, indices in all_batches[worker_id::nw]:
                    batch = self._fetch_numpy(indices)
                    out_q.put((i, batch), timeout=0)
            except (QueueClosed, KeyboardInterrupt):
                pass
            except Exception as e:
                # surface the real failure in the TRAINER process — a
                # bare worker exit(1) with the traceback lost to stderr
                # is undebuggable (oversized batch vs slot_size is the
                # classic case)
                try:
                    # truncate: an error message larger than the slot
                    # would fail the put and drop the report entirely
                    msg = f"worker {worker_id}: {type(e).__name__}: {e}"
                    out_q.put(("__worker_error__", msg[:4096]),
                              timeout=5.0)
                except Exception:
                    pass
                raise

        procs = [ctx.Process(target=worker, args=(w,), daemon=True)
                 for w in range(nw)]
        for p in procs:
            p.start()
        pending = {}
        try:
            for want in range(n_batches):
                waited = 0.0
                while want not in pending:
                    poll = 5.0
                    if self.timeout:
                        poll = max(min(poll, self.timeout - waited), 0.01)
                    try:
                        i, batch = out_q.get(timeout=poll)
                    except TimeoutError:
                        waited += poll
                        # fail fast only when the batch we are waiting on
                        # belongs to a crashed worker (batch i is produced
                        # by worker i % nw) — a worker that died AFTER
                        # delivering, or a slow-but-live worker, is fine
                        owner = procs[want % nw]
                        if owner.exitcode not in (None, 0):
                            raise RuntimeError(
                                f"DataLoader worker {want % nw} exited "
                                f"unexpectedly (code {owner.exitcode}) "
                                f"before delivering batch {want}; "
                                f"see stderr")
                        if self.timeout and waited >= self.timeout:
                            raise DataLoaderTimeoutError(want, self.timeout)
                        continue
                    if i == "__worker_error__":
                        raise RuntimeError(
                            f"DataLoader worker failed: {batch}")
                    pending[i] = batch
                yield self.collate_fn(pending.pop(want))
        finally:
            out_q.close()
            for p in procs:
                p.join(timeout=2)
                if p.exitcode is None:
                    p.terminate()
                    # reap after terminate: an unjoined killed child stays
                    # a zombie for the life of the trainer, leaking a pid
                    # per worker per epoch
                    p.join(timeout=2)
            out_q.release()

    def _fetch_numpy(self, indices):
        """Worker-side fetch: keep samples as numpy/python (picklable,
        device-free) — collation to device Tensors happens in the trainer
        process (matches the reference's worker → trainer split)."""
        return [self.dataset[i] for i in indices]

    def _iter_threaded(self):
        """Worker thread pool streaming through bounded queues (the
        reference's _DataLoaderIterMultiProcess shape, reference:
        dataloader_iter.py:358).

        The batch sampler is consumed LAZILY by a feeder thread through
        a queue bounded at ``num_workers * prefetch_factor`` — the old
        implementation materialized the whole epoch's index list plus
        one Queue per batch up front, O(epoch) memory before the first
        batch.  Delivery stays in-order via a reorder buffer; a worker
        exception is re-raised at its batch's position; ``timeout``
        bounds the wait for each batch."""
        nw = self.num_workers
        window = nw * self.prefetch_factor
        index_q = queue.Queue(maxsize=window)
        out_q = queue.Queue(maxsize=window)
        stop = threading.Event()

        def _put(q, item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def feeder():
            try:
                for item in enumerate(self.batch_sampler):
                    if not _put(index_q, item):
                        return
            except Exception as e:  # sampler failure → consumer
                _put(out_q, ("sampler_error", None, _WorkerFailure(e)))
                return
            for _ in range(nw):     # one end-marker per worker
                if not _put(index_q, None):
                    return

        def worker():
            while not stop.is_set():
                try:
                    item = index_q.get(timeout=0.1)
                except queue.Empty:
                    continue
                if item is None:
                    _put(out_q, ("done", None, None))
                    return
                i, indices = item
                try:
                    _put(out_q, ("batch", i, self._fetch(indices)))
                except Exception as e:  # re-raised at position i
                    _put(out_q, ("batch", i, _WorkerFailure(e)))

        threads = [threading.Thread(target=feeder, daemon=True)]
        threads += [threading.Thread(target=worker, daemon=True)
                    for _ in range(nw)]
        for t in threads:
            t.start()
        pending = {}
        want = 0
        done_workers = 0
        waited = 0.0
        poll = 0.2
        try:
            while True:
                if want in pending:
                    item = pending.pop(want)
                    if isinstance(item, _WorkerFailure):
                        raise item.exc
                    yield item
                    want += 1
                    waited = 0.0
                    continue
                if done_workers == nw:
                    # FIFO guarantees each worker's batches precede its
                    # end-marker, so nothing is still in flight
                    return
                try:
                    kind, i, payload = out_q.get(timeout=poll)
                except queue.Empty:
                    waited += poll
                    if self.timeout and waited >= self.timeout:
                        raise DataLoaderTimeoutError(want, self.timeout)
                    continue
                if kind == "done":
                    done_workers += 1
                elif kind == "sampler_error":
                    raise payload.exc
                else:
                    pending[i] = payload
        finally:
            stop.set()
