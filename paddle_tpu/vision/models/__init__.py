from .lenet import LeNet  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152,
    BasicBlock, BottleneckBlock,
)
from .mobilenet import MobileNetV1, mobilenet_v1  # noqa: F401
from .extra_models import (  # noqa: F401
    VGG, vgg11, vgg13, vgg16, vgg19, AlexNet, alexnet, SqueezeNet,
    squeezenet1_0, squeezenet1_1, DenseNet, densenet121, densenet161,
    densenet169, densenet201, densenet264, GoogLeNet, googlenet,
    InceptionV3, inception_v3, ShuffleNetV2, shufflenet_v2_x0_25,
    shufflenet_v2_x0_33, shufflenet_v2_swish,
    shufflenet_v2_x0_5, shufflenet_v2_x1_0, shufflenet_v2_x1_5,
    shufflenet_v2_x2_0, MobileNetV2, mobilenet_v2, MobileNetV3Small,
    MobileNetV3Large, mobilenet_v3_small, mobilenet_v3_large,
    resnext50_32x4d, resnext101_32x4d, resnext152_32x4d,
    resnext50_64x4d, resnext101_64x4d, resnext152_64x4d,
    wide_resnet50_2, wide_resnet101_2,
)


# pretrained=True story (reference: per-arch model_urls +
# get_weights_path_from_url, e.g. vision/models/squeezenet.py:25): every
# lowercase factory is wrapped so pretrained=True loads
# <WEIGHTS_HOME>/<arch>.pdparams from the local cache — this environment
# has no egress, so the cache is the source of truth (utils/download.py)
import functools as _functools


def _with_pretrained(fn, arch):
    @_functools.wraps(fn)
    def wrapper(pretrained=False, **kwargs):
        model = fn(pretrained=False, **kwargs)
        if pretrained:
            from ...utils.download import load_pretrained_weights
            load_pretrained_weights(model, arch)
        return model
    return wrapper


for _name, _fn in list(globals().items()):
    if (callable(_fn) and _name[:1].islower() and not _name.startswith("_")
            and "pretrained" in getattr(
                getattr(_fn, "__wrapped__", _fn), "__code__",
                type("c", (), {"co_varnames": ()})).co_varnames):
        globals()[_name] = _with_pretrained(_fn, _name)
del _functools, _name, _fn
