"""Hot-spare recovery: buddy-replicated in-memory shard snapshots.

Every recovery path the runtime already has — guardian peer-abort,
elastic reshard, sentinel rollback — bottoms out in a DISK checkpoint,
so one flaky host costs all steps since the last persisted ``ckpt-N``
plus a storage round-trip.  Gemini (SOSP '23) and CheckFreq (FAST '21)
show that replicating shard state into *peer host RAM* at near-every-
step cadence makes recovery seconds-fast while disk stays the
durability backstop.  This module is that layer, built from primitives
the repo already ships:

- each rank, every ``FLAGS_hot_spare_every`` update steps, snapshots
  its shard state (params, optimizer moments, GradScaler vec, RNG
  counter, data-pipeline position — the exact
  ``Model._sentinel_snapshot()`` shape) into host RAM and streams it to
  its **ring buddy**'s RAM over the rpc ``Blob`` raw-byte fast path —
  chunked, crc32-per-chunk, and double-buffered on the receiver: a
  sender crash mid-transfer can never clobber the buddy's last valid
  copy, because staged chunks only replace it at a fully-verified
  commit;
- buddy assignment derives from the active ``ProcessMesh`` process
  order (ring: rank ``i``'s replica lives on the next process in mesh
  order) and re-derives on elastic resize;
- on a *cooperative* exit (preemption SIGTERM, clean end) the agent
  **parks** every snapshot it holds — its own and its buddies'
  replicas — into the guardian store, so a full-pod relaunch (the
  controller restarts all ranks when one dies) still finds the dead
  rank's RAM-resident state: live-RPC pull from the holder first,
  parked copy second.  With a TCPStore guardian the parked bytes are
  genuinely memory-resident on the controller host; the FileKVStore
  substrate (single-host tests) stands in for it transport-wise.

Recovery is a ladder tried loudest-first (docs/FAULT_TOLERANCE.md
"Recovery ladder"):

1. **peer restore** — the relaunched incarnation reads the buddy map
   the controller advertised through the guardian store, pulls the
   dead rank's shard from its buddy (live endpoint, then parked copy),
   crc- and finiteness-validates it, and resumes.  Target: seconds.
2. **sentinel rollback** prefers the newest finiteness-validated local
   snapshot over the disk anchor when fresher (framework/sentinel.py).
3. **disk ``restore_latest``** as today — and byte-identical to it
   when ``FLAGS_hot_spare`` is off.

Every fall-through is loud: a dead buddy, torn transfer, or corrupt
snapshot emits a typed :class:`PeerRestoreWarning` naming the rung that
failed before the next rung runs.  Telemetry (``ckpt.peer.*``) is
declared at arm time so "zero peer restores" on a dashboard means
"nothing failed", never "nobody was counting".
"""
from __future__ import annotations

import io
import json
import os
import pickle
import sys
import threading
import time
import warnings
import zlib

from ..utils.flags import flag as _flag

SCHEMA_VERSION = 1

#: guardian-store key layout (all under ``{job}/hot_spare/``)
_K_BUDDIES = "{job}/hot_spare/buddies"
_K_ENDPOINT = "{job}/hot_spare/endpoints/r{rank}"
_K_PARKED = "{job}/hot_spare/parked/r{rank}"


class PeerSnapshotError(RuntimeError):
    """Base class for hot-spare snapshot/restore failures."""


class BuddyUnavailableError(PeerSnapshotError):
    """The buddy holding this rank's replica cannot serve it (dead
    endpoint, no parked copy, or the ``buddy_crash`` injection)."""


class SnapshotIntegrityError(PeerSnapshotError):
    """A peer snapshot failed crc or finiteness validation — bitrot or
    a torn transfer that somehow reached a reader."""


class PeerRestoreWarning(UserWarning):
    """Typed warning emitted whenever the recovery ladder falls through
    a rung — peer restore failing over to disk must be loud."""


# ----------------------------------------------------------------------
# telemetry — declared at arm time so every series exposes from zero
# ----------------------------------------------------------------------
def declare_metrics():
    """Pre-register the full ``ckpt.peer.*`` family (counters at 0,
    histograms with 0 samples) in the process registry."""
    from ..observability import registry as _registry
    _registry.counter("ckpt.peer.snapshots",
                      "peer snapshots committed to a buddy's RAM")
    _registry.counter("ckpt.peer.bytes_sent",
                      "snapshot payload bytes streamed to buddies")
    _registry.counter("ckpt.peer.restores",
                      "recoveries served from a peer snapshot")
    _registry.counter("ckpt.peer.stale_skipped",
                      "peer snapshots consulted but older than the "
                      "competing disk state")
    _registry.counter("ckpt.peer.crc_failures",
                      "snapshot chunks/payloads failing crc or "
                      "finiteness validation")
    _registry.histogram("ckpt.peer.transfer_ms",
                        "wall time of one snapshot stream to the buddy")
    _registry.histogram("ckpt.peer.restore_ms",
                        "wall time of a peer-snapshot restore")
    return _registry


def _counter(name):
    from ..observability import registry as _registry
    return _registry.counter(name)


def _observe(name, value):
    from ..observability import registry as _registry
    _registry.histogram(name).observe(value)


# ----------------------------------------------------------------------
# buddy ring
# ----------------------------------------------------------------------
def derive_buddies(world, mesh=None):
    """``{rank: holder_rank}`` — rank ``r``'s snapshot replica lives on
    ``buddies[r]``, the next process in ring order.  Ring order is the
    active ``ProcessMesh``'s process order when one is installed for
    this world size (so a hybrid mesh keeps replicas off the same
    model-parallel group where possible), else plain rank order.  A
    world of one has no buddy (local snapshots only)."""
    world = int(world)
    order = None
    if mesh is None:
        try:
            from ..distributed.mesh import get_mesh
            mesh = get_mesh()
        except Exception:
            mesh = None
    if mesh is not None:
        try:
            pids = list(mesh.process_ids)
            if len(pids) == world:
                order = pids
        except Exception:
            order = None
    if order is None:
        order = list(range(world))
    if len(order) < 2:
        return {}
    n = len(order)
    return {int(order[i]): int(order[(i + 1) % n]) for i in range(n)}


def advertise_buddy_map(store, job, world, mesh=None, resized_from=None):
    """Write the buddy map into the guardian store (the launch
    controller calls this each incarnation; relaunched workers read it
    before their own mesh exists).  Returns the map."""
    buddies = derive_buddies(world, mesh=mesh)
    doc = {"schema": SCHEMA_VERSION, "world": int(world),
           "buddies": {str(k): v for k, v in buddies.items()}}
    if resized_from is not None:
        doc["resized_from"] = int(resized_from)
    store.set(_K_BUDDIES.format(job=job), json.dumps(doc).encode())
    return buddies


def read_buddy_map(store, job):
    """The advertised ``{rank: holder}`` map, or None."""
    raw = store.get(_K_BUDDIES.format(job=job))
    if not raw:
        return None
    try:
        doc = json.loads(bytes(raw).decode())
        return {int(k): int(v) for k, v in doc["buddies"].items()}
    except (ValueError, KeyError, TypeError):
        return None


# ----------------------------------------------------------------------
# snapshot records + the receiver-side double-buffered store
# ----------------------------------------------------------------------
def pack_state(state):
    """Host-side state tree → payload bytes (pickle of the flattened
    reshard tree: the object skeleton plus the flat numpy arrays dict,
    so a peer restore feeds the SAME assembly ``_resume_from`` uses)."""
    from ..distributed.reshard import flatten_state
    tree, arrays = flatten_state(state)
    buf = io.BytesIO()
    pickle.dump({"tree": tree, "arrays": arrays}, buf,
                protocol=pickle.HIGHEST_PROTOCOL)
    return buf.getvalue()


def unpack_state(payload):
    """Payload bytes → the original state tree."""
    from ..distributed.reshard import rebuild_state
    doc = pickle.loads(payload)
    return rebuild_state(doc["tree"], doc["arrays"])


def make_record(owner, step, book, state):
    payload = pack_state(state)
    return {"schema": SCHEMA_VERSION, "owner": int(owner),
            "step": int(step), "book": dict(book or {}),
            "nbytes": len(payload), "crc": zlib.crc32(payload),
            "payload": payload, "parked_by": None}


def verify_record(record):
    """crc-check a record's payload; raises SnapshotIntegrityError (and
    counts ``ckpt.peer.crc_failures``) on mismatch."""
    crc = zlib.crc32(record["payload"])
    if crc != record["crc"] or len(record["payload"]) != record["nbytes"]:
        _counter("ckpt.peer.crc_failures").inc()
        raise SnapshotIntegrityError(
            f"peer snapshot for rank {record.get('owner')} step "
            f"{record.get('step')} failed crc (got {crc:#x}, recorded "
            f"{record['crc']:#x}, {len(record['payload'])} of "
            f"{record['nbytes']} bytes)")
    return record


def validated_state(record):
    """Record → (state, book) after crc + finiteness validation.  A
    non-finite snapshot is as dead as a torn one — counting it under
    ``crc_failures`` keeps the single 'snapshot unusable' series."""
    verify_record(record)
    state = unpack_state(record["payload"])
    from .checkpoint_manager import validate_finite_state
    try:
        validate_finite_state(state)
    except Exception as e:
        _counter("ckpt.peer.crc_failures").inc()
        raise SnapshotIntegrityError(
            f"peer snapshot for rank {record.get('owner')} step "
            f"{record.get('step')} failed finiteness validation: {e}"
        ) from e
    return state, record["book"]


class HotSpareStore:
    """Receiver-side replica store: one *valid* record per owner rank
    plus per-transfer staging buffers.  Double-buffered by protocol —
    chunks accumulate in staging keyed by transfer id, and only a
    commit whose every chunk arrived and whose whole-payload crc checks
    out flips the owner's valid pointer.  A sender dying mid-transfer
    leaves staging garbage and the previous valid copy untouched."""

    def __init__(self):
        self._lock = threading.Lock()
        self._valid = {}      # owner -> committed record
        self._staging = {}    # (owner, xfer_id) -> staging dict

    def begin(self, owner, xfer_id, step, book, total_chunks,
              total_bytes, payload_crc):
        with self._lock:
            self._staging[(int(owner), str(xfer_id))] = {
                "step": int(step), "book": dict(book or {}),
                "total_chunks": int(total_chunks),
                "total_bytes": int(total_bytes),
                "crc": int(payload_crc), "chunks": {}, "poisoned": False}

    def chunk(self, owner, xfer_id, idx, chunk_crc, data):
        data = bytes(data)
        key = (int(owner), str(xfer_id))
        if zlib.crc32(data) != int(chunk_crc):
            _counter("ckpt.peer.crc_failures").inc()
            with self._lock:
                st = self._staging.get(key)
                if st is not None:
                    st["poisoned"] = True
            raise SnapshotIntegrityError(
                f"chunk {idx} of transfer {xfer_id} (owner {owner}) "
                "failed crc32 — rejected before staging")
        with self._lock:
            st = self._staging.get(key)
            if st is None:
                raise PeerSnapshotError(
                    f"chunk for unknown transfer {xfer_id} "
                    f"(owner {owner}) — no begin seen")
            st["chunks"][int(idx)] = data

    def commit(self, owner, xfer_id):
        """Atomically flip the owner's valid record — or refuse.  The
        previous valid copy survives every refusal path."""
        key = (int(owner), str(xfer_id))
        with self._lock:
            st = self._staging.pop(key, None)
        if st is None:
            raise PeerSnapshotError(
                f"commit for unknown transfer {xfer_id} (owner {owner})")
        if st["poisoned"] or len(st["chunks"]) != st["total_chunks"]:
            raise PeerSnapshotError(
                f"transfer {xfer_id} (owner {owner}) incomplete at "
                f"commit: {len(st['chunks'])}/{st['total_chunks']} "
                f"chunks{' (poisoned)' if st['poisoned'] else ''}")
        payload = b"".join(st["chunks"][i]
                           for i in range(st["total_chunks"]))
        if len(payload) != st["total_bytes"] or \
                zlib.crc32(payload) != st["crc"]:
            _counter("ckpt.peer.crc_failures").inc()
            raise SnapshotIntegrityError(
                f"transfer {xfer_id} (owner {owner}) payload failed "
                "whole-payload crc at commit — last valid copy kept")
        record = {"schema": SCHEMA_VERSION, "owner": int(owner),
                  "step": st["step"], "book": st["book"],
                  "nbytes": st["total_bytes"], "crc": st["crc"],
                  "payload": payload, "parked_by": None}
        with self._lock:
            self._valid[int(owner)] = record
        return record["step"]

    def latest(self, owner):
        with self._lock:
            return self._valid.get(int(owner))

    def install(self, record):
        """Directly install a committed record (local-agent use)."""
        with self._lock:
            self._valid[int(record["owner"])] = record

    def owners(self):
        with self._lock:
            return sorted(self._valid)


#: per-job receiver stores; module-level so the rpc-served functions
#: (pickled by reference) reach the same objects in the server process.
_STORES: dict = {}
_STORES_LOCK = threading.Lock()


def store_for(job):
    with _STORES_LOCK:
        st = _STORES.get(str(job))
        if st is None:
            st = _STORES[str(job)] = HotSpareStore()
        return st


# ------ rpc-served endpoints (module-level: pickled by reference) -----
def _rpc_begin(job, owner, xfer_id, step, book_json, total_chunks,
               total_bytes, payload_crc):
    store_for(job).begin(owner, xfer_id, step, json.loads(book_json),
                         total_chunks, total_bytes, payload_crc)
    return "ok"


def _rpc_chunk(job, owner, xfer_id, idx, chunk_crc, blob):
    data = blob.data if hasattr(blob, "data") else blob
    store_for(job).chunk(owner, xfer_id, idx, chunk_crc, data)
    return "ok"


def _rpc_commit(job, owner, xfer_id):
    return store_for(job).commit(owner, xfer_id)


def _rpc_fetch(job, owner):
    """Serve the newest valid replica held for ``owner`` (live peer
    restore).  Returns pickled record bytes, or None."""
    rec = store_for(job).latest(owner)
    if rec is None:
        return None
    return pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)


# ----------------------------------------------------------------------
# the per-rank agent
# ----------------------------------------------------------------------
_XFER_SEQ = [0]


def _next_xfer_id(rank):
    _XFER_SEQ[0] += 1
    return f"{os.getpid()}-{rank}-{_XFER_SEQ[0]}"


class HotSpareAgent:
    """One per training process.  Owns (a) the rank's own latest
    snapshot record, (b) an rpc endpoint receiving buddies' streams
    into the process-global :class:`HotSpareStore`, and (c) the
    park-on-exit protocol."""

    def __init__(self, job, rank, world, store=None, every=None,
                 chunk_bytes=None, timeout_s=None, serve=None):
        self.job = str(job)
        self.rank = int(rank)
        self.world = int(world)
        self.every = max(int(every if every is not None
                             else _flag("FLAGS_hot_spare_every", 8)), 1)
        self.chunk_bytes = max(int(
            chunk_bytes if chunk_bytes is not None
            else _flag("FLAGS_hot_spare_chunk_kb", 1024) * 1024), 1)
        self.timeout_s = float(timeout_s if timeout_s is not None
                               else _flag("FLAGS_hot_spare_timeout_s",
                                          10.0))
        if store is None:
            from ..distributed.host_collectives import guardian_store
            store = guardian_store()
        self.store = store
        self.buddies = derive_buddies(self.world)
        resized = _resized_worlds()
        if resized is not None:
            old, new = resized
            print(f"hot-spare: buddy ring re-derived after elastic "
                  f"resize {old}->{new}: {self.buddies}",
                  file=sys.stderr, flush=True)
        self._latest = None          # own newest committed record
        self._lock = threading.Lock()
        self._thread = None
        self._parked = False
        self._server = None
        if serve is None:
            serve = self.world > 1
        if serve:
            from ..distributed.rpc.rpc import RpcServer
            self._server = RpcServer(worker_name(self.job, self.rank))
            if self.store is not None:
                self.store.set(
                    _K_ENDPOINT.format(job=self.job, rank=self.rank),
                    json.dumps({"name": self._server.info.name,
                                "ip": self._server.info.ip,
                                "port": self._server.info.port,
                                "pid": os.getpid()}).encode())

    # -- snapshot side -------------------------------------------------
    def maybe_snapshot(self, it, state_fn, book):
        """Every ``every``-th update step, capture ``state_fn()`` and
        stream it to the buddy on a background thread.  One transfer in
        flight at a time — a slow buddy skips cadences instead of
        stacking threads behind the step loop."""
        if int(it) % self.every != 0:
            return False
        if self._thread is not None and self._thread.is_alive():
            return False
        state = state_fn()
        self._thread = threading.Thread(
            target=self._snapshot, args=(int(it), state, dict(book)),
            daemon=True, name=f"hot-spare-snap-{it}")
        self._thread.start()
        return True

    def snapshot_now(self, it, state, book):
        """Synchronous snapshot + stream (tests, benchmarks)."""
        self.wait()
        self._snapshot(int(it), state, dict(book))

    def _snapshot(self, it, state, book):
        try:
            record = make_record(self.rank, it, book, state)
        except Exception as e:
            print(f"hot-spare: snapshot serialization failed at it "
                  f"{it}: {e}", file=sys.stderr, flush=True)
            return
        with self._lock:
            self._latest = record
        holder = self.buddies.get(self.rank)
        if holder is None or self._server is None:
            return
        try:
            self._stream(record, holder)
        except Exception as e:
            # a dead/slow buddy must never take the training loop down;
            # the local copy + the disk ladder below still stand
            print(f"hot-spare: stream to buddy rank {holder} failed: "
                  f"{e}", file=sys.stderr, flush=True)

    def _stream(self, record, holder):
        from ..distributed.rpc.rpc import Blob, rpc_sync
        from ..utils import fault_injection as _fi
        to = self._resolve(holder)
        if to is None:
            return False
        payload = record["payload"]
        chunks = [payload[i:i + self.chunk_bytes]
                  for i in range(0, len(payload), self.chunk_bytes)] \
            or [b""]
        xfer = _next_xfer_id(self.rank)
        t0 = time.perf_counter()
        rpc_sync(to, _rpc_begin,
                 (self.job, self.rank, xfer, record["step"],
                  json.dumps(record["book"]), len(chunks),
                  record["nbytes"], record["crc"]),
                 timeout=self.timeout_s)
        drop = _fi.check_peer_snap_drop(record["step"])
        stop_after = drop.get("after_chunks", 1) if drop is not None \
            else None
        for i, chunk in enumerate(chunks):
            if stop_after is not None and i >= stop_after:
                # injected sender death mid-transfer: staging is left
                # torn, no commit — the buddy's last valid copy stands
                return False
            rpc_sync(to, _rpc_chunk,
                     (self.job, self.rank, xfer, i, zlib.crc32(chunk),
                      Blob(chunk)), timeout=self.timeout_s)
        rpc_sync(to, _rpc_commit, (self.job, self.rank, xfer),
                 timeout=self.timeout_s)
        ms = (time.perf_counter() - t0) * 1e3
        _counter("ckpt.peer.snapshots").inc()
        _counter("ckpt.peer.bytes_sent").inc(record["nbytes"])
        _observe("ckpt.peer.transfer_ms", ms)
        return True

    def _resolve(self, holder):
        """Worker name for ``holder``'s hot-spare endpoint, registering
        it from the guardian store when not already known locally."""
        name = worker_name(self.job, holder)
        if self.store is not None:
            raw = self.store.get(
                _K_ENDPOINT.format(job=self.job, rank=holder))
            if raw:
                try:
                    ep = json.loads(bytes(raw).decode())
                    from ..distributed.rpc.rpc import connect_worker
                    connect_worker(ep["name"], ep["ip"], ep["port"])
                    return ep["name"]
                except (ValueError, KeyError):
                    pass
        return name

    # -- local/latest accessors ----------------------------------------
    def latest_record(self):
        with self._lock:
            return self._latest

    def wait(self, timeout=None):
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout if timeout is not None else self.timeout_s)

    # -- park-on-exit --------------------------------------------------
    def park(self):
        """Persist every RAM-resident snapshot — own latest + all held
        buddy replicas — into the guardian store, so the state survives
        the full-pod relaunch a cooperative exit precedes.  Idempotent;
        called from the preemption path and from close()."""
        if self._parked:
            return 0
        self.wait()
        if self.store is None:
            return 0
        parked = 0
        records = []
        own = self.latest_record()
        if own is not None:
            records.append(own)
        held = store_for(self.job)
        for owner in held.owners():
            rec = held.latest(owner)
            if rec is not None and rec["owner"] != self.rank:
                records.append(rec)
        for rec in records:
            rec = dict(rec)
            rec["parked_by"] = self.rank
            try:
                self.store.set(
                    _K_PARKED.format(job=self.job, rank=rec["owner"]),
                    pickle.dumps(rec,
                                 protocol=pickle.HIGHEST_PROTOCOL))
                parked += 1
            except Exception as e:
                print(f"hot-spare: parking snapshot for rank "
                      f"{rec['owner']} failed: {e}", file=sys.stderr,
                      flush=True)
        self._parked = True
        return parked

    def close(self, park=True):
        if park:
            self.park()
        else:
            self.wait()
        if self._server is not None:
            self._server.close()
            self._server = None
        global _AGENT
        if _AGENT is self:
            _AGENT = None


def worker_name(job, rank):
    return f"hotspare:{job}:r{int(rank)}"


def _resized_worlds():
    try:
        from ..distributed.fleet.elastic import resized_worlds
        return resized_worlds()
    except Exception:
        return None


# ----------------------------------------------------------------------
# module-level agent registry (one armed agent per process)
# ----------------------------------------------------------------------
_AGENT = None


def arm(rank, world, job=None, store=None, **kw):
    """Declare the telemetry family and install the process agent.
    Re-arming replaces (and closes) a previous agent."""
    global _AGENT
    declare_metrics()
    if _AGENT is not None:
        _AGENT.close(park=False)
    job = job if job is not None else os.environ.get("PADDLE_JOB_ID",
                                                     "default")
    _AGENT = HotSpareAgent(job, rank, world, store=store, **kw)
    return _AGENT


def disarm(park=False):
    global _AGENT
    if _AGENT is not None:
        _AGENT.close(park=park)
        _AGENT = None


def current_agent():
    return _AGENT


def sentinel_candidate():
    """The armed agent's newest finiteness-validated local snapshot as
    ``(state, book)``, or None.  The sentinel consults this at rollback
    escalation: a validated peer snapshot fresher than the disk anchor
    loses fewer steps (rung 2 of the ladder)."""
    agent = _AGENT
    if agent is None:
        return None
    rec = agent.latest_record()
    if rec is None:
        return None
    try:
        return validated_state(rec)
    except PeerSnapshotError as e:
        warnings.warn(f"hot-spare: local snapshot unusable for "
                      f"sentinel rollback ({e}); falling back to the "
                      "disk anchor", PeerRestoreWarning, stacklevel=2)
        return None


# ----------------------------------------------------------------------
# the recovery ladder (restore side)
# ----------------------------------------------------------------------
def peer_restore(job, rank, store=None, timeout_s=None):
    """Rung 1: pull ``rank``'s shard from its buddy's RAM.  Tries the
    holder's live rpc endpoint first, then the parked guardian-store
    copy.  Returns ``(state, book, source)`` with source ``"peer"`` (a
    buddy's replica) or ``"self"`` (this rank's own parked copy), or
    None when no snapshot exists.  Raises
    :class:`BuddyUnavailableError` when the ``buddy_crash`` injection
    is armed for this rank, and :class:`SnapshotIntegrityError` when
    the only available snapshot fails validation."""
    if store is None:
        from ..distributed.host_collectives import guardian_store
        store = guardian_store()
    if store is None:
        return None
    rank = int(rank)
    timeout_s = float(timeout_s if timeout_s is not None
                      else _flag("FLAGS_hot_spare_timeout_s", 10.0))
    buddies = read_buddy_map(store, job) or {}
    holder = buddies.get(rank)
    from ..utils import fault_injection as _fi
    t0 = time.perf_counter()
    raw = None
    # 1a: the holder may still be alive and serving
    if holder is not None:
        if _fi.check_buddy_crash() is not None:
            raise BuddyUnavailableError(
                f"buddy rank {holder} holding rank {rank}'s replica is "
                "down (injected buddy_crash)")
        ep_raw = store.get(_K_ENDPOINT.format(job=job, rank=holder))
        if ep_raw:
            try:
                ep = json.loads(bytes(ep_raw).decode())
                from ..distributed.rpc.rpc import (connect_worker,
                                                   rpc_sync)
                connect_worker(ep["name"], ep["ip"], ep["port"])
                raw = rpc_sync(ep["name"], _rpc_fetch, (job, rank),
                               timeout=timeout_s)
            except (ConnectionError, TimeoutError, OSError, ValueError,
                    KeyError):
                raw = None
    # 1b: the holder parked its replicas before exiting
    if raw is None:
        raw = store.get(_K_PARKED.format(job=job, rank=rank))
    if raw is None:
        if holder is not None and _fi.active("buddy_crash") is not None:
            raise BuddyUnavailableError(
                f"no live endpoint and no parked snapshot for rank "
                f"{rank} (holder rank {holder})")
        return None
    record = pickle.loads(bytes(raw))
    state, book = validated_state(record)
    parked_by = record.get("parked_by")
    source = "self" if parked_by == rank else "peer"
    ms = (time.perf_counter() - t0) * 1e3
    _counter("ckpt.peer.restores").inc()
    _observe("ckpt.peer.restore_ms", ms)
    print(f"hot-spare: rank {rank} restored from {source} snapshot "
          f"(step {record['step']}, {record['nbytes']} bytes, "
          f"{ms:.0f}ms)", file=sys.stderr, flush=True)
    return state, book, source


def restore_with_ladder(job, rank, disk_fn, store=None, timeout_s=None):
    """Run the recovery ladder loudest-first.  Rung 1 is
    :func:`peer_restore`; every failure there emits a typed
    :class:`PeerRestoreWarning` and falls through to ``disk_fn`` (rung
    3 — the caller's existing ``restore_latest`` path, which must
    return ``(state, book, "disk")`` or None)."""
    declare_metrics()
    got = None
    try:
        got = peer_restore(job, rank, store=store, timeout_s=timeout_s)
    except PeerSnapshotError as e:
        msg = (f"hot-spare: peer restore failed for rank {rank} "
               f"({type(e).__name__}: {e}); falling back to disk")
        warnings.warn(msg, PeerRestoreWarning, stacklevel=2)
        print(f"PeerRestoreWarning: {msg}", file=sys.stderr, flush=True)
    if got is not None:
        return got
    if disk_fn is None:
        return None
    return disk_fn()
