"""paddle.distributed.spawn analog (reference:
python/paddle/distributed/spawn.py — fork N workers running `func(rank)`
with the parallel-env contract set up)."""
from __future__ import annotations

import multiprocessing as mp
import os

from .launch.context import free_port


def _worker(func, rank, nprocs, master, args, backend):
    os.environ.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nprocs),
        "PADDLE_MASTER": master,
        "RANK": str(rank),
        "WORLD_SIZE": str(nprocs),
        "COORDINATOR_ADDRESS": master,
    })
    func(*args)


def spawn(func, args=(), nprocs=1, join=True, daemon=False, backend=None,
          **options):
    """Spawn `nprocs` processes running func; returns the context
    (reference parity: paddle.distributed.spawn)."""
    if nprocs == 1:
        _worker(func, 0, 1, "", args, backend)
        return None
    ctx = mp.get_context("spawn")
    master = f"127.0.0.1:{free_port()}"
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(func, rank, nprocs, master, args, backend),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        bad = [p.exitcode for p in procs if p.exitcode != 0]
        if bad:
            raise RuntimeError(f"spawned workers failed: exit codes {bad}")
    return procs
