from .io import save, load  # noqa: F401
from .checkpoint_manager import (  # noqa: F401
    CheckpointManager, CheckpointError, verify_checkpoint,
)
from ..core.state import seed, get_default_dtype, set_default_dtype  # noqa: F401
