"""Device prefetch: double-buffered host->device transfer.

A producer thread pulls host batches from the pipeline, issues
``jax.device_put`` (asynchronous: the transfer engine runs it while the
current step computes on donated buffers) and parks up to ``depth``
device-resident batches in a bounded queue.  The consumer — the fit
loop — pops ready batches; every pop records wait time and buffer
occupancy into the goodput meter, which is where the
input-bound-vs-compute-bound gauge comes from.

Sharded placement: when a mesh with a ``dp`` axis of size > 1 is
active, batches are placed with ``NamedSharding(mesh, P('dp'))`` over
the leading axis — each device receives exactly its slice, rather than
the replicate-then-slice pattern that doubles transfer volume on
hybrid dp×mp meshes.

Checkpoint consistency: each queued batch travels with the pipeline
state snapshot taken right after it was produced; the pipeline commits
a snapshot only when its batch is *yielded to the caller*, so
prefetched-but-unconsumed batches are replayed on resume instead of
being lost.
"""
from __future__ import annotations

import queue
import threading
import time

import numpy as np

from ..core.tensor import Tensor
from .goodput import GoodputMeter  # noqa: F401  (re-export convenience)


def _dp_batch_sharding():
    """NamedSharding placing the batch axis over the active mesh's dp
    axis (other axes replicated), or None when no dp>1 mesh is live."""
    try:
        from ..distributed import mesh as _mesh
        m = _mesh.get_mesh()
    except Exception:
        return None
    jm = getattr(m, "_jax_mesh", None)
    if jm is None or "dp" not in jm.axis_names:
        return None
    if int(jm.shape.get("dp", 1)) <= 1:
        return None
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(jm, PartitionSpec("dp"))


def _put_leaf(arr, sharding):
    import jax
    if sharding is not None and getattr(arr, "ndim", 0) >= 1:
        dp = int(sharding.mesh.shape["dp"])
        if arr.shape[0] % dp == 0:
            return Tensor(jax.device_put(arr, sharding))
    return Tensor(jax.device_put(arr))


def to_device_batch(batch, sharding=None):
    """Map a host batch (nested tuple/list/dict of numpy arrays) to
    device-resident Tensors, preserving structure."""
    if isinstance(batch, Tensor):
        return batch
    if isinstance(batch, np.ndarray):
        return _put_leaf(batch, sharding)
    if isinstance(batch, (list, tuple)):
        return type(batch)(to_device_batch(b, sharding) for b in batch)
    if isinstance(batch, dict):
        return {k: to_device_batch(v, sharding) for k, v in batch.items()}
    return batch


class DevicePrefetch:
    name = "device_prefetch"

    def __init__(self, depth=2):
        if int(depth) < 1:
            raise ValueError(f"device_prefetch(depth={depth}): need >= 1")
        self.depth = int(depth)

    def state_dict(self):
        return {}

    def load_state_dict(self, sd):
        pass

    def iterate(self, pipe):
        """Yield ``(device_batch, state_after)`` for the remainder of
        the pipeline's current epoch, transfers overlapped."""
        q = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        sharding = _dp_batch_sharding()

        def _put(item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for host_batch, state in pipe._host_batches():
                    if not _put(("batch",
                                 to_device_batch(host_batch, sharding),
                                 state)):
                        return
                _put(("end", None, None))
            except BaseException as e:  # noqa: BLE001 — relayed to consumer
                _put(("error", e, None))

        t = threading.Thread(target=producer, daemon=True,
                             name="paddle-data-prefetch")
        t.start()
        try:
            while True:
                occupancy = q.qsize() / self.depth
                t0 = time.perf_counter()
                kind, payload, state = q.get()
                wait_ms = (time.perf_counter() - t0) * 1e3
                if kind == "end":
                    return
                if kind == "error":
                    raise payload
                pipe.goodput.record_consume(wait_ms, occupancy)
                yield payload, state
        finally:
            stop.set()
            while True:  # unblock a producer parked on a full queue
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5)
