"""Trace-to-XLA compiler for dygraph code (`to_static` analogue).

Reference capability: paddle.jit.to_static (reference: python/paddle/jit/api.py:234
— AST transform / SOT bytecode capture into a static program executed by
run_program + InterpreterCore).  TPU-native realization: a two-phase
lazy-tensor capture —

1. **Discovery call** (first call per input signature): the function runs
   eagerly while a tracer records (a) every pre-existing Tensor whose data is
   read (parameter/buffer capture → becomes a compiled-program input) and
   (b) host-scalar providers (learning rate, RNG key) that must be re-fed
   each step.  The caller gets real results — the first call IS a real step.

2. **Bind trace**: `jax.jit` traces a pure wrapper that installs JAX tracers
   into the captured tensors' data slots, re-runs the python function (tape
   autograd, optimizer update and all — everything composes because every op
   bottoms out in jnp), then collects returned tensors + every mutated
   tensor's final value as program outputs.  Subsequent calls execute one
   fused XLA program — the analogue of the reference's whole-program
   InterpreterCore run, but compiled.

No graph breaks: host reads of traced values raise (like JAX), which is the
portable subset the reference's SOT falls back from.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..core import state as _state
from ..core.tensor import Tensor


class _DiscoveryTracer:
    """Records captures + host providers during the eager first call."""

    def __init__(self):
        self.created = set()          # id(Tensor) made during trace
        self.captured = {}            # id(Tensor) -> Tensor (ordered via list)
        self.capture_list = []
        self.providers = []           # host-value providers, call order
        self.rng_counter = 0
        self._rng_provider_registered = False
        self._rng_base_val = None

    def on_create(self, t):
        self.created.add(id(t))

    def on_read(self, t):
        i = id(t)
        if i not in self.created and i not in self.captured:
            self.captured[i] = t
            self.capture_list.append(t)

    def on_write(self, t):
        # writes don't need recording at discovery; mutation targets are
        # collected during the bind trace
        pass

    def host_input(self, provider):
        self.providers.append(provider)
        return provider()

    def rng_base(self):
        if not self._rng_provider_registered:
            self._rng_provider_registered = True

            def provider():
                k = jax.random.fold_in(_state.STATE.rng_key,
                                       _state.STATE.rng_counter)
                _state.STATE.rng_counter += 1
                return k
            self._rng_base_val = self.host_input(provider)
        return self._rng_base_val


class _BindTracer:
    """Active while jax.jit traces the pure wrapper."""

    def __init__(self, host_tracers, capture_ids=frozenset()):
        self.created = set()
        self.mutated = {}             # id(Tensor) -> pre-write concrete data
        self.mutated_list = []
        self.host_tracers = host_tracers
        self.host_idx = 0
        self.rng_counter = 0
        self._rng_base_val = None
        self.capture_ids = capture_ids

    def on_create(self, t):
        self.created.add(id(t))

    def on_read(self, t):
        # a concrete (non-tracer) read of a tensor that is neither a declared
        # capture nor created inside this trace would be silently baked into
        # the program as a constant — a stale-state bug.  Discovery should
        # have captured it; fail loudly instead.
        if (id(t) not in self.capture_ids and id(t) not in self.created
                and id(t) not in self.mutated
                and not isinstance(t._data_, jax.core.Tracer)):
            raise RuntimeError(
                "to_static bind trace read a concrete tensor that was not "
                "captured at discovery (shape "
                f"{tuple(t._data_.shape)}, name={t.name!r}). This usually "
                "means the traced function's control flow diverged between "
                "calls; its value would be frozen into the compiled program.")

    def on_write(self, t):
        i = id(t)
        if i not in self.created and i not in self.mutated:
            self.mutated[i] = t._data_  # original value, pre-write
            self.mutated_list.append(t)

    def host_input(self, provider):
        v = self.host_tracers[self.host_idx]
        self.host_idx += 1
        return v

    def rng_base(self):
        if self._rng_base_val is None:
            self._rng_base_val = self.host_input(None)
        return self._rng_base_val


def host_scalar(provider):
    """Fetch a host-computed value as a traced input under tracing, or the
    plain value eagerly.  Used for learning rates / step counters that change
    between compiled calls."""
    tr = _state.STATE.tracer
    if tr is not None:
        return tr.host_input(provider)
    return provider()


def _flatten_args(args, kwargs):
    leaves, treedef = jax.tree.flatten((args, kwargs),
                                       is_leaf=lambda x: isinstance(x, Tensor))
    arrays, spec = [], []
    for leaf in leaves:
        if isinstance(leaf, Tensor):
            arrays.append(leaf._data_)
            spec.append(None)
        else:
            spec.append(leaf)
    return arrays, (treedef, tuple(spec))


def _unflatten_args(arrays, struct):
    treedef, spec = struct
    arrays = iter(arrays)
    leaves = [Tensor(next(arrays)) if s is None else s for s in spec]
    return jax.tree.unflatten(treedef, leaves)


def _signature(args, kwargs):
    leaves, treedef = jax.tree.flatten((args, kwargs),
                                       is_leaf=lambda x: isinstance(x, Tensor))
    sig = []
    for leaf in leaves:
        if isinstance(leaf, Tensor):
            sig.append(("T", tuple(leaf._data_.shape), str(leaf._data_.dtype)))
        else:
            try:
                hash(leaf)
                sig.append(leaf)
            except TypeError:
                sig.append(repr(leaf))
    return treedef, tuple(sig)


_WARMUP = object()


class _CompiledEntry:
    __slots__ = ("captures", "providers", "jitted", "mut_targets",
                 "grad_targets", "out_struct")

    def __init__(self):
        self.captures = []
        self.providers = []
        self.jitted = None
        self.mut_targets = []     # Tensors whose data is replaced after call
        self.grad_targets = []    # Tensors whose .grad is materialized
        self.out_struct = None


class StaticFunction:
    """Callable wrapper produced by @to_static."""

    def __init__(self, fn, input_spec=None, build_strategy=None,
                 full_graph=True):
        self._fn = fn
        self._cache = {}
        for attr in ("__name__", "__qualname__", "__doc__"):
            try:
                setattr(self, attr, getattr(fn, attr))
            except AttributeError:
                pass

    @property
    def __wrapped__(self):
        return self._fn

    def __get__(self, instance, owner):
        if instance is None:
            return self
        return functools.partial(self.__call__, instance)

    def concrete_cache_size(self):
        return len(self._cache)

    def __call__(self, *args, **kwargs):
        if _state.STATE.tracer is not None:
            # nested to_static: inline into the enclosing trace
            return self._fn(*args, **kwargs)
        key = _signature(args, kwargs)
        entry = self._cache.get(key)
        if entry is None:
            # warm-up: run once fully eager so lazily-initialized persistent
            # state (optimizer moments, step counters, buffers) exists BEFORE
            # discovery — otherwise discovery marks it "created" and the bind
            # trace would bake its current value in as a constant.  The
            # sentinel is recorded only after a successful eager run: if the
            # warm-up raises, the next call with this signature warms up
            # again instead of discovering against half-initialized state.
            result = self._fn(*args, **kwargs)
            self._cache[key] = _WARMUP
            return result
        if entry is _WARMUP:
            return self._discover(key, args, kwargs)
        return self._run_compiled(entry, args, kwargs)

    # ---------------- phase 1: discovery (eager) ----------------
    def _discover(self, key, args, kwargs):
        entry = _CompiledEntry()
        tracer = _DiscoveryTracer()
        _state.STATE.tracer = tracer
        try:
            out = self._fn(*args, **kwargs)
        finally:
            _state.STATE.tracer = None
        entry.captures = tracer.capture_list
        entry.providers = tracer.providers
        self._build(entry, args, kwargs)
        self._cache[key] = entry
        return out

    # ---------------- phase 2: bind + compile ----------------
    def _build(self, entry, args, kwargs):
        fn = self._fn

        def pure(arg_arrays, cap_arrays, host_vals, arg_struct):
            tracer = _BindTracer(host_vals,
                                 frozenset(id(t) for t in entry.captures))
            saved = [(t, t._data_) for t in entry.captures]
            bound_args, bound_kwargs = _unflatten_args(arg_arrays, arg_struct)
            for t, arr in zip(entry.captures, cap_arrays):
                t._data_ = arr
            _state.STATE.tracer = tracer
            try:
                out = fn(*bound_args, **bound_kwargs)
            finally:
                _state.STATE.tracer = None
            # collect outputs
            out_leaves, out_tree = jax.tree.flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            out_arrays, out_spec = [], []
            for leaf in out_leaves:
                if isinstance(leaf, Tensor):
                    out_arrays.append(leaf._data_)
                    out_spec.append(None)
                else:
                    out_spec.append(leaf)
            entry.out_struct = (out_tree, tuple(out_spec))
            # mutated tensors -> outputs
            entry.mut_targets = list(tracer.mutated_list)
            mut_arrays = [t._data_ for t in entry.mut_targets]
            # escaped gradients on captured tensors -> outputs
            entry.grad_targets = []
            grad_arrays = []
            for t in entry.captures:
                g = t.grad
                if g is not None and isinstance(g._data_, jax.core.Tracer):
                    entry.grad_targets.append(t)
                    grad_arrays.append(g._data_)
            # restore original concrete data (mutations are applied by the
            # caller from the returned arrays)
            captured_ids = {id(t) for t in entry.captures}
            for t, orig in saved:
                t._data_ = orig
            for t in entry.mut_targets:
                if id(t) not in captured_ids:
                    # mutated without prior read: restore the pre-write value
                    # recorded by the tracer so no JAX tracer leaks out
                    t._data_ = tracer.mutated[id(t)]
            for t in entry.grad_targets:
                t.grad = None
            return tuple(out_arrays), tuple(mut_arrays), tuple(grad_arrays)

        entry.jitted = jax.jit(pure, static_argnums=(3,))

    def _run_compiled(self, entry, args, kwargs):
        arg_arrays, arg_struct = _flatten_args(args, kwargs)
        cap_arrays = [t._data_ for t in entry.captures]
        host_vals = [p() for p in entry.providers]
        out_arrays, mut_arrays, grad_arrays = entry.jitted(
            arg_arrays, cap_arrays, host_vals, arg_struct)
        # apply mutations
        for t, arr in zip(entry.mut_targets, mut_arrays):
            t._data_ = arr
        for t, arr in zip(entry.grad_targets, grad_arrays):
            if t.grad is None:
                t.grad = Tensor(arr)
            else:
                t.grad._data_ = arr
        # rebuild outputs
        out_tree, out_spec = entry.out_struct
        arrays = iter(out_arrays)
        leaves = [Tensor(next(arrays)) if s is None else s for s in out_spec]
        return jax.tree.unflatten(out_tree, leaves)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True, **kwargs):
    """Compile a dygraph function/Layer into one XLA program per input
    signature (reference API: python/paddle/jit/api.py:234)."""
    from ..nn.layer import Layer

    def decorate(fn):
        if isinstance(fn, Layer):
            layer = fn
            static_fwd = StaticFunction(layer.forward.__func__
                                        if hasattr(layer.forward, "__func__")
                                        else layer.forward)
            bound = functools.partial(static_fwd, layer) \
                if hasattr(layer.forward, "__func__") else static_fwd
            layer.forward = bound
            return layer
        return StaticFunction(fn, input_spec=input_spec)

    if function is not None:
        return decorate(function)
    return decorate
