"""Compiled serving tick (ISSUE 13): one donated-buffer jit program per
scheduler iteration over device-resident state — bit-equality vs the
uncompiled scheduler across mixed workloads, flag-off byte-identity,
typed warn-once fallbacks, watchdog/drain semantics, and the shared
capture core factored out of framework/train_step.py."""
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTForCausalLM, gpt_config
from paddle_tpu.serving import (
    DeadlineExceededError, Engine, SamplingParams, SchedulerStallError,
    ServingConfig, serving_stats,
)
from paddle_tpu.serving.compiled_tick import (
    CompiledServingTick, TickFallbackWarning,
)
from paddle_tpu.utils import flags as _flags


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_config(
        "gpt2-124m", num_layers=2, hidden_size=64, num_heads=2,
        vocab_size=256, max_seq_len=64))
    m.eval()
    return m


@pytest.fixture
def tick_flag():
    """Restore the tick/fused-sampling flags after each test."""
    saved = {k: _flags._FLAGS[k] for k in
             ("FLAGS_compiled_tick", "FLAGS_serving_fused_sampling")}
    yield _flags._FLAGS
    _flags._FLAGS.update(saved)


def _prompts(lens, seed=0, vocab=256):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (n,)).astype("int32") for n in lens]


def _ref_greedy(model, prompt, max_new, eos_token_id=None):
    ids = model.generate(paddle.to_tensor(prompt[None, :]),
                         max_new_tokens=max_new, temperature=0.0,
                         eos_token_id=eos_token_id)
    return np.asarray(ids._data_)[0, prompt.size:]


def _serve(model, subs, cfg=None, compiled=True, flags=None):
    """Run the engine with FLAGS_compiled_tick set to `compiled`;
    returns ([RequestOutput], stats snapshot, engine tick object)."""
    fl = flags if flags is not None else _flags._FLAGS
    saved = fl["FLAGS_compiled_tick"]
    fl["FLAGS_compiled_tick"] = compiled
    try:
        eng = Engine(model, cfg or ServingConfig(
            num_slots=2, max_queue=len(subs) + 1)).start()
        try:
            futs = [eng.submit(p, max_new_tokens=mn, sampling=sp,
                               eos_token_id=eos)
                    for p, mn, sp, eos in subs]
            outs = [f.result(timeout=300) for f in futs]
            snap = eng.stats()
            tick = eng._tick
        finally:
            eng.shutdown()
        return outs, snap, tick
    finally:
        fl["FLAGS_compiled_tick"] = saved


def test_mixed_workload_bit_equality(model, tick_flag):
    """Greedy, greedy+eos (slot refilled mid-flight), seeded-sampled,
    and seeded+penalty/top-k/top-p requests through 2 slots: the
    compiled tick's outputs are bit-identical to the uncompiled
    scheduler's, completion reasons included."""
    pa, pb, pc, pd, pe = _prompts([5, 9, 3, 7, 6], seed=7)
    eos = int(_ref_greedy(model, pb, 8)[1])   # pb finishes on eos @2
    subs = [
        (pa, 8, None, None),
        (pb, 8, None, eos),
        (pc, 8, SamplingParams(temperature=0.8, top_k=20, seed=3), None),
        (pd, 8, SamplingParams(temperature=1.0, top_p=0.9,
                               repetition_penalty=1.3, seed=5), None),
        (pe, 8, None, None),
    ]
    ref, snap_u, _ = _serve(model, subs, compiled=False)
    got, snap_c, _ = _serve(model, subs, compiled=True)
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(r.output_ids, g.output_ids)
        assert r.finish_reason == g.finish_reason
    assert got[1].finish_reason == "eos"
    assert got[1].output_ids.size < 8         # refilled mid-flight
    assert snap_c["tick_compiled_hits"] > 0
    assert snap_u["tick_compiled_hits"] == 0
    np.testing.assert_array_equal(got[0].output_ids,
                                  _ref_greedy(model, pa, 8))


def test_flag_off_is_tickless(model, tick_flag):
    """FLAGS_compiled_tick off: no tick object is built at all — the
    scheduler runs the historical per-call path (and with fused
    sampling off too, unseeded draws consume the global RNG exactly as
    before: same paddle.seed, same stream)."""
    (p,) = _prompts([5])
    tick_flag["FLAGS_serving_fused_sampling"] = False
    subs = [(p, 5, SamplingParams(temperature=0.9), None)]

    def run():
        paddle.seed(123)
        outs, snap, tick = _serve(model, subs, compiled=False)
        return outs[0].output_ids, snap, tick

    toks1, snap, tick = run()
    toks2, _, _ = run()
    assert tick is None
    np.testing.assert_array_equal(toks1, toks2)   # global-RNG stream
    assert snap["tick_compiled_hits"] == 0 and snap["tick_fallbacks"] == 0


def test_unseeded_sampling_typed_warn_once_fallback(model, tick_flag):
    """Non-greedy sampling without a seed cannot ride the vectorized
    in-program chain: the engine warns ONCE with the typed
    TickFallbackWarning and latches the uncompiled iteration."""
    pa, pb = _prompts([4, 6], seed=1)
    sp = SamplingParams(temperature=1.0)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        outs, snap, _ = _serve(
            model, [(pa, 6, sp, None), (pb, 6, sp, None)],
            compiled=True)
    tw = [x for x in w if issubclass(x.category, TickFallbackWarning)]
    assert len(tw) == 1, [str(x.message) for x in tw]
    assert "seed" in str(tw[0].message)
    assert snap["tick_compiled_hits"] == 0
    assert snap["tick_fallbacks"] > 0
    assert all(o.output_ids.size == 6 for o in outs)


def test_slots_layout_and_speculation_fall_back_typed(model, tick_flag):
    """kv_layout='slots' and speculation-on both latch the uncompiled
    scheduler with the typed warning; speculation_k=0 with a draft
    model configured does NOT (the tick runs)."""
    (p,) = _prompts([5])
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        outs, snap, _ = _serve(
            model, [(p, 4, None, None)],
            cfg=ServingConfig(num_slots=1, kv_layout="slots"),
            compiled=True)
    assert any(issubclass(x.category, TickFallbackWarning) and
               "slots" in str(x.message) for x in w)
    assert snap["tick_compiled_hits"] == 0
    np.testing.assert_array_equal(outs[0].output_ids,
                                  _ref_greedy(model, p, 4))

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        outs, snap, _ = _serve(
            model, [(p, 4, None, None)],
            cfg=ServingConfig(num_slots=1, draft_model=model,
                              speculation_k=2),
            compiled=True)
    assert any(issubclass(x.category, TickFallbackWarning) and
               "speculative" in str(x.message) for x in w)
    np.testing.assert_array_equal(outs[0].output_ids,
                                  _ref_greedy(model, p, 4))

    # K=0: bitwise the plain loop — and the tick hosts it
    outs, snap, _ = _serve(
        model, [(p, 4, None, None)],
        cfg=ServingConfig(num_slots=1, draft_model=model,
                          speculation_k=0),
        compiled=True)
    assert snap["tick_compiled_hits"] > 0
    np.testing.assert_array_equal(outs[0].output_ids,
                                  _ref_greedy(model, p, 4))


def test_seeded_stream_reproducible_and_lane_independent(model,
                                                         tick_flag):
    """A seeded request's sampled stream is identical across engine
    runs AND across lanes (per-row host path, fused call, compiled
    tick); different seeds give different streams."""
    (p,) = _prompts([6], seed=9)
    sp7 = SamplingParams(temperature=0.9, top_k=50, seed=7)
    subs = [(p, 8, sp7, None)]
    a, _, _ = _serve(model, subs, compiled=True)
    b, _, _ = _serve(model, subs, compiled=True)
    c, _, _ = _serve(model, subs, compiled=False)
    np.testing.assert_array_equal(a[0].output_ids, b[0].output_ids)
    np.testing.assert_array_equal(a[0].output_ids, c[0].output_ids)
    d, _, _ = _serve(model, [(p, 8, SamplingParams(
        temperature=0.9, top_k=50, seed=8), None)], compiled=True)
    assert not np.array_equal(a[0].output_ids, d[0].output_ids)


def test_deadline_evict_under_compiled_tick(model, tick_flag):
    """Deadline enforcement keeps its per-token granularity on the
    compiled lane: an expired in-flight request is evicted (typed
    error, slot freed) and the survivor completes bit-equal."""
    pa, pb = _prompts([5, 4], seed=2)
    tick_flag["FLAGS_compiled_tick"] = True
    eng = Engine(model, ServingConfig(num_slots=2, max_queue=4)).start()
    try:
        f_slow = eng.submit(pa, max_new_tokens=50, deadline_s=0.12)
        f_ok = eng.submit(pb, max_new_tokens=5)
        with pytest.raises(DeadlineExceededError):
            f_slow.result(timeout=60)
        out = f_ok.result(timeout=60)
        snap = eng.stats()
    finally:
        eng.shutdown()
    np.testing.assert_array_equal(out.output_ids,
                                  _ref_greedy(model, pb, 5))
    assert snap["requests_evicted_deadline"] >= 1


def test_drain_completes_inflight_under_compiled_tick(model, tick_flag):
    """drain() semantics survive the compiled tick: in-flight slots run
    to completion, queued requests fail, admissions stop."""
    from paddle_tpu.serving import EngineShutdownError
    pa, pb, pc = _prompts([5, 6, 4], seed=4)
    tick_flag["FLAGS_compiled_tick"] = True
    eng = Engine(model, ServingConfig(num_slots=1, max_queue=8)).start()
    inflight = eng.submit(pa, max_new_tokens=30)
    t0 = time.monotonic()
    while serving_stats()["active_slots"] < 1 and \
            time.monotonic() - t0 < 30:
        time.sleep(0.005)
    queued = eng.submit(pb, max_new_tokens=5)
    eng.drain(deadline_s=60)
    out = inflight.result(timeout=5)
    assert out.output_ids.size == 30
    with pytest.raises(EngineShutdownError):
        queued.result(timeout=5)
    with pytest.raises(EngineShutdownError):
        eng.submit(pc)


def test_stall_watchdog_restarts_compiled_tick(model, tick_flag,
                                               monkeypatch):
    """A stalled compiled tick trips the PR 5 scheduler watchdog: the
    outstanding futures fail with SchedulerStallError, the loop
    restarts with a FRESH tick (the donated pools may be torn), and the
    engine serves again — scheduler_restarts/stalls counted."""
    (p,) = _prompts([5], seed=6)
    # warm the persistent compile cache for this tick program first: a
    # cold first compile inside the watchdog's budget would read as a
    # stall of its own and churn the restart budget
    _serve(model, [(p, 2, None, None)],
           cfg=ServingConfig(num_slots=1), compiled=True)
    orig = CompiledServingTick._run
    state = {"calls": 0}

    def stalling_run(self):
        state["calls"] += 1
        if state["calls"] == 2:
            t0 = time.monotonic()
            while time.monotonic() - t0 < 60.0:
                time.sleep(0.01)     # interruptible by async-raise
        return orig(self)

    monkeypatch.setattr(CompiledServingTick, "_run", stalling_run)
    tick_flag["FLAGS_compiled_tick"] = True
    # budget must sit between the rebuilt tick's (cache-served)
    # recompile time and the injected stall
    eng = Engine(model, ServingConfig(
        num_slots=1, step_timeout_s=6.0,
        max_scheduler_restarts=2)).start()
    try:
        tick0 = eng._tick
        f = eng.submit(p, max_new_tokens=4)
        exc = f.exception(timeout=30)
        assert isinstance(exc, SchedulerStallError), exc
        out = eng.generate(p, max_new_tokens=4, timeout=60)
        snap = eng.stats()
        assert eng._tick is not tick0        # rebuilt on restart
    finally:
        eng.shutdown()
    np.testing.assert_array_equal(out.output_ids,
                                  _ref_greedy(model, p, 4))
    assert snap["scheduler_stalls"] >= 1
    assert snap["scheduler_restarts"] >= 1


def test_pool_gauge_throttle_converges(model, tick_flag):
    """The throttled pool-gauge publisher (ISSUE 13 satellite) still
    converges: after the engine quiesces, the gauges reflect the true
    pool state (every page back, peak recorded) even though steady
    ticks skipped the registry lock."""
    prompts = _prompts([5, 7, 4, 6], seed=8)
    tick_flag["FLAGS_compiled_tick"] = True
    eng = Engine(model, ServingConfig(
        num_slots=2, max_queue=5, enable_prefix_cache=False)).start()
    futs = [eng.submit(p, max_new_tokens=6) for p in prompts]
    outs = [f.result(timeout=300) for f in futs]
    eng.shutdown()          # loop exit force-flushes the pool gauges
    snap = serving_stats()
    assert all(o.output_ids.size == 6 for o in outs)
    assert snap["kv_pages_peak"] > 0
    # quiesced engine: every page back in the pool, gauges converged
    # despite steady-state ticks skipping the registry lock
    assert snap["kv_pages_in_use"] == 0
    assert snap["kv_pages_free"] == eng.cache.usable_pages


def test_tick_metrics_in_snapshot_and_prometheus(model, tick_flag):
    """serving.tick_ms / tick.compiled_hits / tick.fallbacks land in
    serving_stats() and the Prometheus exposition (schema the
    check_telemetry --serving-tick gate enforces)."""
    import paddle_tpu.observability as obs
    (p,) = _prompts([5])
    _, snap, _ = _serve(model, [(p, 4, None, None)], compiled=True)
    assert snap["tick_ms_avg"] is not None and snap["tick_ms_avg"] > 0
    assert snap["tick_compiled_hits"] > 0
    assert snap["tick_fallbacks"] == 0
    text = obs.render_prometheus()
    assert "serving_tick_ms_bucket" in text
    assert "serving_tick_compiled_hits" in text
    assert "serving_tick_fallbacks" in text
    from tools.check_telemetry import (check_serving_tick_exposition,
                                       parse_prometheus)
    series, typed, errors = parse_prometheus(text)
    assert not errors
    assert check_serving_tick_exposition(series, typed) == []


def test_capture_core_shared_with_train_step():
    """The two-phase capture/replay machinery is ONE implementation:
    train_step's historical names alias framework/capture.py, and
    run_discovery captures reads + rolls back side effects."""
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.framework import capture, train_step
    assert train_step._StepBindTracer is capture.BindTracer
    assert train_step._Installed is capture.Installed
    assert train_step.TraceEscape is capture.TraceEscape

    pre = Tensor(np.ones(3, np.float32))
    counter = Tensor(np.zeros((), np.float32))

    def body():
        from paddle_tpu.tensor_ops import math as M
        counter._data = counter._data + 1.0      # write: rolled back
        return M.add(Tensor(np.ones(3, np.float32)), pre)  # read: captured

    disc = capture.run_discovery(body)
    assert any(t is pre for t in disc.capture_list)
    assert not disc.uses_rng
    assert float(np.asarray(counter._data_)) == 0.0   # rollback

    def hostly():
        return float(np.asarray(pre.numpy()).sum())

    with pytest.raises(capture.TraceEscape):
        capture.run_discovery(hostly)


def test_concurrent_engines_share_one_model(model, tick_flag):
    """Thread-mode fleets host several engines over ONE model object:
    while one engine's tick program traces (tracers swapped into the
    shared parameters), the other engines' eager prefills/decodes must
    not observe them — the process-wide capture TRACE_LOCK serializes
    the window.  Both engines' greedy outputs stay bit-equal to the
    sequential reference."""
    prompts = _prompts([5, 7, 4, 6], seed=21)
    refs = [_ref_greedy(model, p, 6) for p in prompts]
    tick_flag["FLAGS_compiled_tick"] = True
    engines = [Engine(model, ServingConfig(num_slots=2,
                                           max_queue=8)).start()
               for _ in range(2)]
    try:
        # submit to BOTH immediately: engine 0's first tick traces
        # while engine 1 is mid-prefill/decode on the same parameters
        futs = [(e, e.submit(p, max_new_tokens=6))
                for p in prompts for e in engines]
        outs = [(e, f.result(timeout=300)) for e, f in futs]
    finally:
        for e in engines:
            e.shutdown()
    for (e, o), ref in zip(outs, [r for r in refs for _ in engines]):
        np.testing.assert_array_equal(o.output_ids, ref)


def test_fused_sampling_flag_off_keeps_per_row_path(model, tick_flag):
    """FLAGS_serving_fused_sampling off: seeded requests go back to the
    historical per-row scheduler-thread RNG draw — the stream ignores
    the request seed (a DIFFERENT request seed gives the same tokens,
    unlike the seeded lane where streams are seed-derived)."""
    (p,) = _prompts([5], seed=12)
    tick_flag["FLAGS_serving_fused_sampling"] = False

    def run(request_seed):
        outs, _, _ = _serve(
            model, [(p, 6, SamplingParams(temperature=0.9,
                                          seed=request_seed), None)],
            compiled=False)
        return outs[0].output_ids

    a, b = run(7), run(8)
    # historical path: the scheduler thread's own RNG drives the draw,
    # so changing the request seed changes nothing...
    np.testing.assert_array_equal(a, b)
    # ...while the fused lane derives the stream from the request seed
    tick_flag["FLAGS_serving_fused_sampling"] = True
    c, d = run(7), run(8)
    assert not np.array_equal(c, d)
    assert not np.array_equal(a, c)
