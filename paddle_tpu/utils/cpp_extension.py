"""JIT C++ extension builder (ctypes-based).

Reference capability: `paddle.utils.cpp_extension` (reference:
python/paddle/utils/cpp_extension/ — setuptools + JIT `load()` builds of
`PD_BUILD_OP` custom ops).  pybind11 is not available in this image, so the
TPU build exposes a C ABI contract instead: sources export plain C
functions, `load()` compiles them with g++ into a cached .so and returns a
`ctypes.CDLL`.  This is the build path for the framework's own native
components (csrc/) and for user custom ops.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sysconfig


DEFAULT_CACHE = os.path.join(
    os.path.expanduser(os.environ.get("PADDLE_EXTENSION_DIR",
                                      "~/.cache/paddle_tpu_extensions")))


class BuildError(RuntimeError):
    pass


def _hash_key(sources, cflags, ldflags):
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join(cflags).encode())
    h.update(" ".join(ldflags).encode())
    return h.hexdigest()[:16]


def load(name, sources, extra_cflags=None, extra_ldflags=None,
         extra_include_paths=None, build_directory=None, verbose=False,
         with_python=False):
    """Compile `sources` into <cache>/<name>-<hash>.so and dlopen it.

    Returns a ctypes.CDLL.  Rebuilds only when sources/flags change
    (reference: cpp_extension.load JIT semantics)."""
    sources = [os.path.abspath(s) for s in sources]
    cflags = ["-O3", "-fPIC", "-std=c++17", "-shared", "-pthread"]
    cflags += extra_cflags or []
    inc = list(extra_include_paths or [])
    if with_python:
        inc.append(sysconfig.get_paths()["include"])
    ldflags = ["-lpthread", "-lrt"] + (extra_ldflags or [])

    cache = build_directory or DEFAULT_CACHE
    os.makedirs(cache, exist_ok=True)
    so_path = os.path.join(
        cache, f"{name}-{_hash_key(sources, cflags, ldflags)}.so")
    if not os.path.exists(so_path):
        cmd = (["g++"] + cflags + [f"-I{p}" for p in inc]
               + sources + ["-o", so_path] + ldflags)
        if verbose:
            print("[cpp_extension]", " ".join(cmd))
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=300)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise BuildError(f"g++ invocation failed: {e}") from e
        if r.returncode != 0:
            raise BuildError(
                f"build of {name} failed:\n{r.stderr[-4000:]}")
    return ctypes.CDLL(so_path)


# ---- setuptools-style parity surface (reference: cpp_extension/setup) ----
class CppExtension:
    def __init__(self, sources, *args, **kwargs):
        self.sources = sources
        self.kwargs = kwargs


CUDAExtension = CppExtension  # accepted, builds CPU-side (no CUDA on TPU)


def setup(name=None, ext_modules=None, **kwargs):
    """Build every extension eagerly into the cache (JIT-style stand-in for
    the reference's setuptools command)."""
    built = {}
    for ext in ext_modules or []:
        built[name or "ext"] = load(name or "ext", ext.sources,
                                    **ext.kwargs)
    return built


def get_include():
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "csrc")


def register_c_kernel(op_name, library, symbol, nondiff=True):
    """Kernel-registration C ABI (reference capability: the PHI C-API
    kernel registry — paddle/phi/capi/include/kernel_registry.h lets a
    shared library register kernels the dispatcher then routes to).

    `symbol` must follow the host-kernel ABI
        void symbol(const float* x, float* y, int64_t n)
    (unary elementwise over float32).  The kernel becomes a dispatchable
    framework op: it runs on the HOST via jax.pure_callback — the TPU
    analog of a reference CPU kernel — so it composes with jit and
    sharding (XLA inserts the host transfer) but is non-differentiable
    unless a VJP op is registered separately.

    `library` is a ctypes.CDLL (e.g. from load()) or a .so path.
    Returns the python op callable (also importable wherever the
    registry op is exposed)."""
    import numpy as np

    lib = library if not isinstance(library, str) else ctypes.CDLL(library)
    cfn = getattr(lib, symbol)
    cfn.restype = None
    cfn.argtypes = [ctypes.POINTER(ctypes.c_float),
                    ctypes.POINTER(ctypes.c_float), ctypes.c_longlong]

    def host_kernel(x):
        x = np.ascontiguousarray(x, np.float32)
        y = np.empty_like(x)
        cfn(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), x.size)
        return y

    from ..core.dispatch import defop

    @defop(op_name, nondiff=nondiff)
    def c_kernel_op(x):
        import jax
        import jax.numpy as jnp
        return jax.pure_callback(
            host_kernel,
            jax.ShapeDtypeStruct(x.shape, jnp.float32), x,
            vmap_method="sequential")

    return c_kernel_op
