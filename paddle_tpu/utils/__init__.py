from . import flags  # noqa: F401
from .flags import set_flags, get_flags  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError:
        return None
