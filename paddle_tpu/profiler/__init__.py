from .profiler import (  # noqa: F401
    Profiler, ProfilerTarget, ProfilerState, make_scheduler, RecordEvent,
    export_chrome_tracing, export_protobuf, load_profiler_result,
    merge_chrome_traces, write_chrome_trace,
)
from .timer import benchmark, TimerHub, mfu  # noqa: F401
from ..ops.flops import FlopsCounter, count_flops  # noqa: F401
from . import profiler_statistic  # noqa: F401
from .profiler_statistic import SortedKeys, summary  # noqa: F401


class SummaryView:
    """Profiler stats view selector (reference: profiler/profiler.py
    SummaryView enum)."""
    DeviceView = "device"
    OverView = "overview"
    ModelView = "model"
    DistributedView = "dist"
    KernelView = "kernel"
    OperatorView = "operator"
    MemoryView = "memory"
    MemoryManipulationView = "memory_manipulation"
    UDFView = "udf"
