"""Slot-based KV caches for continuous batching.

`models/generation.init_kv_caches` keys every sequence in a batch to ONE
shared scalar offset — correct for a single `generate()` call, useless
for serving where requests arrive and finish at different times.  This
module generalizes the layout to a fixed ``[num_slots, max_len, H, D]``
cache per layer with an int32 offset PER SLOT, the structure vLLM gets
from paged KV blocks and Orca from request-level batching: sequences of
different ages coexist in the same compiled decode step, and a finished
slot is refilled by a new request without draining the batch.

Static shapes throughout: whatever mix of ages occupies the slots, the
decode step is the SAME XLA program (the per-slot offsets are runtime
data, not shapes), so the executable cache from PR 1 serves every step.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor


class SlotKVCache:
    """Per-layer ``{"k", "v", "offset"}`` dicts shaped for the model's
    decode path (`IF.masked_multihead_attention` accepts the [num_slots]
    offset vector) plus host-side slot bookkeeping.

    Slot lifecycle::

        free --allocate()--> reserved --write_prefill()--> active
          ^                                                  |
          +---------------- release() <-- (eos/length/deadline/shutdown)

    A free slot still rides along in the batched decode step (static
    shape!) — it re-writes position 0 with dummy K/V each step, which
    the next `write_prefill` fully overwrites and the per-row causal
    mask never exposes to live rows.
    """

    def __init__(self, num_layers, num_slots, max_len, num_kv_heads,
                 head_dim, dtype="float32"):
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.offsets = np.zeros(self.num_slots, np.int32)
        self._free = list(range(self.num_slots - 1, -1, -1))
        self._dirty = False
        shape = [self.num_slots, self.max_len, num_kv_heads, head_dim]
        off = Tensor(jnp.asarray(self.offsets))
        self.layers = [
            {"k": Tensor(jnp.zeros(shape, dtype=dtype)),
             "v": Tensor(jnp.zeros(shape, dtype=dtype)),
             "offset": off}
            for _ in range(num_layers)]

    # ---------------- slot bookkeeping ----------------
    @property
    def free_slots(self):
        return len(self._free)

    def allocate(self):
        """Reserve a free slot index, or None when fully occupied."""
        return self._free.pop() if self._free else None

    def release(self, slot):
        """Return a slot to the free pool (offset pinned back to 0; the
        stale K/V rows stay until the next prefill overwrites them)."""
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free")
        self.offsets[slot] = 0
        self._free.append(slot)
        self._dirty = True

    # ---------------- cache data ----------------
    def write_prefill(self, slot, prefill_caches, prompt_len):
        """Copy a batch-1 prefill's per-layer caches (the dicts
        `init_kv_caches(..., batch=1, max_len=self.max_len)` produced
        and the model filled) into `slot`'s rows, and start the slot's
        clock at `prompt_len`."""
        if prompt_len > self.max_len:
            raise ValueError(
                f"prompt of {prompt_len} tokens exceeds slot capacity "
                f"{self.max_len}")
        for lay, src in zip(self.layers, prefill_caches):
            lay["k"] = Tensor(lay["k"]._data_.at[slot].set(
                src["k"]._data_[0]))
            lay["v"] = Tensor(lay["v"]._data_.at[slot].set(
                src["v"]._data_[0]))
        self.offsets[slot] = prompt_len
        self._dirty = True

    def advance(self, slots):
        """Bump the offsets of `slots` by one decoded token."""
        idx = list(slots)
        if idx:
            self.offsets[idx] += 1
        self._dirty = True

    def layer_caches(self):
        """The per-layer cache dicts, ready to pass as
        ``model(tokens, caches=...)`` for the batched decode step.
        Host-side offset mutations (advance/release/write_prefill) only
        mark the cache dirty; the ONE shared device offsets array is
        re-uploaded here, once per scheduler iteration — not once per
        bookkeeping call per layer as the original `_sync_offsets` did."""
        self._flush()
        return self.layers

    def _flush(self):
        if not self._dirty:
            return
        off = Tensor(jnp.asarray(self.offsets))
        for lay in self.layers:
            lay["offset"] = off
        self._dirty = False
