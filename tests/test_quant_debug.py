"""Quantization + nan/inf debug tests (reference: test/quantization/,
FLAGS_check_nan_inf tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.quantization import (
    QAT, PTQ, QuantConfig, QuantedLayer, FakeQuanterWithAbsMaxObserver,
    AbsmaxObserver,
)


def test_qat_quantize_and_train():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    qat = QAT(QuantConfig())
    model = qat.quantize(model)
    assert isinstance(model[0], QuantedLayer)
    x = paddle.randn([4, 8])
    out = model(x)
    loss = (out ** 2).mean()
    loss.backward()
    # STE: gradient flows through fake-quant to the weight
    assert model[0].inner.weight.grad is not None
    assert np.isfinite(model[0].inner.weight.grad.numpy()).all()

    converted = qat.convert(model)
    assert isinstance(converted[0], nn.Linear)
    assert converted[0].weight_scale is not None


def test_fake_quant_close_to_identity():
    q = FakeQuanterWithAbsMaxObserver(quant_bits=8)
    x = paddle.to_tensor(np.linspace(-1, 1, 11).astype(np.float32))
    out = q(x)
    np.testing.assert_allclose(out.numpy(), x.numpy(), atol=1.0 / 127 + 1e-6)


def test_ptq_observe_convert():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 8))
    ptq = PTQ(QuantConfig())
    model = ptq.quantize(model)
    for _ in range(3):
        model(paddle.randn([4, 8]))
    model = ptq.convert(model)
    lin = model[0]
    assert lin.activation_scale is not None and lin.activation_scale > 0
    # weights are now on the int8 grid
    w = lin.weight.numpy()
    grid = np.round(w / lin.weight_scale * 127)
    np.testing.assert_allclose(w, grid * lin.weight_scale / 127, atol=1e-6)


def test_check_nan_inf_flag():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError, match="NaN|Inf"):
            _ = x / paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        # healthy ops pass
        _ = x + x
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_check_nan_inf_warn_level():
    paddle.set_flags({"FLAGS_check_nan_inf": True,
                      "FLAGS_check_nan_inf_level": 3})
    try:
        x = paddle.to_tensor(np.array([1.0], np.float32))
        zero = paddle.to_tensor(np.array([0.0], np.float32))
        out = x / zero  # warns, does not raise
        assert np.isinf(out.numpy()).any()
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False,
                          "FLAGS_check_nan_inf_level": 0})


def test_asp_nm_sparsity_workflow():
    """2:4 pruning + mask-preserving training (reference: incubate/asp)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.incubate import asp

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 4))
    masks = asp.prune_model(model, n=2, m=4)
    assert masks, "no weights pruned"
    for name, mask in masks.items():
        blocks = mask.reshape(-1, 4)
        np.testing.assert_array_equal(blocks.sum(-1),
                                      2 * np.ones(len(blocks)))
    w0 = [p for n_, p in model.named_parameters()
          if n_.endswith("weight")][0]
    assert abs(asp.calculate_density(w0) - 0.5) < 0.05

    opt = asp.decorate(paddle.optimizer.AdamW(
        1e-2, parameters=model.parameters()))
    x = paddle.to_tensor(np.random.default_rng(0)
                         .standard_normal((8, 16)).astype("float32"))
    y = paddle.to_tensor(np.random.default_rng(1).integers(0, 4, (8,))
                         .astype("int64"))
    for _ in range(3):
        loss = paddle.nn.functional.cross_entropy(model(x), y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    # sparsity survives optimizer updates
    assert abs(asp.calculate_density(w0) - 0.5) < 0.05
    asp.reset_excluded_layers(model)


def test_amp_operator_stats_and_compare(tmp_path):
    """reference: amp/debugging.py collect_operator_stats +
    accuracy_compare.py."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.amp import debugging as dbg

    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    with dbg.collect_operator_stats() as ca:
        paddle.nn.functional.gelu(x)
    with dbg.collect_operator_stats() as cb:
        y = paddle.nn.functional.gelu(x)
        y / paddle.to_tensor(np.zeros(4, np.float32))   # infs
    pa, pb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    ca.dump(pa)
    cb.dump(pb)
    diffs = dbg.compare_accuracy(pa, pb)
    assert diffs and diffs[0]["delta"] > 0
    assert dbg.compare_accuracy(pa, pa) == []


def test_fused_bias_act_variants():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import functional as IF

    x = paddle.to_tensor(
        np.random.default_rng(0).standard_normal((3, 8)).astype("float32"))
    b = paddle.to_tensor(np.ones(8, np.float32))
    for act in ("gelu", "relu", "silu"):
        out = IF.fused_bias_act(x, b, act_method=act)
        assert tuple(out.shape) == (3, 8)
    glu = IF.fused_bias_act(x, None, act_method="swiglu")
    assert tuple(glu.shape) == (3, 4)
    x.stop_gradient = False
    IF.fused_bias_act(x, b, act_method="gelu").sum().backward()
    assert x.grad is not None


def test_asp_decorate_before_prune_and_odd_shapes():
    """The reference's documented order (decorate THEN prune) must work,
    and non-divisible weights are skipped, not fatal."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.incubate import asp

    paddle.seed(1)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 10))
    opt = asp.decorate(paddle.optimizer.AdamW(
        1e-2, parameters=model.parameters()))
    masks = asp.prune_model(model)          # after decorate
    # [32,10] weight skipped (10 % 4 != 0); [16,32] pruned
    assert len(masks) == 1
    x = paddle.to_tensor(np.random.default_rng(2)
                         .standard_normal((4, 16)).astype("float32"))
    y = paddle.to_tensor(np.random.default_rng(3).integers(0, 10, (4,))
                         .astype("int64"))
    for _ in range(2):
        loss = paddle.nn.functional.cross_entropy(model(x), y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    w0 = [p for n_, p in model.named_parameters()
          if n_.endswith("weight")][0]
    assert abs(asp.calculate_density(w0) - 0.5) < 0.05
    asp.reset_excluded_layers(model)
    assert not hasattr(w0, "_asp_mask")


def test_quantize_dynamic_int8_linear_accuracy_and_compile():
    """True-int8 dynamic path (reference: int8 predict with activation
    quant, analysis_predictor.h:94): int8x int8 dot with int32
    accumulation matches fp32 within quant tolerance, in eager AND
    inside a compiled step."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.quantization import quantize_dynamic, Int8DynamicLinear

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 8))
    x = np.random.default_rng(0).standard_normal((4, 32)).astype("float32")
    ref = net(paddle.to_tensor(x)).numpy()

    quantize_dynamic(net)
    assert isinstance(net[0], Int8DynamicLinear)
    assert isinstance(net[2], Int8DynamicLinear)
    out = net(paddle.to_tensor(x)).numpy()
    # int8 weights+activations: relative error bounded by quant grid
    assert np.abs(out - ref).max() < 0.05 * np.abs(ref).max() + 0.05

    @paddle.jit.to_static
    def predict(t):
        return net(t)

    for _ in range(3):
        y = predict(paddle.to_tensor(x))
    np.testing.assert_allclose(y.numpy(), out, rtol=1e-5, atol=1e-5)


def test_quantize_dynamic_bundle_round_trip(tmp_path):
    """A dynamic-int8 model exports to a StableHLO bundle whose compiled
    program CONTAINS the int8 dot (weights ride as int8), and the
    Predictor serves it bit-identically."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, static
    from paddle_tpu.jit import InputSpec
    from paddle_tpu.quantization import quantize_dynamic
    from paddle_tpu.inference import Predictor, Config

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 8))
    x = np.random.default_rng(0).standard_normal((4, 32)).astype("float32")
    quantize_dynamic(net)
    ref = net(paddle.to_tensor(x)).numpy()
    prefix = str(tmp_path / "dq")
    static.save_inference_model(
        prefix, [InputSpec([4, 32], "float32", "x")], None, layer=net)
    out = Predictor(Config(prefix)).run([x])[0]
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
    prog, _, _ = static.load_inference_model(prefix)
    assert "i8" in prog.ir_text()   # int8 really lives in the program


def test_quantize_dynamic_root_and_bad_types():
    import numpy as np
    import pytest
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.quantization import quantize_dynamic, Int8DynamicLinear

    paddle.seed(0)
    lin = nn.Linear(8, 4)
    x = np.ones((2, 8), np.float32)
    ref = lin(paddle.to_tensor(x)).numpy()
    q = quantize_dynamic(lin)        # bare Linear → replacement returned
    assert isinstance(q, Int8DynamicLinear)
    out = q(paddle.to_tensor(x)).numpy()
    assert np.abs(out - ref).max() < 0.05 * np.abs(ref).max() + 0.05

    with pytest.raises(ValueError, match="Linear subclasses only"):
        quantize_dynamic(nn.Sequential(nn.Conv2D(1, 2, 3)),
                         layer_types=(nn.Conv2D,))


def test_quantize_dynamic_state_dict_round_trip():
    """int8 weight + scale are buffers: state_dict carries them and a
    reload reproduces identical outputs."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.quantization import quantize_dynamic

    paddle.seed(3)
    net = nn.Sequential(nn.Linear(16, 8))
    quantize_dynamic(net)
    x = np.random.default_rng(1).standard_normal((2, 16)).astype("float32")
    ref = net(paddle.to_tensor(x)).numpy()
    state = net.state_dict()
    assert any("qweight" in k for k in state)
    assert any("w_scale" in k for k in state)

    paddle.seed(99)                  # different init
    net2 = nn.Sequential(nn.Linear(16, 8))
    quantize_dynamic(net2)
    net2.set_state_dict(state)
    np.testing.assert_allclose(net2(paddle.to_tensor(x)).numpy(), ref,
                               rtol=1e-6)
