"""End-to-end 'book' smokes: train to a loss threshold, save an
inference bundle, reload it, and predict — the reference's
test/book/test_fit_a_line.py / test_recognize_digits.py pattern
(train → save_inference_model → load_inference_model → infer)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu import static, inference
from paddle_tpu.static import InputSpec


def test_fit_a_line(tmp_path):
    """Linear regression trains below threshold and round-trips through
    the saved inference bundle (reference: test/book/test_fit_a_line.py)."""
    rng = np.random.default_rng(0)
    w_true = rng.normal(size=(13, 1)).astype(np.float32)
    x_all = rng.normal(size=(256, 13)).astype(np.float32)
    y_all = x_all @ w_true + 0.01 * rng.normal(
        size=(256, 1)).astype(np.float32)

    paddle.seed(0)
    net = nn.Linear(13, 1)
    opt = paddle.optimizer.SGD(0.05, parameters=net.parameters())
    loss_fn = nn.MSELoss()

    last = None
    for epoch in range(60):
        for i in range(0, 256, 32):
            x = paddle.to_tensor(x_all[i:i + 32])
            y = paddle.to_tensor(y_all[i:i + 32])
            loss = loss_fn(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            last = float(loss)
        if last < 0.05:
            break
    assert last < 0.05, f"fit_a_line did not converge: loss={last}"

    prefix = str(tmp_path / "fit_a_line")
    static.save_inference_model(
        prefix, [InputSpec([None, 13], "float32", "x")], None, layer=net)
    prog, feeds, fetches = static.load_inference_model(prefix)
    exe = static.Executor()
    out = exe.run(prog, feed={"x": x_all[:8]}, fetch_list=fetches)[0]
    np.testing.assert_allclose(out, net(paddle.to_tensor(x_all[:8])).numpy(),
                               rtol=1e-4, atol=1e-5)
    # predictions track the generating line
    assert float(np.mean((out - y_all[:8]) ** 2)) < 0.1


def test_recognize_digits_mlp(tmp_path):
    """Tiny MLP classifier trains to accuracy threshold; the Predictor
    serves the saved bundle (reference: test/book/test_recognize_digits.py)."""
    rng = np.random.default_rng(1)
    n, d, k = 512, 16, 4
    centers = rng.normal(size=(k, d)).astype(np.float32) * 2.0
    labels = rng.integers(0, k, size=n)
    feats = centers[labels] + 0.3 * rng.normal(size=(n, d)).astype(
        np.float32)

    paddle.seed(1)
    net = nn.Sequential(nn.Linear(d, 32), nn.ReLU(), nn.Linear(32, k))
    opt = paddle.optimizer.Adam(5e-3, parameters=net.parameters())
    ce = nn.CrossEntropyLoss()

    for epoch in range(30):
        for i in range(0, n, 64):
            x = paddle.to_tensor(feats[i:i + 64])
            y = paddle.to_tensor(labels[i:i + 64].astype(np.int64))
            loss = ce(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
        with paddle.no_grad():
            pred = np.argmax(net(paddle.to_tensor(feats)).numpy(), axis=1)
        acc = float((pred == labels).mean())
        if acc > 0.9:
            break
    assert acc > 0.9, f"classifier stuck at acc={acc}"

    prefix = str(tmp_path / "digits")
    static.save_inference_model(
        prefix, [InputSpec([None, d], "float32", "x")], None, layer=net)
    pred = inference.create_predictor(inference.Config(prefix))
    out = pred.run([feats[:32]])[0]
    served_acc = float((np.argmax(out, 1) == labels[:32]).mean())
    assert served_acc > 0.85, served_acc


def test_word2vec_book(tmp_path):
    """Skip-gram word2vec on a synthetic corpus: embeddings train until
    same-cluster words are nearer than cross-cluster words, then the
    embedding table round-trips through the saved bundle (reference:
    test/book/test_word2vec_book.py — N-gram embedding model trained to
    a cost threshold, then infer from the saved model)."""
    rng = np.random.default_rng(2)
    vocab, dim = 32, 16
    # two topic clusters: words co-occur only within their cluster
    cluster = np.arange(vocab) % 2
    pairs = []
    for _ in range(4000):
        c = rng.integers(0, 2)
        members = np.where(cluster == c)[0]
        w, ctx = rng.choice(members, 2, replace=False)
        pairs.append((w, ctx))
    pairs = np.array(pairs, np.int64)

    paddle.seed(2)

    class SkipGram(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(vocab, dim)
            self.out = nn.Linear(dim, vocab)

        def forward(self, w):
            return self.out(self.emb(w))

    net = SkipGram()
    opt = paddle.optimizer.Adam(5e-3, parameters=net.parameters())
    ce = nn.CrossEntropyLoss()
    last = None
    for epoch in range(12):
        perm = rng.permutation(len(pairs))
        for i in range(0, len(pairs), 256):
            b = pairs[perm[i:i + 256]]
            loss = ce(net(paddle.to_tensor(b[:, 0])),
                      paddle.to_tensor(b[:, 1]))
            loss.backward()
            opt.step()
            opt.clear_grad()
            last = float(loss)
        if last < 3.0:   # uniform over 32 words would be ln(32)=3.47
            break
    assert last < 3.0, f"word2vec did not converge: loss={last}"

    emb = net.emb.weight.numpy()
    emb = emb / np.linalg.norm(emb, axis=1, keepdims=True)
    sims = emb @ emb.T
    same = sims[cluster[:, None] == cluster[None, :]].mean()
    cross = sims[cluster[:, None] != cluster[None, :]].mean()
    assert same > cross + 0.1, (same, cross)

    prefix = str(tmp_path / "word2vec")
    static.save_inference_model(
        prefix, [InputSpec([None], "int64", "w")], None, layer=net)
    pred = inference.create_predictor(inference.Config(prefix))
    out = pred.run([pairs[:16, 0]])[0]
    ref = net(paddle.to_tensor(pairs[:16, 0])).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
