#!/usr/bin/env python
"""Eager op-dispatch overhead microbench: tier-1 op cache on vs off.

Measures ops/sec over a representative eager op loop — a 3-layer MLP
forward chain (matmul, add, relu, ... , sum) over grad-tracked tensors,
plus the full fwd+bwd train-style step — with the tier-1 executable
cache (core/op_cache.py, FLAGS_eager_op_cache) enabled and disabled in
the same process.  The uncached mode pays JAX eager dispatch plus a
fresh jax.vjp trace per op; the cached mode replays one jitted
executable per op signature.

Prints ONE JSON line and (unless --no-write) records the full result at
benchmarks/EAGER_OVERHEAD.json next to the other bench artifacts.
`--smoke` shrinks the iteration counts for CI (tools/run_ci.sh), which
then validates the JSON schema via tools/check_bench_result.py.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

# ops per fwd() call: 3 x (matmul, add, relu) + sum
_OPS_PER_FWD = 10


def _build(paddle):
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((32, 64)).astype(np.float32),
                         stop_gradient=False)
    ws = [paddle.to_tensor(
        (rng.standard_normal((64, 64)) * 0.05).astype(np.float32),
        stop_gradient=False) for _ in range(3)]
    bs = [paddle.to_tensor(np.zeros(64, np.float32), stop_gradient=False)
          for _ in range(3)]
    F = paddle.nn.functional

    def fwd():
        h = x
        for w, b in zip(ws, bs):
            h = F.relu(paddle.add(paddle.matmul(h, w), b))
        return h.sum()

    def step():
        loss = fwd()
        loss.backward()
        for p in ws + bs + [x]:
            p.clear_grad()
        return loss

    return fwd, step


def _time_loop(fn, iters, jax):
    fn()                       # warm (compiles on the cached pass)
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out._data_)
    return time.perf_counter() - t0, float(out)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny iteration counts for CI")
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "EAGER_OVERHEAD.json"))
    ap.add_argument("--no-write", action="store_true")
    args = ap.parse_args()

    import jax
    import paddle_tpu as paddle
    from paddle_tpu.core import op_cache
    from paddle_tpu.utils import cache_stats

    iters = args.iters or (40 if args.smoke else 200)
    paddle.seed(0)
    fwd, step = _build(paddle)

    results = {}
    losses = {}
    stats = None
    for mode, label in ((True, "cached"), (False, "uncached")):
        op_cache.clear()
        paddle.set_flags({"FLAGS_eager_op_cache": mode})
        dt_fwd, _ = _time_loop(fwd, iters, jax)
        dt_step, loss = _time_loop(step, max(iters // 4, 5), jax)
        results[label] = {
            "fwd_ops_per_sec": round(iters * _OPS_PER_FWD / dt_fwd, 1),
            "step_ops_per_sec": round(
                max(iters // 4, 5) * _OPS_PER_FWD / dt_step, 1),
        }
        losses[label] = loss
        if mode:
            stats = cache_stats()   # snapshot before clear() wipes tier 1
    paddle.set_flags({"FLAGS_eager_op_cache": True})

    if not np.allclose(losses["cached"], losses["uncached"],
                       rtol=1e-5, atol=1e-6):
        print(f"PARITY FAILURE: cached loss {losses['cached']} != "
              f"uncached {losses['uncached']}", file=sys.stderr)
        return 1

    speedup_fwd = (results["cached"]["fwd_ops_per_sec"]
                   / results["uncached"]["fwd_ops_per_sec"])
    speedup_step = (results["cached"]["step_ops_per_sec"]
                    / results["uncached"]["step_ops_per_sec"])
    rec = {
        "metric": "eager_op_dispatch_ops_per_sec",
        "value": results["cached"]["fwd_ops_per_sec"],
        "unit": "ops/sec",
        "speedup_vs_uncached": round(speedup_fwd, 3),
        "step_speedup_vs_uncached": round(speedup_step, 3),
        "cached": results["cached"],
        "uncached": results["uncached"],
        "loss": round(losses["cached"], 6),
        "iters": iters,
        "ops_per_fwd": _OPS_PER_FWD,
        "smoke": bool(args.smoke),
        "platform": jax.devices()[0].platform,
        "tier1": {k: stats["tier1"][k]
                  for k in ("hits", "misses", "evictions", "bypasses",
                            "entries", "bytes")},
    }
    if not args.no_write:
        try:
            with open(args.out, "w") as f:
                json.dump(rec, f, indent=1)
        except OSError as e:
            print(f"[eager_overhead] could not write {args.out}: {e}",
                  file=sys.stderr)
    print(json.dumps({k: rec[k] for k in
                      ("metric", "value", "unit", "speedup_vs_uncached",
                       "step_speedup_vs_uncached", "smoke")}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
