from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401

from . import ops  # noqa: F401


_image_backend = "pil"


def set_image_backend(backend):
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unsupported image backend {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image file (reference: vision/image.py image_load)."""
    b = backend or _image_backend
    if b == "cv2":
        raise NotImplementedError("cv2 not available in this environment")
    from PIL import Image
    img = Image.open(path)
    if b == "tensor":
        import numpy as np
        from ..core.tensor import Tensor
        import jax.numpy as jnp
        return Tensor(jnp.asarray(np.asarray(img)))
    return img
