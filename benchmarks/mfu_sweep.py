"""MFU sweep — two lanes:

**Layout sweep (default, ISSUE 12).**  Measure the compiled train step
across every dp×mp factorization of a virtual CPU world (≥4 devices)
for an mp-sharded GPT, compare against the auto-layout planner's
projections (``cost_model.plan_layout``), and emit
``benchmarks/MFU_SWEEP.json``: per-layout step p50 / tokens-per-sec /
MFU, the planner's pick, and projected-vs-measured error.  The smoke
config is parameter-heavy with few tokens — the regime where pure dp
genuinely loses (its gradient all-reduce moves the full model and its
optimizer update is replicated per device, while dp×mp shards both) —
so the ≥1.3x hybrid-vs-dp gate in ``tools/check_bench_result.py``
measures real physics, not dispatch noise.

Projection calibration: the analytic roofline carries spec-sheet
constants, so absolute CPU-host times are off by a box-dependent scale
plus a fixed per-step dispatch overhead.  Both are absorbed by an
affine two-anchor fit (the dp-only layout and the measured-best
layout); the HELD-OUT layouts' calibrated error is what the ≤25% gate
checks — the model must get the curvature between layouts right, the
anchors only set units.

**Batch sweep (``--batch-sweep``, TPU only).**  The original lane: find
the best single-chip GPT-2 batch size on real hardware, record it to
``TUNED.json`` for bench.py and append measurements to
``TPU_RUNS.jsonl``.
"""
from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

import numpy as np

BATCHES = [int(b) for b in os.environ.get(
    "MFU_SWEEP_BATCHES", "8,16,32").split(",")]
SEQ = 1024
STEPS = 8

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)       # `python benchmarks/mfu_sweep.py`
    # without an exported PYTHONPATH must still find paddle_tpu


def _log(msg):
    print(f"[mfu_sweep] {msg}", file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# layout sweep (virtual CPU world)
# ---------------------------------------------------------------------------

_LAYOUT_WORKER = r"""
import json, os, sys, time
n_dev = int(os.environ["MFU_SWEEP_DEVICES"])
os.environ["PALLAS_AXON_POOL_IPS"] = ""
flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
         if "host_platform_device_count" not in f]
flags.append(f"--xla_force_host_platform_device_count={n_dev}")
os.environ["XLA_FLAGS"] = " ".join(flags)
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

cfg_json = json.loads(os.environ["MFU_SWEEP_CONFIG"])
dp, mp = cfg_json["dp"], cfg_json["mp"]
batch, seq = cfg_json["batch"], cfg_json["seq"]
steps, warmup = cfg_json["steps"], cfg_json["warmup"]

import paddle_tpu as paddle
from paddle_tpu.models import ParallelGPTForCausalLM
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.fleet.base import _commit_params
from paddle_tpu.framework.train_step import CompiledTrainStep

cfg = GPTConfig(vocab_size=cfg_json["vocab"], hidden_size=cfg_json["hidden"],
                num_layers=cfg_json["layers"], num_heads=cfg_json["heads"],
                max_seq_len=seq, use_flash_attention=False)
paddle.seed(0)
mesh = mesh_mod.init_mesh([dp, mp], ["dp", "mp"])
if mp > 1:
    # hybrid GSPMD lane: the mesh must be ACTIVE so the TP layers'
    # constraints direct the collectives
    mesh_mod.set_mesh(mesh)
model = ParallelGPTForCausalLM(cfg)
opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters(),
                             weight_decay=0.01)
if mp > 1:
    _commit_params(model, mesh)
n_params = int(sum(p.size for p in model.parameters()))
rng = np.random.default_rng(0)
data = rng.integers(0, cfg.vocab_size, (batch, seq + 1), dtype=np.int32)
x, y = paddle.to_tensor(data[:, :-1]), paddle.to_tensor(data[:, 1:])

def forward(x, y):
    _, loss = model(x, labels=y)
    return loss

# dp-only baselines pass the mesh explicitly WITHOUT activating it:
# the shard_map lane (PR 8) with replicated weights — the exact
# pre-ISSUE-12 best case for this model at this world size
step = CompiledTrainStep(forward, opt, network=model, mesh=mesh)
for _ in range(warmup):
    loss = step(x, y, update=True)
jax.block_until_ready(loss._data_)
ts = []
for _ in range(steps):
    t0 = time.perf_counter()
    jax.block_until_ready(step(x, y, update=True)._data_)
    ts.append(time.perf_counter() - t0)
p50 = float(np.median(ts)) * 1e3
print(json.dumps({
    "dp": dp, "mp": mp, "p50_ms": p50,
    "tokens_per_sec": batch * seq / (p50 / 1e3),
    "compiled": bool(step.compiled),
    "fallback_reason": step.fallback_reason,
    "n_params": n_params,
    "loss": float(np.asarray(loss._data_)),
}))
"""


def _measure_layout(dp, mp, world, cfg, timeout=900):
    env = dict(os.environ)
    env.update({
        "MFU_SWEEP_DEVICES": str(world),
        "MFU_SWEEP_CONFIG": json.dumps(dict(cfg, dp=dp, mp=mp)),
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(_HERE)]
            + ([env_p] if (env_p := os.environ.get("PYTHONPATH")) else [])),
    })
    try:
        r = subprocess.run([sys.executable, "-c", _LAYOUT_WORKER],
                           env=env, capture_output=True, text=True,
                           timeout=timeout)
    except subprocess.TimeoutExpired:
        _log(f"layout dp{dp}xmp{mp} TIMED OUT")
        return None
    if r.returncode != 0:
        _log(f"layout dp{dp}xmp{mp} FAILED: {r.stderr[-500:]}")
        return None
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return None


def layout_sweep(args):
    import jax
    from paddle_tpu.cost_model import device_peak_flops, plan_layout
    from paddle_tpu.cost_model.planner import candidate_step_time

    world = args.world
    if args.smoke:
        cfg = dict(vocab=16384, hidden=256, layers=2, heads=4,
                   batch=4, seq=8, steps=args.steps or 8, warmup=3)
    else:
        cfg = dict(vocab=32768, hidden=512, layers=4, heads=8,
                   batch=8, seq=32, steps=args.steps or 10, warmup=3)

    layouts = [(world // m, m) for m in range(1, world + 1)
               if world % m == 0 and cfg["hidden"] % m == 0]
    _log(f"sweeping {len(layouts)} layouts over a {world}-device "
         f"virtual world: {layouts}")
    measured = {}
    n_params = None
    for dp, mp in layouts:
        rec = _measure_layout(dp, mp, world, cfg)
        if rec is None:
            continue
        measured[f"dp{dp}mp{mp}"] = rec
        n_params = rec["n_params"]
        _log(f"dp{dp}xmp{mp}: p50 {rec['p50_ms']:.1f}ms "
             f"(compiled={rec['compiled']})")
    if len(measured) < 2 or n_params is None:
        _log("not enough successful layout measurements")
        return 1

    # the recorded COMM_BUDGET files must pass their schema gate — a
    # stale budget failing loudly HERE beats it silently skewing a
    # future budget-calibrated plan (BudgetSchemaError propagates)
    from paddle_tpu.cost_model import load_comm_budgets
    budgets = load_comm_budgets(search_dir=_HERE)
    _log(f"validated {len(budgets)} COMM_BUDGET file(s): "
         f"{sorted(budgets)}")

    # planner projections over the SAME grid, from the measured model
    desc = dict(n_params=float(n_params), n_layers=cfg["layers"],
                hidden=cfg["hidden"], global_batch=cfg["batch"],
                seq_len=cfg["seq"], dtype_bytes=4)
    plan = plan_layout(desc, world, device="cpu")
    for name, rec in measured.items():
        step_s, _ = candidate_step_time(desc, rec["dp"], rec["mp"],
                                        device="cpu")
        rec["projected_raw_ms"] = step_s * 1e3

    # affine two-anchor calibration: dp-only + measured-best absorb the
    # host's scale and fixed dispatch overhead; the held-out layouts'
    # error gates the model's between-layout curvature
    dp_name = f"dp{world}mp1"
    best_name = min(measured, key=lambda n: measured[n]["p50_ms"])
    peak = device_peak_flops("cpu")
    a = measured.get(dp_name, measured[best_name])
    b = measured[best_name]
    if a is b or abs(a["projected_raw_ms"] - b["projected_raw_ms"]) < 1e-9:
        scale, offset = b["p50_ms"] / b["projected_raw_ms"], 0.0
    else:
        scale = (a["p50_ms"] - b["p50_ms"]) / (a["projected_raw_ms"]
                                               - b["projected_raw_ms"])
        offset = a["p50_ms"] - scale * a["projected_raw_ms"]
    errs = {}
    flops_step = 6.0 * n_params * cfg["batch"] * cfg["seq"]
    for name, rec in measured.items():
        rec["projected_ms"] = scale * rec["projected_raw_ms"] + offset
        rec["projected_err"] = abs(rec["projected_ms"] - rec["p50_ms"]) \
            / rec["p50_ms"]
        rec["anchor"] = name in (dp_name, best_name)
        rec["mfu"] = flops_step / (rec["p50_ms"] / 1e3 * peak * world)
        if not rec["anchor"]:
            errs[name] = rec["projected_err"]

    pick_name = f"dp{plan.dp}mp{plan.mp}"
    pick = measured.get(pick_name)
    best = measured[best_name]
    dp_only = measured.get(dp_name)
    rec = {
        "metric": "mfu_sweep_layouts",
        "value": round(best["p50_ms"], 3),
        "unit": "ms",
        "world_size": world,
        "model": dict(desc, n_params=int(n_params)),
        "layouts": {k: {kk: (round(vv, 4) if isinstance(vv, float)
                             else vv) for kk, vv in v.items()}
                    for k, v in measured.items()},
        "speedup_hybrid_vs_dp": round(
            dp_only["p50_ms"] / best["p50_ms"], 3) if dp_only else None,
        "planner": {
            "pick": {"dp": plan.dp, "mp": plan.mp},
            "pick_measured": pick is not None,
            "pick_p50_ms": round(pick["p50_ms"], 3) if pick else None,
            "pick_vs_best": round(pick["p50_ms"] / best["p50_ms"], 4)
            if pick else None,
            "max_projected_err": round(max(errs.values()), 4)
            if errs else 0.0,
            "calibration": {"scale": round(scale, 4),
                            "offset_ms": round(offset, 4),
                            "anchors": sorted({dp_name, best_name})},
            "source": plan.source,
            "projected_step_ms": round(plan.projected_step_s * 1e3, 4),
        },
        "steps": cfg["steps"],
        "batch": cfg["batch"],
        "seq": cfg["seq"],
        "smoke": bool(args.smoke),
        "platform": jax.devices()[0].platform,
    }
    out = args.out or os.path.join(_HERE, "MFU_SWEEP.json")
    try:
        with open(out, "w") as f:
            json.dump(rec, f, indent=1)
    except OSError as e:
        _log(f"could not write {out}: {e}")
    print(json.dumps({k: rec[k] for k in
                      ("metric", "value", "unit", "world_size",
                       "speedup_hybrid_vs_dp", "smoke")}
                     | {"planner_pick": rec["planner"]["pick"],
                        "pick_vs_best": rec["planner"]["pick_vs_best"],
                        "max_projected_err":
                            rec["planner"]["max_projected_err"]}))
    return 0


# ---------------------------------------------------------------------------
# batch sweep (TPU only — the original lane)
# ---------------------------------------------------------------------------

def measure(batch):
    """One measured config in a fresh python process (a fresh process
    releases all device buffers of the previous config)."""
    code = f"""
import json, sys, time
import numpy as np
import jax
import paddle_tpu as paddle
from paddle_tpu.models import GPTForCausalLM
from paddle_tpu.models.gpt import gpt_config

batch, seq, steps = {batch}, {SEQ}, {STEPS}
cfg = gpt_config("gpt2-124m", max_seq_len=seq, use_flash_attention=True)
try:
    from paddle_tpu.pallas.flash_attention import autotune_blocks
    autotune_blocks(seq, cfg.head_dim, batch=batch, heads=cfg.num_heads)
except Exception:
    pass
paddle.seed(0)
with paddle.amp.auto_cast(enable=True, level="O2", dtype="bfloat16"):
    model = GPTForCausalLM(cfg)
opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters(),
                             weight_decay=0.01)
rng = np.random.default_rng(0)
data = rng.integers(0, cfg.vocab_size, (batch, seq + 1), dtype=np.int32)
x, y = paddle.to_tensor(data[:, :-1]), paddle.to_tensor(data[:, 1:])
x1, y1 = paddle.to_tensor(data[:1, :-1]), paddle.to_tensor(data[:1, 1:])

# one donated-buffer compiled step (framework/train_step.py) — the same
# lane bench.py measures; eager fallback stays byte-identical
from paddle_tpu.framework.train_step import CompiledTrainStep

def forward(x, y):
    with paddle.amp.auto_cast(enable=True, level="O2", dtype="bfloat16"):
        _, loss = model(x, labels=y)
    return loss

def eager_step(x, y, update=True):
    loss = forward(x, y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    return loss

_cs = CompiledTrainStep(forward, opt, network=model, eager_step=eager_step)

def train_step(x, y):
    return _cs(x, y, update=True)

for _ in range(2):
    loss = train_step(x1, y1)
for _ in range(3):
    loss = train_step(x, y)
float(loss)

def timed(k):
    t0 = time.perf_counter()
    lv = None
    for _ in range(k):
        lv = train_step(x, y)
    lv = float(lv)
    return time.perf_counter() - t0, lv

t1, _ = timed(1)
tN, final_loss = timed(steps)
slope = (tN - t1) / (steps - 1)
print(json.dumps({{"batch": batch, "slope": slope,
                  "tokens_per_sec": batch * seq / slope,
                  "step_time_ms_p50": slope * 1e3,
                  "step_lane": "compiled" if _cs.compiled else "eager",
                  "t1": t1, "tN": tN, "loss": final_loss}}))
"""
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=2000)
    except subprocess.TimeoutExpired:
        _log(f"batch {batch} TIMED OUT — skipping")
        return None
    if r.returncode != 0:
        _log(f"batch {batch} FAILED: {r.stderr[-400:]}")
        return None
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return None


def batch_sweep():
    import jax
    if jax.devices()[0].platform not in ("tpu", "axon"):
        _log("not on TPU — batch sweep skipped")
        return 1
    runs_path = os.path.join(_HERE, "TPU_RUNS.jsonl")
    from paddle_tpu.cost_model import device_peak_flops
    peak = device_peak_flops(jax.devices()[0].platform)
    results = []
    for b in BATCHES:
        _log(f"measuring batch {b} ...")
        rec = measure(b)
        if rec is None:
            continue
        results.append(rec)
        _log(f"batch {b}: {rec['tokens_per_sec']:.0f} tok/s")
        with open(runs_path, "a") as f:
            f.write(json.dumps({
                "ts": datetime.datetime.now(
                    datetime.timezone.utc).isoformat(timespec="seconds"),
                "metric": "gpt2_124m_train_tokens_per_sec",
                "sweep": True, "batch": rec["batch"], "seq": SEQ,
                "tokens_per_sec": round(rec["tokens_per_sec"], 1),
                "step_lane": rec.get("step_lane"),
                "step_time_ms_p50": round(
                    rec.get("step_time_ms_p50", 0), 3),
                "loss": round(rec["loss"], 4),
                "timing": {"t1_s": round(rec["t1"], 6),
                           "tN_s": round(rec["tN"], 6), "N": STEPS,
                           "slope_s_per_step": round(rec["slope"], 6),
                           "method": "slope"},
                "platform": jax.devices()[0].platform,
                "peak_flops": peak,
            }) + "\n")
    if not results:
        _log("no successful measurements")
        return 1
    best = max(results, key=lambda r: r["tokens_per_sec"])
    tuned_path = os.path.join(_HERE, "TUNED.json")
    with open(tuned_path, "w") as f:
        json.dump({"gpt2_124m": {"batch": best["batch"], "seq": SEQ,
                                 "tokens_per_sec": round(
                                     best["tokens_per_sec"], 1)}}, f)
    _log(f"best batch {best['batch']} "
         f"({best['tokens_per_sec']:.0f} tok/s) -> {tuned_path}")
    print(json.dumps(best))
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-sweep", action="store_true",
                    help="original TPU single-chip batch sweep")
    ap.add_argument("--smoke", action="store_true",
                    help="small layout-sweep config for CI")
    ap.add_argument("--world", type=int,
                    default=int(os.environ.get("MFU_SWEEP_WORLD", "4")))
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    if args.batch_sweep:
        return batch_sweep()
    return layout_sweep(args)


if __name__ == "__main__":
    sys.exit(main())
