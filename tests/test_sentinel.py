"""Training sentinel: anomaly detection, last-known-good rollback,
bad-batch quarantine, and bad-host blame (docs/RESILIENCE.md).

The drills mirror the fault-tolerance suites: fault points
(``loss_spike`` / ``bad_batch`` / ``grad_bitflip``) make every branch
reachable deterministically, and recovered trajectories are compared
against clean runs that skip the same batches.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.framework.checkpoint_manager import (
    CheckpointManager, NonFiniteCheckpointError, validate_finite_state,
    verify_checkpoint)
from paddle_tpu.framework.sentinel import (
    TrainingSentinel, decide_blame, read_blame, sentinel_enabled)
from paddle_tpu.utils import fault_injection

N, BS = 48, 4


class ToyData:
    def __len__(self):
        return N

    def __getitem__(self, i):
        rng = np.random.default_rng(i)
        x = rng.normal(size=(8,)).astype(np.float32)
        return x, np.tanh(np.sum(x, keepdims=True)).astype(np.float32)


def _build():
    paddle.seed(3)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.AdamW(0.01,
                                         parameters=net.parameters()),
        loss=nn.MSELoss())
    return model, net


def _weights(net):
    return {k: np.asarray(v._data_) for k, v in net.state_dict().items()}


def _clean_run_skipping(skip_iters, compiled=False):
    """Reference trajectory: same batches, the quarantined iterations
    never trained, no sentinel."""
    paddle.set_flags({"FLAGS_sentinel": False,
                      "FLAGS_compiled_train_step": compiled})
    model, net = _build()
    data = ToyData()
    for it in range(N // BS):
        if it in skip_iters:
            continue
        xs = np.stack([data[i][0] for i in range(it * BS, (it + 1) * BS)])
        ys = np.stack([data[i][1] for i in range(it * BS, (it + 1) * BS)])
        model.train_batch(paddle.to_tensor(xs), paddle.to_tensor(ys))
    return _weights(net)


@pytest.fixture
def flags():
    """Snapshot/restore the flags the drills touch."""
    keys = ("FLAGS_sentinel", "FLAGS_compiled_train_step",
            "FLAGS_fault_inject", "FLAGS_sentinel_check_every",
            "FLAGS_sentinel_anchor_every", "FLAGS_sentinel_max_skips",
            "FLAGS_sentinel_rollback_after", "FLAGS_sentinel_window",
            "FLAGS_sentinel_dump_path")
    old = {k: paddle.get_flags([k])[k] for k in keys}
    yield paddle.set_flags
    paddle.set_flags(old)


def _fit_with_sentinel(tmp_path=None, **fit_kw):
    model, net = _build()
    holder = {}
    orig = paddle.Model._install_sentinel

    def patched(self, cb):
        s = orig(self, cb)
        holder["sentinel"] = s
        return s

    paddle.Model._install_sentinel = patched
    try:
        kw = dict(batch_size=BS, epochs=1, verbose=0, shuffle=False)
        kw.update(fit_kw)
        if tmp_path is not None:
            kw["save_dir"] = str(tmp_path)
        model.fit(ToyData(), **kw)
    finally:
        paddle.Model._install_sentinel = orig
    return model, net, holder.get("sentinel")


# ---------------------------------------------------------------------------
# satellites: GradScaler floor/streak, finite-validated checkpoints
# ---------------------------------------------------------------------------


def test_gradscaler_min_loss_scale_floor_and_streak_metric():
    from paddle_tpu.amp import GradScaler
    from paddle_tpu.utils import monitor
    sc = GradScaler(init_loss_scaling=256.0, decr_every_n_nan_or_inf=1,
                    min_loss_scale=64.0)
    for _ in range(10):
        sc._found_inf = True
        sc.update()
    assert sc.get_loss_scaling() == 64.0      # floored, not 1.0
    assert sc.found_inf_streak == 10
    assert monitor.get_monitor_value("amp.found_inf_streak") == 10
    sc._found_inf = False
    sc.update()
    assert sc.found_inf_streak == 0
    assert monitor.get_monitor_value("amp.found_inf_streak") == 0


def test_gradscaler_always_check_skips_at_unit_scale():
    """The sentinel's unit-scale wrapper must catch an Inf gradient —
    previously the check was skipped entirely at scale == 1.0."""
    from paddle_tpu.amp import GradScaler
    paddle.seed(0)
    net = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    loss = net(x).sum()
    loss.backward()
    p = net.parameters()[0]
    p.grad._data_ = p.grad._data_.at[(0, 0)].set(float("inf"))
    before = np.asarray(p._data_).copy()
    sc = GradScaler(init_loss_scaling=1.0,
                    use_dynamic_loss_scaling=False,
                    always_check_found_inf=True)
    sc.step(opt)
    assert sc._found_inf
    assert np.array_equal(before, np.asarray(p._data_))  # update skipped


def test_validate_finite_refuses_poisoned_checkpoint(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    bad = {"model": {"w": np.array([1.0, np.nan], np.float32)}}
    with pytest.raises(NonFiniteCheckpointError) as ei:
        mgr.save(bad, step=0, validate_finite=True)
    assert "model.w" in str(ei.value)
    assert mgr.restore_latest() is None       # nothing was persisted
    # default save still accepts (pre-PR behavior unchanged)
    mgr.save(bad, step=0)
    assert mgr.latest_step() == 0


def test_validate_finite_walks_nested_state():
    validate_finite_state({"a": [np.zeros(3), {"b": np.ones(2)}],
                           "n": 7, "s": "text"})
    with pytest.raises(NonFiniteCheckpointError):
        validate_finite_state({"a": [np.zeros(3),
                                     {"b": np.array([np.inf])}]})


def test_anchor_is_exempt_from_retention(tmp_path):
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
    mgr.save_anchor({"w": np.ones(3, np.float32)}, step=1)
    for s in range(6):
        mgr.save({"w": np.full(3, float(s), np.float32)}, step=s)
    steps = mgr.all_steps()
    assert steps == [4, 5]                    # retention rotated ckpts
    restored = mgr.restore_anchor()
    assert restored is not None
    state, step = restored
    assert step == 1 and np.array_equal(state["w"], np.ones(3))
    # a poisoned anchor update must refuse and keep the old anchor
    with pytest.raises(NonFiniteCheckpointError):
        mgr.save_anchor({"w": np.array([np.nan])}, step=2)
    assert mgr.restore_anchor()[1] == 1


def test_new_fault_point_specs_validate():
    spec = fault_injection.parse(
        "bad_batch:at_step=3,mode=nan;loss_spike:at_step=2,scale=1e6;"
        "grad_bitflip:rank=1,count=6")
    assert spec["bad_batch"]["at_step"] == 3
    assert spec["loss_spike"]["scale"] == 1e6
    assert spec["grad_bitflip"]["count"] == 6
    for bad in ("bad_batch:nope=1", "loss_spike:at_step=x",
                "grad_bitflip"):
        with pytest.raises(fault_injection.FaultSpecError):
            fault_injection.parse(bad)


# ---------------------------------------------------------------------------
# detection units
# ---------------------------------------------------------------------------


def test_zscore_spike_detection_unit(flags):
    flags({"FLAGS_sentinel": True, "FLAGS_sentinel_window": 16,
           "FLAGS_sentinel_check_every": 1})
    sen = TrainingSentinel(model=None)
    for it in range(12):
        sen.after_step(it, 0, it, 1.0 + 0.01 * it, update=True)
    assert sen.report()["anomalies"] == []
    sen.after_step(12, 0, 12, 1e6, update=True)   # finite spike
    rep = sen.report()
    assert [a["signal"] for a in rep["anomalies"]] == ["loss_spike"]
    assert rep["quarantined"] == [12]


def test_nonfinite_loss_detection_unit(flags):
    flags({"FLAGS_sentinel": True, "FLAGS_sentinel_check_every": 1})
    sen = TrainingSentinel(model=None)
    sen.after_step(0, 0, 0, float("nan"), update=True)
    rep = sen.report()
    assert rep["anomalies"][0]["signal"] == "nonfinite_loss"
    assert rep["quarantined"] == [0]


def test_blame_decision_unit():
    h = {0: {"local_anomalies": 0}, 1: {"local_anomalies": 3}}
    assert decide_blame(h) == 1
    # global pathology (both ranks anomalous) blames nobody
    assert decide_blame({0: {"local_anomalies": 2},
                         1: {"local_anomalies": 3}}) is None
    # below the threshold: not enough evidence
    assert decide_blame({0: {"local_anomalies": 0},
                         1: {"local_anomalies": 1}}) is None
    assert decide_blame({0: {"local_anomalies": 4}}) is None  # 1 rank


def test_sentinel_dump_schema(flags, tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import check_telemetry
    finally:
        sys.path.pop(0)
    dump_path = str(tmp_path / "sentinel.json")
    flags({"FLAGS_sentinel": True, "FLAGS_sentinel_check_every": 1,
           "FLAGS_sentinel_dump_path": dump_path})
    sen = TrainingSentinel(model=None)
    sen.after_step(0, 0, 0, float("nan"), update=True)
    path = sen.dump(action="rollback", step=0, anchor_step=0)
    assert path == dump_path and os.path.exists(path)
    errors = check_telemetry.check_sentinel_dump(path)
    assert not errors, errors
    data = json.load(open(path))
    assert data["reason"] == "sentinel"
    assert data["sentinel"]["anomalies"][0]["signal"] == "nonfinite_loss"


# ---------------------------------------------------------------------------
# fit drills
# ---------------------------------------------------------------------------


def test_sentinel_off_trajectory_bitwise_identical_eager(flags):
    """Healthy-path parity, eager lane: the sentinel's seams are pure
    pass-throughs — trajectories must be BITWISE equal on/off."""
    flags({"FLAGS_sentinel": False, "FLAGS_compiled_train_step": False})
    _, net_off, _ = _fit_with_sentinel()
    flags({"FLAGS_sentinel": True})
    _, net_on, sen = _fit_with_sentinel()
    assert sen is not None and sen.report()["anomalies"] == []
    off, on = _weights(net_off), _weights(net_on)
    for k in off:
        assert np.array_equal(off[k], on[k]), k


def test_sentinel_healthy_compiled_trajectory_ulp_equal(flags):
    """Compiled lane: the sentinel program adds the scaler-vec + health
    outputs, so XLA may re-fuse reductions — trajectories agree to the
    same ~1-ulp bound docs/TRAIN_STEP.md sets for program refusion (the
    flag-OFF program itself is byte-identical to pre-sentinel builds)."""
    flags({"FLAGS_sentinel": False, "FLAGS_compiled_train_step": True})
    _, net_off, _ = _fit_with_sentinel()
    flags({"FLAGS_sentinel": True})
    _, net_on, sen = _fit_with_sentinel()
    assert sen is not None and sen.report()["anomalies"] == []
    off, on = _weights(net_off), _weights(net_on)
    for k in off:
        np.testing.assert_allclose(off[k], on[k], rtol=2e-6, atol=1e-7,
                                   err_msg=k)


def test_rollback_drill_eager_loss_spike(flags, tmp_path):
    spike_it = 7
    flags({"FLAGS_sentinel": True, "FLAGS_compiled_train_step": False,
           "FLAGS_sentinel_check_every": 4,
           "FLAGS_sentinel_anchor_every": 4,
           "FLAGS_fault_inject":
               f"loss_spike:at_step={spike_it},scale=1e6"})
    _, net, sen = _fit_with_sentinel(tmp_path=tmp_path / "ckpts")
    rep = sen.report()
    assert rep["rollbacks"] == 1, rep
    assert spike_it in rep["quarantined"], rep
    # detection within the window: the anomaly step is the spiked one
    assert any(a["step"] == spike_it for a in rep["anomalies"])
    flags({"FLAGS_fault_inject": ""})
    ref = _clean_run_skipping({spike_it})
    got = _weights(net)
    worst = max(float(np.abs(got[k] - ref[k]).max()) for k in ref)
    assert worst < 5e-4, worst
    # the anchor rode the CheckpointManager anchor dir, exempt from
    # regular scans
    assert (tmp_path / "ckpts" / "anchor").exists()
    assert verify_checkpoint(str(tmp_path / "ckpts" / "anchor"))


def test_quarantine_drill_compiled_bad_batch(flags):
    bad_it = 7
    flags({"FLAGS_sentinel": True, "FLAGS_compiled_train_step": True,
           "FLAGS_sentinel_check_every": 4,
           "FLAGS_sentinel_anchor_every": 4,
           "FLAGS_fault_inject": f"bad_batch:at_step={bad_it},mode=nan"})
    model, net, sen = _fit_with_sentinel()
    rep = sen.report()
    cs = model._compiled_step
    assert cs not in (None, False) and cs.compiled, \
        getattr(cs, "fallback_reason", cs)
    # the NaN batch was skipped IN-PROGRAM (no rollback needed) and
    # quarantined for any future replay
    assert rep["skips"] >= 1 and bad_it in rep["quarantined"], rep
    flags({"FLAGS_fault_inject": ""})
    ref = _clean_run_skipping({bad_it}, compiled=True)
    got = _weights(net)
    worst = max(float(np.abs(got[k] - ref[k]).max()) for k in ref)
    assert worst < 5e-4, worst


def test_skip_streak_escalates_to_rollback(flags, tmp_path):
    flags({"FLAGS_sentinel": True, "FLAGS_compiled_train_step": False,
           "FLAGS_sentinel_check_every": 2,
           "FLAGS_sentinel_max_skips": 2,
           "FLAGS_sentinel_anchor_every": 2,
           "FLAGS_fault_inject": "bad_batch:mode=nan,count=3"})
    _, net, sen = _fit_with_sentinel(tmp_path=tmp_path / "ckpts")
    rep = sen.report()
    assert rep["rollbacks"] >= 1, rep
    assert {0, 1}.issubset(set(rep["quarantined"])), rep
    flags({"FLAGS_fault_inject": ""})
    ref = _clean_run_skipping(set(rep["quarantined"]))
    got = _weights(net)
    worst = max(float(np.abs(got[k] - ref[k]).max()) for k in ref)
    assert worst < 5e-4, worst


def test_controller_quarantine_shrinks_world(tmp_path, monkeypatch):
    from paddle_tpu.distributed.launch.context import Context, parse_args
    from paddle_tpu.distributed.launch.controller import (
        CollectiveController)
    from paddle_tpu.framework.sentinel import publish_blame

    monkeypatch.setenv("PADDLE_JOB_ID", "default")
    args = parse_args(["--nproc_per_node", "2", "--log_dir",
                       str(tmp_path), "dummy.py"])
    ctl = CollectiveController(Context(args=args))
    ctl._guardian_env()
    publish_blame(ctl._trap, 1, {"anomalies": 4})
    assert read_blame(ctl._trap.store, ctl._trap.job)["rank"] == 1
    ctl._apply_quarantine()
    assert ctl._world == 1
    assert ctl._extra_env["PADDLE_ELASTIC_RESIZED"] == "2:1"
    # blame consumed: a second relaunch does not shrink again
    ctl._apply_quarantine()
    assert ctl._world == 1


def test_sentinel_disabled_flag_reads_false(flags):
    flags({"FLAGS_sentinel": False})
    assert not sentinel_enabled()
    flags({"FLAGS_sentinel": True})
    assert sentinel_enabled()


# ---------------------------------------------------------------------------
# 2-process blame drill (slow: spawns a jax.distributed world)
# ---------------------------------------------------------------------------


def test_blame_drill_two_procs(tmp_path):
    from paddle_tpu.distributed.launch.context import Context, parse_args
    from paddle_tpu.distributed.launch.controller import (
        CollectiveController)
    worker = os.path.join(os.path.dirname(__file__),
                          "_sentinel_worker.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    old = {k: os.environ.get(k)
           for k in ("PYTHONPATH", "FLAGS_sentinel_dump_path")}
    os.environ["PYTHONPATH"] = repo + os.pathsep + \
        os.environ.get("PYTHONPATH", "")
    os.environ["FLAGS_sentinel_dump_path"] = \
        str(tmp_path / "sentinel.json")
    try:
        args = parse_args(["--nproc_per_node", "2", "--max_restart", "0",
                           "--log_dir", str(tmp_path / "logs"),
                           worker, "blame", str(tmp_path)])
        CollectiveController(Context(args=args)).run()
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    reports = {}
    for rank in (0, 1):
        p = tmp_path / f"blame_report.{rank}.json"
        assert p.exists(), list(tmp_path.iterdir())
        reports[rank] = json.load(open(p))
    # rank 1's grads were the anomaly source: blamed by name
    assert reports[0]["report"]["blamed_rank"] == 1, reports
    assert reports[0]["report"]["local_anomalies"] == 0, reports
    assert reports[1]["report"]["local_anomalies"] >= 2, reports
    # the sentinel dump carries the blame for post-mortem reading
    dump = tmp_path / "sentinel.rank0.json"
    assert dump.exists(), list(tmp_path.iterdir())
    data = json.load(open(dump))
    assert data["reason"] == "sentinel"
    assert data["sentinel"]["blamed_rank"] == 1
    # escalation ended in the quarantine path on at least one rank
    assert any("sentinel-error" in reports[r]["outcome"]
               for r in (0, 1)), reports
