"""Device management (reference: python/paddle/device/).

TPU-native: devices are JAX devices; `set_device` selects the default
placement.  There is no per-op stream management — XLA owns scheduling.
"""
from __future__ import annotations

import jax


_current = None


def set_device(device: str):
    """Accepts 'tpu', 'cpu', 'tpu:0' etc."""
    global _current
    _current = device
    return device


def get_device() -> str:
    if _current is not None:
        return _current
    d = jax.devices()[0]
    return f"{d.platform}:{getattr(d, 'id', 0)}"


def get_all_devices():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def device_count() -> int:
    return jax.device_count()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return any(d.platform in ("tpu", "axon") for d in jax.devices())


def synchronize(device=None):
    """Block until all queued device work is done (reference:
    paddle.device.synchronize / cudaDeviceSynchronize).  JAX arrays are
    async; effectively a fence via block_until_ready on a trivial op."""
    import jax.numpy as jnp
    jnp.zeros(()).block_until_ready()


class Stream:
    """API-parity stub: XLA manages streams internally on TPU."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_stream(self, other):
        pass


class Event:
    def __init__(self, enable_timing=False):
        self._t = None

    def record(self, stream=None):
        import time
        synchronize()
        self._t = time.perf_counter()

    def synchronize(self):
        synchronize()

    def elapsed_time(self, end):
        return (end._t - self._t) * 1000.0


def cuda_stream_guard(*a, **k):
    import contextlib

    @contextlib.contextmanager
    def _g():
        yield
    return _g()


class XPUPlace:
    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return f"Place(xpu:{self.device_id})"


class IPUPlace:
    def __repr__(self):
        return "Place(ipu)"


def get_cudnn_version():
    """No cuDNN in an XLA/TPU runtime (reference returns the linked
    version on CUDA builds)."""
    return None


def is_compiled_with_cinn():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_custom_device(device_type):
    return device_type in ("tpu", "axon")


def get_all_device_type():
    import jax
    try:
        return sorted({d.platform for d in jax.devices()} | {"cpu"})
    except Exception:
        return ["cpu"]


def get_all_custom_device_type():
    return [t for t in get_all_device_type() if t not in ("cpu", "gpu")]


def get_available_device():
    import jax
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [d for d in get_available_device()
            if not d.startswith(("cpu", "gpu"))]


def current_stream(device=None):
    """XLA orders execution per device; the Stream object is the
    compatibility handle (reference: device/cuda streams)."""
    return Stream(device)


def set_stream(stream):
    return stream


def stream_guard(stream):
    """Context placing ops on a stream (reference: device/__init__.py
    stream_guard) — XLA orders per-device execution, so this scopes the
    compatibility handle only."""
    import contextlib

    @contextlib.contextmanager
    def ctx():
        yield stream
    return ctx()
