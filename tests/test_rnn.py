"""RNN layer family (reference: python/paddle/nn/layer/rnn.py; tests
mirror test/legacy_test/test_rnn_op.py's numpy-reference pattern)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def _np(t):
    return np.asarray(t._data)


def _np_lstm_ref(x, h0, c0, w_ih, w_hh, b_ih, b_hh):
    """numpy LSTM over time, gates [i, f, g, o]."""
    B, T, _ = x.shape
    H = h0.shape[-1]
    h, c = h0.copy(), c0.copy()
    ys = []
    sig = lambda a: 1.0 / (1.0 + np.exp(-a))  # noqa: E731
    for t in range(T):
        z = x[:, t] @ w_ih.T + h @ w_hh.T + b_ih + b_hh
        i, f, g, o = np.split(z, 4, axis=-1)
        c = sig(f) * c + sig(i) * np.tanh(g)
        h = sig(o) * np.tanh(c)
        ys.append(h)
    return np.stack(ys, 1), h, c


def test_lstm_matches_numpy():
    paddle.seed(0)
    B, T, I, H = 3, 5, 8, 16
    cell = nn.LSTMCell(I, H)
    rnn = nn.RNN(cell)
    x = paddle.to_tensor(
        np.random.default_rng(0).standard_normal((B, T, I)).astype("float32"))
    ys, (h, c) = rnn(x)
    ref_y, ref_h, ref_c = _np_lstm_ref(
        _np(x), np.zeros((B, H), np.float32), np.zeros((B, H), np.float32),
        _np(cell.weight_ih), _np(cell.weight_hh),
        _np(cell.bias_ih), _np(cell.bias_hh))
    np.testing.assert_allclose(_np(ys), ref_y, atol=1e-5)
    np.testing.assert_allclose(_np(h), ref_h, atol=1e-5)
    np.testing.assert_allclose(_np(c), ref_c, atol=1e-5)


def test_cells_single_step():
    paddle.seed(1)
    B, I, H = 2, 4, 6
    x = paddle.randn([B, I])
    for cell_cls in (nn.SimpleRNNCell, nn.GRUCell):
        cell = cell_cls(I, H)
        y, h = cell(x)
        assert tuple(y.shape) == (B, H)
    lstm = nn.LSTMCell(I, H)
    y, (h, c) = lstm(x)
    assert tuple(y.shape) == (B, H) and tuple(c.shape) == (B, H)


@pytest.mark.parametrize("mode", ["SimpleRNN", "GRU", "LSTM"])
def test_network_shapes_and_grad(mode):
    paddle.seed(2)
    B, T, I, H, L = 2, 6, 8, 12, 2
    net = getattr(nn, mode)(I, H, num_layers=L, direction="bidirectional")
    x = paddle.randn([B, T, I])
    x.stop_gradient = False
    out, final = net(x)
    assert tuple(out.shape) == (B, T, 2 * H)
    if mode == "LSTM":
        h, c = final
        assert tuple(h.shape) == (L * 2, B, H) and tuple(c.shape) == (L * 2, B, H)
    else:
        assert tuple(final.shape) == (L * 2, B, H)
    out.mean().backward()
    assert x.grad is not None
    for p in net.parameters():
        assert p.grad is not None


def test_sequence_length_masking():
    paddle.seed(3)
    B, T, I, H = 2, 5, 4, 8
    net = nn.GRU(I, H)
    x_np = np.random.default_rng(1).standard_normal((B, T, I)).astype(
        "float32")
    x = paddle.to_tensor(x_np)
    seq_len = paddle.to_tensor(np.array([3, 5], np.int32))
    out, final = net(x, sequence_length=seq_len)
    # outputs past a sequence's end are zero
    np.testing.assert_allclose(_np(out)[0, 3:], 0.0, atol=0)
    # final state for row 0 equals running only the first 3 steps
    out3, final3 = net(paddle.to_tensor(x_np[:, :3]))
    np.testing.assert_allclose(_np(final)[0, 0], _np(final3)[0, 0],
                               atol=1e-6)


def test_reverse_direction_with_lengths():
    paddle.seed(4)
    B, T, I, H = 2, 6, 4, 8
    cell = nn.SimpleRNNCell(I, H)
    rnn_rev = nn.RNN(cell, is_reverse=True)
    x_np = np.random.default_rng(2).standard_normal((B, T, I)).astype(
        "float32")
    x = paddle.to_tensor(x_np)
    seq_len = paddle.to_tensor(np.array([4, 6], np.int32))
    out, final = rnn_rev(x, sequence_length=seq_len)
    # row 0: reversed over its first 4 steps only; final == output at t=0
    np.testing.assert_allclose(_np(out)[0, 4:], 0.0, atol=0)
    np.testing.assert_allclose(_np(final)[0], _np(out)[0, 0], atol=1e-6)


def test_time_major():
    paddle.seed(5)
    T, B, I, H = 5, 3, 4, 8
    net = nn.LSTM(I, H, time_major=True)
    x = paddle.randn([T, B, I])
    out, (h, c) = net(x)
    assert tuple(out.shape) == (T, B, H)
    assert tuple(h.shape) == (1, B, H)


def test_custom_cell_python_loop():
    class Counter(nn.RNNCellBase):
        def __init__(self):
            super().__init__()
            self.hidden_size = 4
            self.lin = nn.Linear(4, 4)

        def forward(self, x, states=None):
            if states is None:
                states = self.get_initial_states(x)
            h = self.lin(x) + states
            return h, h

    rnn = nn.RNN(Counter())
    x = paddle.randn([2, 3, 4])
    out, final = rnn(x)
    assert tuple(out.shape) == (2, 3, 4)


def test_state_dict_roundtrip():
    net = nn.LSTM(4, 8, num_layers=2)
    sd = net.state_dict()
    assert any("cell_0_0" in k for k in sd)
    net2 = nn.LSTM(4, 8, num_layers=2)
    net2.set_state_dict(sd)
    x = paddle.randn([2, 3, 4])
    o1, _ = net(x)
    o2, _ = net2(x)
    np.testing.assert_allclose(_np(o1), _np(o2), atol=0)
