"""FFT family (reference capability: python/paddle/fft.py — fft/ifft/
rfft/irfft and 2d/nd variants over phi FFT kernels; on TPU jnp.fft lowers
to XLA's FFT HLO)."""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import apply_op
from .core.tensor import Tensor


def _wrap1(name, jfn):
    def op(x, n=None, axis=-1, norm="backward", name_=None):
        return apply_op(name, lambda a: jfn(a, n=n, axis=axis, norm=norm),
                        (x if isinstance(x, Tensor) else Tensor(x),))
    op.__name__ = name
    return op


def _wrapn(name, jfn):
    def op(x, s=None, axes=None, norm="backward", name_=None):
        kw = {"s": s, "norm": norm}
        if axes is not None:
            kw["axes"] = axes
        return apply_op(name, lambda a: jfn(a, **kw),
                        (x if isinstance(x, Tensor) else Tensor(x),))
    op.__name__ = name
    return op


fft = _wrap1("fft", jnp.fft.fft)
ifft = _wrap1("ifft", jnp.fft.ifft)
rfft = _wrap1("rfft", jnp.fft.rfft)
irfft = _wrap1("irfft", jnp.fft.irfft)
hfft = _wrap1("hfft", jnp.fft.hfft)
ihfft = _wrap1("ihfft", jnp.fft.ihfft)

fft2 = _wrapn("fft2", jnp.fft.fft2)
ifft2 = _wrapn("ifft2", jnp.fft.ifft2)
rfft2 = _wrapn("rfft2", jnp.fft.rfft2)
irfft2 = _wrapn("irfft2", jnp.fft.irfft2)
fftn = _wrapn("fftn", jnp.fft.fftn)
ifftn = _wrapn("ifftn", jnp.fft.ifftn)
rfftn = _wrapn("rfftn", jnp.fft.rfftn)
irfftn = _wrapn("irfftn", jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or jnp.float32))


def fftshift(x, axes=None, name=None):
    return apply_op("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes),
                    (x if isinstance(x, Tensor) else Tensor(x),))


def ifftshift(x, axes=None, name=None):
    return apply_op("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes),
                    (x if isinstance(x, Tensor) else Tensor(x),))


def _data(x):
    return x._data_ if isinstance(x, Tensor) else jnp.asarray(x)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """2-D Hermitian FFT: complex hermitian input → real output
    (reference: fft.py hfft2 = fft over axes[:-1] then hfft on the last)."""
    a = _data(x)
    inner = jnp.fft.fft(a, n=None if s is None else s[0], axis=axes[0],
                        norm=norm)
    n_last = None if s is None else s[1]
    return Tensor(jnp.fft.hfft(inner, n=n_last, axis=axes[1], norm=norm))


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    a = _data(x)
    first = jnp.fft.ihfft(a, n=None if s is None else s[1], axis=axes[1],
                          norm=norm)
    return Tensor(jnp.fft.ifft(first, n=None if s is None else s[0],
                               axis=axes[0], norm=norm))


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    a = _data(x)
    nd = a.ndim
    axes = tuple(range(nd)) if axes is None else tuple(axes)
    for i, ax in enumerate(axes[:-1]):
        a = jnp.fft.fft(a, n=None if s is None else s[i], axis=ax,
                        norm=norm)
    return Tensor(jnp.fft.hfft(
        a, n=None if s is None else s[-1], axis=axes[-1], norm=norm))


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    a = _data(x)
    nd = a.ndim
    axes = tuple(range(nd)) if axes is None else tuple(axes)
    a = jnp.fft.ihfft(a, n=None if s is None else s[-1], axis=axes[-1],
                      norm=norm)
    for i, ax in enumerate(axes[:-1]):
        a = jnp.fft.ifft(a, n=None if s is None else s[i], axis=ax,
                         norm=norm)
    return Tensor(a)
