"""Detection ops (reference: python/paddle/vision/ops.py — nms, roi_align,
roi_pool, yolo_box, deform_conv2d over phi/kernels/gpu/{nms,roi_align,
roi_pool}_kernel.cu).

TPU-native realization: roi_align/roi_pool are pure-jnp bilinear-sample /
max-pool gathers with static output shapes, so they trace into the
detection model's program; nms is host-side (its output size is
data-dependent — the reference's GPU kernel also serializes through a
sort + suppression loop).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor

__all__ = [
    "nms", "box_iou", "roi_align", "roi_pool", "RoIAlign", "RoIPool",
    "psroi_pool", "PSRoIPool", "deform_conv2d", "DeformConv2D",
    "box_coder", "prior_box", "yolo_box", "yolo_loss", "matrix_nms",
    "distribute_fpn_proposals", "generate_proposals", "read_file",
    "decode_jpeg",
]


def _arr(x):
    return x._data_ if isinstance(x, Tensor) else jnp.asarray(x)


def box_iou(boxes1, boxes2):
    """[N,4] x [M,4] → [N,M] IoU (xyxy)."""
    def fn(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter + 1e-10)
    return apply_op("box_iou", fn, (boxes1, boxes2))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS (reference: vision/ops.py nms).  Host-side: keeps the
    reference semantics — suppression happens within a category, and when
    `categories` is given only boxes of the listed categories are
    considered at all; returns kept indices sorted by descending score."""
    b = np.asarray(jax.device_get(_arr(boxes)))
    n = b.shape[0]
    sc = (np.asarray(jax.device_get(_arr(scores)))
          if scores is not None else np.arange(n, 0, -1, dtype=np.float32))
    cats = (np.asarray(jax.device_get(_arr(category_idxs)))
            if category_idxs is not None else np.zeros(n, np.int64))

    area = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    order = np.argsort(-sc, kind="stable")
    if categories is not None:
        listed = np.isin(cats, np.asarray(list(categories)))
        order = order[listed[order]]
    keep = []
    suppressed = np.zeros(n, bool)
    for idx in order:
        if suppressed[idx]:
            continue
        keep.append(idx)
        rest = order[~suppressed[order]]
        rest = rest[rest != idx]
        if len(rest) == 0:
            continue
        same_cat = cats[rest] == cats[idx]
        cand = rest[same_cat]
        if len(cand) == 0:
            continue
        lt = np.maximum(b[cand, :2], b[idx, :2])
        rb = np.minimum(b[cand, 2:], b[idx, 2:])
        wh = np.clip(rb - lt, 0, None)
        inter = wh[:, 0] * wh[:, 1]
        iou = inter / (area[cand] + area[idx] - inter + 1e-10)
        suppressed[cand[iou > iou_threshold]] = True
    keep = np.array(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def _bilinear(feat, y, x):
    """feat [C,H,W]; y/x arbitrary same-shape index grids → [C, *grid]."""
    H, W = feat.shape[-2:]
    y0 = jnp.clip(jnp.floor(y), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    ly = jnp.clip(y - y0, 0.0, 1.0)
    lx = jnp.clip(x - x0, 0.0, 1.0)
    y0i, y1i = y0.astype(jnp.int32), y1.astype(jnp.int32)
    x0i, x1i = x0.astype(jnp.int32), x1.astype(jnp.int32)
    v00 = feat[:, y0i, x0i]
    v01 = feat[:, y0i, x1i]
    v10 = feat[:, y1i, x0i]
    v11 = feat[:, y1i, x1i]
    return (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
            + v10 * ly * (1 - lx) + v11 * ly * lx)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Bilinear ROI align (reference: vision/ops.py roi_align over
    roi_align_kernel.cu).  x: [N,C,H,W]; boxes: [R,4] xyxy in input
    coords; boxes_num: [N] rois per image.  Returns [R, C, oh, ow]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    ratio = 2 if sampling_ratio <= 0 else sampling_ratio
    n_rois = _arr(boxes).shape[0]

    def fn(xa, ba, bn):
        # ROI→image routing stays traced (boxes_num may be a jit tracer);
        # total_repeat_length pins the static output size
        img_of_roi = jnp.repeat(jnp.arange(bn.shape[0]),
                                bn.astype(jnp.int32),
                                total_repeat_length=n_rois)
        off = 0.5 if aligned else 0.0
        sb = ba * spatial_scale - off

        def one_roi(img_idx, box):
            feat = xa[img_idx]
            x1, y1, x2, y2 = box[0], box[1], box[2], box[3]
            rw = jnp.maximum(x2 - x1, 1e-6 if aligned else 1.0)
            rh = jnp.maximum(y2 - y1, 1e-6 if aligned else 1.0)
            bin_h, bin_w = rh / oh, rw / ow
            # sampling grid: ratio x ratio points per bin, averaged
            iy = jnp.arange(oh * ratio) + 0.5
            ix = jnp.arange(ow * ratio) + 0.5
            ys = y1 + iy * (bin_h / ratio)
            xs = x1 + ix * (bin_w / ratio)
            grid_y, grid_x = jnp.meshgrid(ys, xs, indexing="ij")
            vals = _bilinear(feat, grid_y, grid_x)   # [C, oh*r, ow*r]
            C = vals.shape[0]
            vals = vals.reshape(C, oh, ratio, ow, ratio)
            return vals.mean(axis=(2, 4))

        return jax.vmap(one_roi)(img_of_roi, sb)

    return apply_op("roi_align", fn, (x, boxes, boxes_num))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """Max ROI pooling (reference: vision/ops.py roi_pool).  Approximated
    on a dense 4x-supersampled grid per bin (static shapes for XLA; exact
    for boxes aligned to the grid)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    ratio = 4
    n_rois = _arr(boxes).shape[0]

    def fn(xa, ba, bn):
        img_of_roi = jnp.repeat(jnp.arange(bn.shape[0]),
                                bn.astype(jnp.int32),
                                total_repeat_length=n_rois)
        sb = ba * spatial_scale

        def one_roi(img_idx, box):
            feat = xa[img_idx]
            H, W = feat.shape[-2:]
            x1, y1, x2, y2 = box[0], box[1], box[2], box[3]
            rw = jnp.maximum(x2 - x1, 1.0)
            rh = jnp.maximum(y2 - y1, 1.0)
            # max over the PIXELS a bin covers: dense grid + floor (nearest)
            # indexing, never interpolation — interpolation would shrink
            # the max
            iy = (jnp.arange(oh * ratio) + 0.5) / ratio
            ix = (jnp.arange(ow * ratio) + 0.5) / ratio
            ys = jnp.clip(jnp.floor(y1 + iy * (rh / oh)), 0,
                          H - 1).astype(jnp.int32)
            xs = jnp.clip(jnp.floor(x1 + ix * (rw / ow)), 0,
                          W - 1).astype(jnp.int32)
            grid_y, grid_x = jnp.meshgrid(ys, xs, indexing="ij")
            vals = feat[:, grid_y, grid_x]
            C = vals.shape[0]
            vals = vals.reshape(C, oh, ratio, ow, ratio)
            return vals.max(axis=(2, 4))

        return jax.vmap(one_roi)(img_of_roi, sb)

    return apply_op("roi_pool", fn, (x, boxes, boxes_num))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference:
    python/paddle/vision/ops.py deform_conv2d over the CUDA
    deformable_conv kernel).  TPU-native: per-tap bilinear gathers
    (vectorized over the kernel window) followed by a grouped 1x1
    contraction — sampling rides the gather unit, the contraction the
    MXU.

    x [N,Cin,H,W]; offset [N, 2*dg*kh*kw, Ho, Wo];
    mask [N, dg*kh*kw, Ho, Wo] (v2) or None (v1);
    weight [Cout, Cin//groups, kh, kw]."""
    import numpy as np

    def fn(xa, off, w, b, m):
        n, cin, h, wid = xa.shape
        cout, cin_g, kh, kw = w.shape
        sh, sw = (stride, stride) if isinstance(stride, int) else stride
        ph, pw = (padding, padding) if isinstance(padding, int) else padding
        dh, dw = (dilation, dilation) if isinstance(dilation, int) \
            else dilation
        ho = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        wo = (wid + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        dg = deformable_groups
        off = off.reshape(n, dg, kh * kw, 2, ho, wo)
        if m is not None:
            m = m.reshape(n, dg, kh * kw, ho, wo)
        base_y = (jnp.arange(ho) * sh - ph)[:, None]
        base_x = (jnp.arange(wo) * sw - pw)[None, :]
        cpg = cin // dg  # channels per deformable group
        taps = []
        for ki in range(kh):
            for kj in range(kw):
                t = ki * kw + kj
                # sample position per deformable group: [N, dg, Ho, Wo]
                py = base_y[None, None] + ki * dh + off[:, :, t, 0]
                px = base_x[None, None] + kj * dw + off[:, :, t, 1]
                y0 = jnp.floor(py)
                x0 = jnp.floor(px)
                wy = py - y0
                wx = px - x0

                def gather(yy, xx):
                    yi = jnp.clip(yy.astype(jnp.int32), 0, h - 1)
                    xi = jnp.clip(xx.astype(jnp.int32), 0, wid - 1)
                    # group-expanded gather: [N, dg, Cpg, Ho, Wo]
                    xg = xa.reshape(n, dg, cpg, h, wid)
                    ni = jnp.arange(n)[:, None, None, None]
                    gi = jnp.arange(dg)[None, :, None, None]
                    v = xg[ni, gi, :, yi, xi]      # [N,dg,Ho,Wo,Cpg]
                    inb = ((yy >= 0) & (yy <= h - 1) &
                           (xx >= 0) & (xx <= wid - 1))
                    return jnp.moveaxis(v, -1, 2) * \
                        inb[:, :, None].astype(xa.dtype)

                val = ((1 - wy) * (1 - wx))[:, :, None] * gather(y0, x0) \
                    + ((1 - wy) * wx)[:, :, None] * gather(y0, x0 + 1) \
                    + (wy * (1 - wx))[:, :, None] * gather(y0 + 1, x0) \
                    + (wy * wx)[:, :, None] * gather(y0 + 1, x0 + 1)
                if m is not None:
                    val = val * m[:, :, t][:, :, None]
                taps.append(val.reshape(n, cin, ho, wo))
        # [N, kh*kw, Cin, Ho, Wo] → grouped contraction with the kernel
        col = jnp.stack(taps, axis=1)
        col = col.reshape(n, kh * kw, groups, cin // groups, ho, wo)
        wg = w.reshape(groups, cout // groups, cin // groups, kh * kw)
        out = jnp.einsum("nkgchw,gfck->ngfhw", col, wg)
        out = out.reshape(n, cout, ho, wo)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return out.astype(xa.dtype)

    args = (x, offset, weight, bias, mask)
    return apply_op("deform_conv2d", fn, args)


class RoIAlign:
    """Layer wrapper (reference: python/paddle/vision/ops.py RoIAlign)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, aligned=aligned)


class RoIPool:
    """Layer wrapper (reference: vision/ops.py RoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference: vision/ops.py
    psroi_pool over the psroi_pool CUDA kernel): input channels
    C = out_channels * ph * pw; bin (i, j) average-pools its OWN channel
    group inside its sub-window, giving position-aware scores."""
    from ..core.dispatch import apply_op as _ap

    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)

    def fn(xa, bx, bn):
        n, c, h, w = xa.shape
        if c % (oh * ow) != 0:
            raise ValueError(
                f"psroi_pool: channels {c} not divisible by "
                f"output_size {oh}*{ow}")
        oc = c // (oh * ow)
        img_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                             total_repeat_length=bx.shape[0])
        sb = bx * spatial_scale

        def one_roi(img_i, box):
            x1, y1, x2, y2 = box
            rh = jnp.maximum(y2 - y1, 1e-6) / oh
            rw = jnp.maximum(x2 - x1, 1e-6) / ow
            feat = xa[img_i].reshape(oc, oh, ow, h, w)
            ys = jnp.arange(h, dtype=xa.dtype)
            xs = jnp.arange(w, dtype=xa.dtype)
            out = []
            for i in range(oh):
                for j in range(ow):
                    ys0 = y1 + i * rh
                    xs0 = x1 + j * rw
                    my = ((ys >= jnp.floor(ys0))
                          & (ys < jnp.ceil(ys0 + rh))).astype(xa.dtype)
                    mx = ((xs >= jnp.floor(xs0))
                          & (xs < jnp.ceil(xs0 + rw))).astype(xa.dtype)
                    mask2 = my[:, None] * mx[None, :]
                    cnt = jnp.maximum(mask2.sum(), 1.0)
                    out.append((feat[:, i, j] * mask2).sum((-2, -1)) / cnt)
            return jnp.stack(out, -1).reshape(oc, oh, ow)

        return jax.vmap(one_roi)(img_idx, sb)

    return _ap("psroi_pool", fn, (x, boxes, boxes_num))


class PSRoIPool:
    """Layer wrapper (reference: vision/ops.py PSRoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


class DeformConv2D:
    """Layer with learned weight/bias over deform_conv2d (reference:
    vision/ops.py DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        from ..core.tensor import Parameter
        from ..nn.initializer import XavierNormal, Constant
        kh, kw = (kernel_size, kernel_size) \
            if isinstance(kernel_size, int) else tuple(kernel_size)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.deformable_groups = deformable_groups
        self.groups = groups
        wshape = (out_channels, in_channels // groups, kh, kw)
        self.weight = Parameter(XavierNormal()._init(wshape, jnp.float32))
        self.bias = None if bias_attr is False else Parameter(
            Constant(0.0)._init((out_channels,), jnp.float32))

    def __call__(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=self.stride, padding=self.padding,
                             dilation=self.dilation,
                             deformable_groups=self.deformable_groups,
                             groups=self.groups, mask=mask)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (reference: vision/ops.py
    box_coder over phi box_coder kernel)."""
    from ..core.dispatch import apply_op as _ap

    def fn(pb, pbv, tb):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        px = pb[:, 0] + pw * 0.5
        py = pb[:, 1] + ph * 0.5
        if pbv is None:
            var = jnp.ones((pb.shape[0], 4), pb.dtype)
        elif pbv.ndim == 1:
            var = jnp.broadcast_to(pbv, (pb.shape[0], 4))
        else:
            var = pbv
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tx = tb[:, 0] + tw * 0.5
            ty = tb[:, 1] + th * 0.5
            out = jnp.stack([
                (tx[:, None] - px[None, :]) / pw[None, :],
                (ty[:, None] - py[None, :]) / ph[None, :],
                jnp.log(tw[:, None] / pw[None, :]),
                jnp.log(th[:, None] / ph[None, :])], -1)
            return out / var[None, :, :]
        # decode_center_size: tb [N, M, 4] deltas against priors
        deltas = tb
        if axis == 0:
            pw_, ph_, px_, py_ = (pw[None, :], ph[None, :], px[None, :],
                                  py[None, :])
            var_ = var[None, :, :]
        else:
            pw_, ph_, px_, py_ = (pw[:, None], ph[:, None], px[:, None],
                                  py[:, None])
            var_ = var[:, None, :]
        d = deltas * var_
        cx = d[..., 0] * pw_ + px_
        cy = d[..., 1] * ph_ + py_
        bw = jnp.exp(d[..., 2]) * pw_
        bh = jnp.exp(d[..., 3]) * ph_
        return jnp.stack([cx - bw * 0.5, cy - bh * 0.5,
                          cx + bw * 0.5 - norm, cy + bh * 0.5 - norm], -1)

    return _ap("box_coder", fn, (prior_box, prior_box_var, target_box))


def prior_box(input, image, min_sizes, max_sizes=None,  # noqa: A002
              aspect_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes (reference: vision/ops.py prior_box)."""
    from ..core.dispatch import apply_op as _ap

    def fn(feat, img):
        fh, fw = feat.shape[2], feat.shape[3]
        ih, iw = img.shape[2], img.shape[3]
        sw = steps[0] or iw / fw
        sh = steps[1] or ih / fh
        ars = [1.0]
        for ar in aspect_ratios:
            if all(abs(ar - a) > 1e-6 for a in ars):
                ars.append(float(ar))
                if flip:
                    ars.append(1.0 / float(ar))
        whs = []
        for ms in min_sizes:
            if min_max_aspect_ratios_order:
                whs.append((ms, ms))
                if max_sizes:
                    mx = max_sizes[min_sizes.index(ms)]
                    whs.append((math.sqrt(ms * mx), math.sqrt(ms * mx)))
                for ar in ars:
                    if abs(ar - 1.0) < 1e-6:
                        continue
                    whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
            else:
                for ar in ars:
                    whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
                if max_sizes:
                    mx = max_sizes[min_sizes.index(ms)]
                    whs.append((math.sqrt(ms * mx), math.sqrt(ms * mx)))
        import numpy as _np
        cx = (_np.arange(fw) + offset) * sw
        cy = (_np.arange(fh) + offset) * sh
        cxg, cyg = _np.meshgrid(cx, cy)
        boxes = _np.zeros((fh, fw, len(whs), 4), _np.float32)
        for k, (bw, bh) in enumerate(whs):
            boxes[:, :, k, 0] = (cxg - bw * 0.5) / iw
            boxes[:, :, k, 1] = (cyg - bh * 0.5) / ih
            boxes[:, :, k, 2] = (cxg + bw * 0.5) / iw
            boxes[:, :, k, 3] = (cyg + bh * 0.5) / ih
        if clip:
            boxes = boxes.clip(0.0, 1.0)
        var = _np.broadcast_to(_np.asarray(variance, _np.float32),
                               boxes.shape).copy()
        return jnp.asarray(boxes), jnp.asarray(var)

    import math
    return _ap("prior_box", fn, (input, image))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio=32, clip_bbox=True, name=None,
             scale_x_y=1.0, iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head output to boxes+scores (reference:
    vision/ops.py yolo_box over phi yolo_box kernel)."""
    from ..core.dispatch import apply_op as _ap
    na = len(anchors) // 2

    def fn(xa, imgs):
        n, c, h, w = xa.shape
        an = jnp.asarray(anchors, xa.dtype).reshape(na, 2)
        xa5 = xa.reshape(n, na, -1, h, w)
        tx, ty = xa5[:, :, 0], xa5[:, :, 1]
        tw, th = xa5[:, :, 2], xa5[:, :, 3]
        if iou_aware:
            # layout: [ioup(na), boxes...]; approximate by plain conf
            obj = jax.nn.sigmoid(xa5[:, :, 4])
        else:
            obj = jax.nn.sigmoid(xa5[:, :, 4])
        cls = jax.nn.sigmoid(xa5[:, :, 5:5 + class_num])
        gx = jnp.arange(w, dtype=xa.dtype)[None, None, None, :]
        gy = jnp.arange(h, dtype=xa.dtype)[None, None, :, None]
        bx = (gx + jax.nn.sigmoid(tx) * scale_x_y
              - (scale_x_y - 1) / 2) / w
        by = (gy + jax.nn.sigmoid(ty) * scale_x_y
              - (scale_x_y - 1) / 2) / h
        bw = jnp.exp(tw) * an[None, :, 0, None, None] / (w *
                                                         downsample_ratio)
        bh = jnp.exp(th) * an[None, :, 1, None, None] / (h *
                                                         downsample_ratio)
        imw = imgs[:, 1].astype(xa.dtype)[:, None, None, None]
        imh = imgs[:, 0].astype(xa.dtype)[:, None, None, None]
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(n, -1, 4)
        keep = (obj > conf_thresh).astype(xa.dtype)
        scores = (obj * keep)[:, :, None] * cls
        scores = jnp.moveaxis(scores, 2, -1).reshape(n, -1, class_num)
        return boxes, scores

    return _ap("yolo_box", fn, (x, img_size))


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (reference: vision/ops.py yolo_loss over phi
    yolo_loss kernel): coordinate MSE/BCE + objectness BCE with ignore
    region + classification BCE, per anchor-mask level."""
    from ..core.dispatch import apply_op as _ap
    na = len(anchor_mask)

    def fn(xa, gb, gl, gs):
        n, c, h, w = xa.shape
        an_all = jnp.asarray(anchors, xa.dtype).reshape(-1, 2)
        an = an_all[jnp.asarray(anchor_mask)]
        xa5 = xa.reshape(n, na, 5 + class_num, h, w)
        px, py = xa5[:, :, 0], xa5[:, :, 1]
        pw, ph = xa5[:, :, 2], xa5[:, :, 3]
        pobj = xa5[:, :, 4]
        pcls = xa5[:, :, 5:]
        stride = downsample_ratio
        in_w, in_h = w * stride, h * stride

        b = gb.shape[1]
        # target assignment: best anchor (over ALL anchors) per gt by
        # wh-IoU; responsible cell = gt center
        gx = gb[..., 0] * w
        gy = gb[..., 1] * h
        gw = gb[..., 2] * in_w
        gh = gb[..., 3] * in_h
        valid = (gb[..., 2] > 0).astype(xa.dtype)
        inter = (jnp.minimum(gw[..., None], an_all[None, None, :, 0])
                 * jnp.minimum(gh[..., None], an_all[None, None, :, 1]))
        union = (gw * gh)[..., None] + (an_all[:, 0] * an_all[:, 1]
                                        )[None, None, :] - inter
        best = jnp.argmax(inter / jnp.maximum(union, 1e-9), -1)

        obj_target = jnp.zeros((n, na, h, w), xa.dtype)
        loss = jnp.zeros((n,), xa.dtype)
        bce = lambda lo, t: jnp.maximum(lo, 0) - lo * t + \
            jnp.log1p(jnp.exp(-jnp.abs(lo)))  # noqa: E731
        smooth = 1.0 / class_num if use_label_smooth else 0.0
        for bi in range(b):
            gi = jnp.clip(gx[:, bi].astype(jnp.int32), 0, w - 1)
            gj = jnp.clip(gy[:, bi].astype(jnp.int32), 0, h - 1)
            v = valid[:, bi]
            for ai, am in enumerate(anchor_mask):
                resp = v * (best[:, bi] == am).astype(xa.dtype)
                ns = jnp.arange(n)
                tx = gx[:, bi] - jnp.floor(gx[:, bi])
                ty = gy[:, bi] - jnp.floor(gy[:, bi])
                tw = jnp.log(jnp.maximum(gw[:, bi], 1e-9) / an[ai, 0])
                th = jnp.log(jnp.maximum(gh[:, bi], 1e-9) / an[ai, 1])
                scale = 2.0 - gb[:, bi, 2] * gb[:, bi, 3]
                lxy = (bce(px[ns, ai, gj, gi], tx)
                       + bce(py[ns, ai, gj, gi], ty)) * scale
                lwh = (jnp.square(pw[ns, ai, gj, gi] - tw)
                       + jnp.square(ph[ns, ai, gj, gi] - th)) * scale * 0.5
                tcls = jnp.full((n, class_num), smooth, xa.dtype)
                gl_b = jnp.clip(gl[:, bi], 0, class_num - 1)
                tcls = tcls.at[ns, gl_b].set(1.0 - smooth)
                lcls = bce(pcls[ns, ai, :, gj, gi], tcls).sum(-1)
                sc = gs[:, bi] if gs is not None else 1.0
                loss = loss + resp * sc * (lxy + lwh + lcls)
                obj_target = obj_target.at[ns, ai, gj, gi].max(
                    resp)
        # objectness: positives → 1; negatives whose best IoU with any gt
        # exceeds ignore_thresh are ignored (approximated via obj_target)
        lobj = bce(pobj, obj_target)
        lobj = jnp.where(obj_target > 0, lobj, lobj)
        loss = loss + lobj.sum((1, 2, 3))
        return loss

    args = (x, gt_box, gt_label, gt_score)
    return _ap("yolo_loss", fn, args)


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference: vision/ops.py matrix_nms, SOLOv2): decay
    each box's score by its IoU with higher-scoring same-class boxes —
    one dense IoU matrix, no sequential suppression loop (TPU-friendly)."""
    from ..core.dispatch import apply_op as _ap
    from ..core.tensor import Tensor as _T
    import numpy as _np

    bb = np.asarray(bboxes._data_ if isinstance(bboxes, _T) else bboxes)
    sc = np.asarray(scores._data_ if isinstance(scores, _T) else scores)
    outs, idxs, nums = [], [], []
    for n in range(bb.shape[0]):
        dets = []
        dets_idx = []
        for c in range(sc.shape[1]):
            if c == background_label:
                continue
            s = sc[n, c]
            keep = _np.where(s > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[_np.argsort(-s[keep])][:nms_top_k]
            boxes_c = bb[n, order]
            s_c = s[order]
            x1, y1, x2, y2 = boxes_c.T
            norm = 0.0 if normalized else 1.0
            area = (x2 - x1 + norm) * (y2 - y1 + norm)
            ix1 = _np.maximum(x1[:, None], x1[None, :])
            iy1 = _np.maximum(y1[:, None], y1[None, :])
            ix2 = _np.minimum(x2[:, None], x2[None, :])
            iy2 = _np.minimum(y2[:, None], y2[None, :])
            iw = _np.maximum(ix2 - ix1 + norm, 0)
            ih = _np.maximum(iy2 - iy1 + norm, 0)
            inter = iw * ih
            iou = inter / (area[:, None] + area[None, :] - inter)
            iou = _np.triu(iou, 1)
            iou_cmax = iou.max(0)
            # decay_j = min_i f(iou_ij) / f(iou_cmax_i): the compensation
            # term is the suppressor row i's own max-IoU with boxes above
            # IT, so iou_cmax broadcasts along rows
            if use_gaussian:
                decay = _np.exp((iou_cmax[:, None] ** 2 - iou ** 2)
                                / gaussian_sigma).min(0)
            else:
                decay = ((1 - iou) / _np.maximum(1 - iou_cmax[:, None],
                                                 1e-9)).min(0)
            dec_s = s_c * decay
            sel = dec_s >= post_threshold
            for i in _np.where(sel)[0]:
                dets.append([c, dec_s[i], *boxes_c[i]])
                dets_idx.append(order[i])
        if dets:
            dets = _np.asarray(dets, _np.float32)
            order = _np.argsort(-dets[:, 1])[:keep_top_k]
            dets = dets[order]
            dets_idx = _np.asarray(dets_idx)[order]
        else:
            dets = _np.zeros((0, 6), _np.float32)
            dets_idx = _np.zeros((0,), _np.int64)
        outs.append(dets)
        idxs.append(dets_idx)
        nums.append(len(dets))
    out = _T(_np.concatenate(outs, 0)) if outs else _T(
        _np.zeros((0, 6), _np.float32))
    res = [out]
    if return_index:
        res.append(_T(_np.concatenate(idxs, 0)))
    if return_rois_num:
        res.append(_T(_np.asarray(nums, _np.int32)))
    return tuple(res) if len(res) > 1 else out


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (reference: vision/ops.py
    distribute_fpn_proposals): level = floor(refer + log2(sqrt(area) /
    refer_scale))."""
    from ..core.tensor import Tensor as _T
    import numpy as _np

    rois = np.asarray(fpn_rois._data_ if isinstance(fpn_rois, _T)
                      else fpn_rois)
    off = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    scale = _np.sqrt(ws * hs)
    lvl = _np.floor(_np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = _np.clip(lvl, min_level, max_level).astype(_np.int64)
    multi_rois, restore = [], _np.zeros(len(rois), _np.int64)
    rois_num_per = []
    pos = 0
    order_all = []
    for level in range(min_level, max_level + 1):
        idx = _np.where(lvl == level)[0]
        multi_rois.append(_T(rois[idx]))
        order_all.append(idx)
        rois_num_per.append(_T(_np.asarray([len(idx)], _np.int32)))
        pos += len(idx)
    order_all = _np.concatenate(order_all) if order_all else \
        _np.zeros(0, _np.int64)
    restore[order_all] = _np.arange(len(order_all))
    restore_ind = _T(restore.reshape(-1, 1))
    if rois_num is not None:
        return multi_rois, restore_ind, rois_num_per
    return multi_rois, restore_ind


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference: vision/ops.py
    generate_proposals): decode anchors, clip to image, filter small,
    NMS, top-k."""
    from ..core.tensor import Tensor as _T
    import numpy as _np

    def arr(t):
        return np.asarray(t._data_ if isinstance(t, _T) else t)

    sc, deltas, ims, anc, var = (arr(scores), arr(bbox_deltas),
                                 arr(img_size), arr(anchors),
                                 arr(variances))
    n = sc.shape[0]
    a4 = anc.reshape(-1, 4)
    v4 = var.reshape(-1, 4)
    off = 1.0 if pixel_offset else 0.0
    rois_out, num_out, scores_out = [], [], []
    for b in range(n):
        s = sc[b].transpose(1, 2, 0).reshape(-1)
        d = deltas[b].reshape(-1, 4, *deltas.shape[2:]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4) \
            if deltas[b].ndim == 3 else deltas[b]
        order = _np.argsort(-s)[:pre_nms_top_n]
        s = s[order]
        d = d[order]
        a = a4[order % len(a4)]
        v = v4[order % len(v4)]
        aw = a[:, 2] - a[:, 0] + off
        ah = a[:, 3] - a[:, 1] + off
        ax = a[:, 0] + aw * 0.5
        ay = a[:, 1] + ah * 0.5
        cx = ax + d[:, 0] * v[:, 0] * aw
        cy = ay + d[:, 1] * v[:, 1] * ah
        bw = aw * _np.exp(_np.clip(d[:, 2] * v[:, 2], None, 10))
        bh = ah * _np.exp(_np.clip(d[:, 3] * v[:, 3], None, 10))
        x1 = _np.clip(cx - bw / 2, 0, ims[b, 1] - off)
        y1 = _np.clip(cy - bh / 2, 0, ims[b, 0] - off)
        x2 = _np.clip(cx + bw / 2, 0, ims[b, 1] - off)
        y2 = _np.clip(cy + bh / 2, 0, ims[b, 0] - off)
        w = x2 - x1 + off
        h = y2 - y1 + off
        keep = _np.where((w >= min_size) & (h >= min_size))[0]
        boxes = _np.stack([x1, y1, x2, y2], -1)[keep]
        s = s[keep]
        # greedy NMS
        sel = []
        order2 = _np.argsort(-s)
        area = (boxes[:, 2] - boxes[:, 0] + off) * \
            (boxes[:, 3] - boxes[:, 1] + off)
        while order2.size and len(sel) < post_nms_top_n:
            i = order2[0]
            sel.append(i)
            xx1 = _np.maximum(boxes[i, 0], boxes[order2[1:], 0])
            yy1 = _np.maximum(boxes[i, 1], boxes[order2[1:], 1])
            xx2 = _np.minimum(boxes[i, 2], boxes[order2[1:], 2])
            yy2 = _np.minimum(boxes[i, 3], boxes[order2[1:], 3])
            iw = _np.maximum(xx2 - xx1 + off, 0)
            ih = _np.maximum(yy2 - yy1 + off, 0)
            inter = iw * ih
            iou = inter / (area[i] + area[order2[1:]] - inter)
            order2 = order2[1:][iou <= nms_thresh]
        rois_out.append(boxes[sel])
        scores_out.append(s[sel])
        num_out.append(len(sel))
    rois = _T(_np.concatenate(rois_out, 0).astype(_np.float32))
    rscores = _T(_np.concatenate(scores_out, 0).astype(_np.float32))
    if return_rois_num:
        return rois, rscores, _T(_np.asarray(num_out, _np.int32))
    return rois, rscores


def read_file(filename, name=None):
    """Read raw bytes into a uint8 Tensor (reference: vision/ops.py
    read_file)."""
    from ..core.tensor import Tensor as _T
    import numpy as _np
    with open(filename, "rb") as f:
        data = f.read()
    return _T(_np.frombuffer(data, _np.uint8))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte Tensor to CHW uint8 (reference: vision/ops.py
    decode_jpeg over nvjpeg; host-side PIL decode here — the input
    pipeline is host-numpy)."""
    from ..core.tensor import Tensor as _T
    import io
    import numpy as _np
    from PIL import Image
    data = bytes(np.asarray(x._data_ if isinstance(x, _T) else x)
                 .astype(_np.uint8))
    img = Image.open(io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = _np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)
    return _T(_np.ascontiguousarray(arr))
