"""Native component tests: cpp_extension JIT build + shm ring queue +
multiprocess DataLoader (reference: test/cpp_extension, dataloader
use_shared_memory tests)."""
import multiprocessing as mp
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.io.shm_queue import ShmQueue, QueueClosed
from paddle_tpu.utils.cpp_extension import load, BuildError, get_include


def test_cpp_extension_load_and_cache(tmp_path):
    src = tmp_path / "mini.cpp"
    src.write_text('extern "C" int add3(int x) { return x + 3; }\n')
    lib = load("mini_ext", [str(src)], build_directory=str(tmp_path))
    assert lib.add3(4) == 7
    sos = [f for f in os.listdir(tmp_path) if f.endswith(".so")]
    assert len(sos) == 1
    # second load reuses the cached .so (same hash)
    load("mini_ext", [str(src)], build_directory=str(tmp_path))
    assert len([f for f in os.listdir(tmp_path)
                if f.endswith(".so")]) == 1


def test_cpp_extension_build_error(tmp_path):
    src = tmp_path / "broken.cpp"
    src.write_text("this is not C++")
    with pytest.raises(BuildError):
        load("broken_ext", [str(src)], build_directory=str(tmp_path))


def test_shm_queue_roundtrip():
    q = ShmQueue(capacity=4, slot_size=1 << 16)
    try:
        q.put({"x": np.arange(5)})
        q.put("two")
        assert q.qsize() == 2
        first = q.get()
        np.testing.assert_array_equal(first["x"], np.arange(5))
        assert q.get() == "two"
    finally:
        q.close()
        q.release()


def test_shm_queue_oversized_payload():
    q = ShmQueue(capacity=2, slot_size=256)
    try:
        with pytest.raises(ValueError, match="slot_size"):
            q.put(np.zeros(10000))
    finally:
        q.close()
        q.release()


def test_shm_queue_multiprocess():
    q = ShmQueue(capacity=4, slot_size=1 << 16)

    def producer():
        for i in range(20):
            q.put(("item", i))
        q.close()

    p = mp.get_context("fork").Process(target=producer, daemon=True)
    p.start()
    got = []
    try:
        while True:
            got.append(q.get(timeout=10))
    except QueueClosed:
        pass
    p.join()
    q.release()
    assert [i for _, i in got] == list(range(20))


class _SquareDataset(Dataset):
    def __init__(self, n=32):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.float32(i) ** 2, np.float32(i)


def test_dataloader_multiprocess_shm():
    ds = _SquareDataset(32)
    dl = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False,
                    use_shared_memory=True)
    batches = list(dl)
    assert len(batches) == 8
    xs = np.concatenate([b[0].numpy() for b in batches])
    np.testing.assert_allclose(xs, np.arange(32, dtype=np.float32) ** 2)


def test_dataloader_threaded_fallback():
    ds = _SquareDataset(16)
    dl = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False,
                    use_shared_memory=False)
    batches = list(dl)
    assert len(batches) == 4


def test_register_c_kernel_dispatches_and_jits(tmp_path):
    """Kernel-registration C ABI (reference: phi/capi kernel_registry):
    a C function registers as a framework op, runs through the
    dispatcher, and composes with jax.jit via pure_callback."""
    src = tmp_path / "kern.cpp"
    src.write_text(
        'extern "C" void twice_plus_one(const float* x, float* y,\n'
        '                               long long n) {\n'
        '  for (long long i = 0; i < n; ++i) y[i] = 2.0f * x[i] + 1.0f;\n'
        '}\n')
    from paddle_tpu.utils.cpp_extension import register_c_kernel
    lib = load("kern_ext", [str(src)], build_directory=str(tmp_path))
    op = register_c_kernel("twice_plus_one_test", lib, "twice_plus_one")

    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    out = op(x)
    np.testing.assert_allclose(out.numpy(), 2 * x.numpy() + 1)

    # registered in the op registry like any yaml-defined op
    from paddle_tpu.ops.registry import get_op
    assert get_op("twice_plus_one_test") is not None

    # composes with compilation (host callback inside a compiled step)
    @paddle.jit.to_static
    def step(t):
        return op(t) * 3.0

    for _ in range(3):   # discovery + bind + compiled call
        y = step(x)
    np.testing.assert_allclose(y.numpy(), (2 * x.numpy() + 1) * 3.0,
                               rtol=1e-6)


def test_dataloader_worker_error_surfaces_in_trainer():
    """A worker failure (the classic: batch exceeds the shm slot) must
    raise a CLEAR error in the trainer process naming the cause, not a
    bare 'worker exited (code 1)' with the traceback lost to stderr."""
    class Big(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return np.zeros((1 << 16,), np.float32)   # 256 KiB/sample

    dl = DataLoader(Big(), batch_size=4, num_workers=2,
                    use_shared_memory=True)
    dl.shm_slot_size = 1 << 16       # 64 KiB slots: batches cannot fit
    with pytest.raises(RuntimeError, match="slot_size"):
        for _ in dl:
            pass
