"""Tiered executable cache tests (core/op_cache.py).

Covers the ISSUE-1 acceptance surface: tier-1 hit/miss counters, the LRU
eviction bound, fallback-path parity (saved-tensor hooks, unhashable
statics, per-call closure impls, flag off), gradient correctness through
the cached jitted vjp, RNG-drawing op opt-out, and the tier-2 persistent
compilation cache round trip."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import op_cache
from paddle_tpu.utils import cache_stats


@pytest.fixture(autouse=True)
def _fresh_cache():
    op_cache.clear()
    paddle.set_flags({"FLAGS_eager_op_cache": True,
                      "FLAGS_eager_op_cache_size": 4096})
    yield
    op_cache.clear()
    paddle.set_flags({"FLAGS_eager_op_cache": True,
                      "FLAGS_eager_op_cache_size": 4096})


def _t1():
    return cache_stats()["tier1"]


def test_hit_miss_counters():
    x = paddle.to_tensor(np.ones((4, 5), np.float32))
    paddle.nn.functional.relu(x)
    st = _t1()
    assert st["misses"] == 1 and st["hits"] == 0 and st["entries"] == 1
    paddle.nn.functional.relu(x)
    paddle.nn.functional.relu(x)
    st = _t1()
    assert st["misses"] == 1 and st["hits"] == 2
    # a different signature is a separate entry
    y = paddle.to_tensor(np.ones((2, 3), np.float32))
    paddle.nn.functional.relu(y)
    st = _t1()
    assert st["misses"] == 2 and st["entries"] == 2
    assert st["bytes"] > 0


def test_grad_flag_and_static_kwargs_separate_entries():
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    xg = paddle.to_tensor(np.ones((4, 4), np.float32), stop_gradient=False)
    paddle.nn.functional.softmax(x, axis=0)
    paddle.nn.functional.softmax(x, axis=1)   # static kwarg in the key
    paddle.nn.functional.softmax(xg, axis=0)  # grad flag in the key
    st = _t1()
    assert st["misses"] == 3 and st["entries"] == 3


def test_lru_eviction_bound():
    paddle.set_flags({"FLAGS_eager_op_cache_size": 4})
    for n in range(2, 9):   # 7 distinct signatures
        paddle.nn.functional.relu(
            paddle.to_tensor(np.ones((n,), np.float32)))
    st = _t1()
    assert st["entries"] <= 4
    assert st["evictions"] >= 3
    assert st["misses"] == 7


def test_flag_off_bypasses_and_matches():
    x = paddle.to_tensor(np.random.default_rng(0)
                         .standard_normal((8, 8)).astype(np.float32))
    on = paddle.nn.functional.gelu(x).numpy()
    paddle.set_flags({"FLAGS_eager_op_cache": False})
    off = paddle.nn.functional.gelu(x).numpy()
    st = _t1()
    np.testing.assert_allclose(on, off, rtol=1e-6, atol=1e-6)
    assert st["misses"] == 1 and st["hits"] == 0  # only the flag-on call


def test_grad_correctness_through_cached_vjp():
    rng = np.random.default_rng(1)
    xv = rng.standard_normal((6, 4)).astype(np.float32)
    wv = rng.standard_normal((4, 3)).astype(np.float32)

    def run():
        x = paddle.to_tensor(xv, stop_gradient=False)
        w = paddle.to_tensor(wv, stop_gradient=False)
        y = paddle.nn.functional.relu(paddle.matmul(x, w))
        loss = (y * y).sum()
        loss.backward()
        return float(loss), x.grad.numpy(), w.grad.numpy()

    l1, gx1, gw1 = run()           # populates the cache (misses)
    l2, gx2, gw2 = run()           # replays cached jitted vjp forwards
    st = _t1()
    assert st["hits"] > 0, "second pass should hit the cached executables"
    paddle.set_flags({"FLAGS_eager_op_cache": False})
    l3, gx3, gw3 = run()           # today's uncached path
    assert l1 == l2
    np.testing.assert_allclose(gx2, gx1, rtol=0, atol=0)
    np.testing.assert_allclose(l2, l3, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(gx2, gx3, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(gw2, gw3, rtol=1e-5, atol=1e-6)


def test_saved_tensor_hooks_fall_back():
    from paddle_tpu.autograd import saved_tensors_hooks
    packed = []

    def pack(t):
        packed.append(t)
        return t

    def unpack(t):
        return t

    x = paddle.to_tensor(np.ones((3, 3), np.float32), stop_gradient=False)
    with saved_tensors_hooks(pack, unpack):
        y = paddle.matmul(x, x)
        loss = y.sum()
    loss.backward()
    # the hooked ops must NOT be cached (their vjp is deferred to
    # backward re-linearization from the packed values)
    assert packed, "pack hook never fired"
    assert x.grad is not None
    assert all(k[0] != "matmul" for k in list(op_cache._T1)), \
        "op executed under saved_tensors_hooks leaked into the cache"


def test_per_call_closure_impls_bypass():
    # dropout's impl is a per-call closure (closes over the drawn RNG
    # key; not the registry fn): it must bypass the cache, and two calls
    # must keep drawing fresh masks
    x = paddle.to_tensor(np.ones((64, 64), np.float32))
    a = paddle.nn.functional.dropout(x, p=0.5, training=True).numpy()
    b = paddle.nn.functional.dropout(x, p=0.5, training=True).numpy()
    assert all(k[0] != "dropout" for k in list(op_cache._T1)), \
        "per-call closure impl must not be cached"
    assert not np.allclose(a, b), "dropout masks must differ per call"


def test_unhashable_static_bypasses():
    # name=<ndarray> rides through the registered relu's **kwargs: the
    # key cannot hash it, so the call must take the uncached path
    x = paddle.to_tensor(np.ones((3,), np.float32) * -1)
    out = paddle.nn.functional.relu(x, name=np.ones(3, np.float32))
    np.testing.assert_allclose(out.numpy(), np.zeros(3))
    st = _t1()
    assert st["bypasses"] >= 1
    assert st["misses"] == 0 and st["entries"] == 0


def test_rng_drawing_op_opts_out():
    from paddle_tpu.core.dispatch import defop
    import jax

    @defop("_test_rng_draw_op")
    def _test_rng_draw_op(x):
        from paddle_tpu.core import state as _state
        key = _state.next_rng_key()
        return x + jax.random.uniform(key, x.shape)

    x = paddle.to_tensor(np.zeros((16,), np.float32))
    a = _test_rng_draw_op(x).numpy()
    b = _test_rng_draw_op(x).numpy()
    st = _t1()
    assert "_test_rng_draw_op" in st["skipped_ops"]
    assert st["entries"] == 0
    assert not np.allclose(a, b), "RNG op must draw fresh keys per call"


def test_int_vs_float_static_do_not_collide():
    x = paddle.to_tensor(np.full((4,), -2.0, np.float32))
    a = paddle.pow(x, 2).numpy()     # int exponent
    b = paddle.pow(x, 2.0).numpy()   # float exponent: distinct key
    np.testing.assert_allclose(a, b, rtol=1e-6)
    st = _t1()
    assert st["misses"] == 2, "2 and 2.0 must not share a cache key"


def test_eager_train_loss_parity_cache_on_off():
    """The bench-style parity gate: identical losses with the cache on
    and off over a multi-step eager training loop."""

    def train(steps=4):
        paddle.seed(7)
        rng = np.random.default_rng(3)
        x = paddle.to_tensor(rng.standard_normal((8, 16))
                             .astype(np.float32))
        y = paddle.to_tensor(rng.standard_normal((8, 4))
                             .astype(np.float32))
        lin = paddle.nn.Linear(16, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        losses = []
        for _ in range(steps):
            loss = ((lin(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        return losses

    on = train()
    paddle.set_flags({"FLAGS_eager_op_cache": False})
    off = train()
    np.testing.assert_allclose(on, off, rtol=1e-5, atol=1e-7)


def test_tier2_persistent_compile_cache_round_trip(tmp_path):
    import jax
    import jax.numpy as jnp

    prev_dir = jax.config.jax_compilation_cache_dir
    d = str(tmp_path / "xla_cache")
    paddle.set_flags({"FLAGS_compile_cache_dir": d})
    try:
        assert op_cache.ensure_compile_cache()
        f = jax.jit(lambda a: (a * 3 + 1).sum())
        f(jnp.ones((32, 32)))
        st = cache_stats()["tier2"]
        assert st["enabled"] and st["dir"] == d
        assert st["entries"] > 0 and st["bytes"] > 0
        # drop the in-memory executable: the recompile must be served
        # from the persistent cache (the cross-process re-run analog)
        jax.clear_caches()
        before = cache_stats()["tier2"]["hits"]
        f2 = jax.jit(lambda a: (a * 3 + 1).sum())
        f2(jnp.ones((32, 32)))
        assert cache_stats()["tier2"]["hits"] > before
    finally:
        paddle.set_flags({"FLAGS_compile_cache_dir": ""})
        op_cache._T2_APPLIED = None
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
        try:     # re-point the live cache object at the restored dir
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            pass
